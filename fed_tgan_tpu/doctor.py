"""Environment triage: ``python -m fed_tgan_tpu.doctor``.

The reference has no failure diagnosis at all — a wedged backend or a
mis-set address surfaces as an RPC timeout after 600 s (reference
Server/dtds/distributed.py:849-857).  This command checks each layer a
training run depends on, bottom-up, and prints one OK/FAIL line per check
so "why does my launch hang" is answered in seconds:

1. interpreter/runtime versions and platform pins;
2. accelerator backend responsiveness (the subprocess probe with timeout —
   a wedged tunnel FAILs here instead of hanging the first real use);
3. the virtual multi-device CPU mesh + a collective (the tests/CI path,
   and proof the SPMD program model works on this host without chips);
4. the native TCP transport (C++ layer) via a localhost loopback;
5. the persistent compile cache location and machine fingerprint;
6. the serving subsystem: a demo artifact trained, served over HTTP on an
   ephemeral port, and byte-compared against the one-shot --sample-from
   path (decode parity).

Exit code 0 when every check passes, 1 otherwise.  Read-only except for
the loopback socket and (if missing) the cache directory.
"""

from __future__ import annotations

import os
import sys
import time


def _line(ok: bool, name: str, detail: str) -> bool:
    print(f"{'OK  ' if ok else 'FAIL'} {name}: {detail}")
    return ok


def check_runtime() -> bool:
    import jax

    pin = os.environ.get("JAX_PLATFORMS", "(unset)")
    return _line(
        True, "runtime",
        f"python {sys.version.split()[0]}, jax {jax.__version__}, "
        f"JAX_PLATFORMS={pin}",
    )


def check_backend(timeout_s: int = 120) -> bool:
    from fed_tgan_tpu.parallel.mesh import (
        backend_initialized,
        cpu_pinned,
        probe_backend_responsive,
    )

    if backend_initialized():
        import jax

        ds = jax.devices()
        return _line(True, "backend",
                     f"already initialized: {len(ds)}x {ds[0].platform}")
    if cpu_pinned():
        # same policy as the CLI: a cpu pin means there is no accelerator
        # to probe (and on site-hooked hosts the probe subprocess may not
        # honor the env pin anyway — the virtual-mesh check below is the
        # real CPU-path verification)
        return _line(True, "backend",
                     "cpu-pinned; accelerator probe skipped (CLI policy)")
    t0 = time.time()
    ok, reason = probe_backend_responsive(timeout_s=timeout_s)
    detail = reason or f"responsive ({time.time() - t0:.1f}s probe)"
    if reason == "cached":
        detail = "responsive (cached probe stamp)"
    return _line(ok, "backend", detail)


def check_virtual_mesh(n: int = 2) -> bool:
    """A subprocess provisions an ``n``-device CPU mesh and runs one psum —
    the exact mechanism of the test suite and the multi-chip dryrun."""
    import subprocess

    code = (
        "from fed_tgan_tpu.parallel.mesh import provision_virtual_cpu, client_mesh, shard_map\n"
        f"provision_virtual_cpu({n})\n"
        "import jax, jax.numpy as jnp\n"
        "from jax.sharding import PartitionSpec as P\n"
        f"mesh = client_mesh({n})\n"
        "out = jax.jit(shard_map(lambda x: jax.lax.psum(x, 'clients'),\n"
        "    mesh=mesh, in_specs=P('clients'), out_specs=P()))(\n"
        f"    jnp.arange({n}, dtype=jnp.float32))\n"
        f"assert float(out[0]) == sum(range({n})), out\n"
        f"print('psum over', {n}, 'devices ok')\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "virtual-mesh", "timed out after 180s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "virtual-mesh", " | ".join(tail) or "failed")
    return _line(True, "virtual-mesh", proc.stdout.strip())


def check_transport() -> bool:
    """Native C++ transport loopback: server + one client exchange an
    object over 127.0.0.1 on an ephemeral port."""
    import threading

    try:
        from fed_tgan_tpu.runtime.transport import (
            ClientTransport,
            ServerTransport,
        )
    except Exception as exc:
        return _line(False, "transport", f"native library unavailable: {exc}")

    port = 26000 + (os.getpid() * 11) % 6000
    result: dict = {}

    def client() -> None:
        try:
            with ClientTransport("127.0.0.1", port, 1, timeout_ms=10_000) as c:
                c.send_obj({"ping": 1})
                result["echo"] = c.recv_obj()
                # sever our own socket, then send again: the transport must
                # reconnect with backoff and resync sequence numbers — the
                # fault-tolerance path a flaky link exercises in production
                c._lib.ft_peer_close(c._handle, 0)
                c.send_obj({"ping": 2})
                result["echo2"] = c.recv_obj()
        except Exception as exc:  # surfaced via the missing echo below
            result["err"] = repr(exc)

    t = threading.Thread(target=client, daemon=True)
    t.start()
    try:
        with ServerTransport(port, 1, timeout_ms=10_000) as server:
            got = server.recv_obj(1)
            server.send_obj(1, got)
            got = server.recv_obj(1)  # arrives over the reconnected socket
            server.send_obj(1, got)
    except Exception as exc:
        return _line(False, "transport", f"{exc!r}")
    t.join(timeout=10)
    if result.get("echo") != {"ping": 1}:
        return _line(False, "transport",
                     result.get("err", "echo mismatch or client timeout"))
    if result.get("echo2") != {"ping": 2}:
        return _line(False, "transport",
                     result.get("err", "reconnect echo mismatch or timeout"))
    return _line(True, "transport",
                 f"C++ loopback roundtrip + sever/reconnect ok (port {port})")


def check_compile_cache() -> bool:
    from fed_tgan_tpu.runtime.compile_cache import _machine_fingerprint

    base = os.path.join(os.path.expanduser("~"), ".cache", "fed_tgan_tpu",
                        "xla_cache")
    fp = _machine_fingerprint()
    sub = os.path.join(base, fp)
    n = len(os.listdir(sub)) if os.path.isdir(sub) else 0
    return _line(True, "compile-cache",
                 f"{sub} ({n} entries, machine fingerprint {fp})")


def check_static_analysis() -> bool:
    """The jaxlint gate: AST rules J01-J06 over the package, diffed
    against the checked-in baseline.  Pure stdlib -- no JAX tracing."""
    try:
        from fed_tgan_tpu.analysis.lint import (
            apply_baseline,
            load_baseline,
            run_lint,
        )

        findings = run_lint()
        new, old, stale = apply_baseline(findings, load_baseline())
    except Exception as exc:
        return _line(False, "static-analysis", f"{exc!r}")
    if new:
        heads = ", ".join(f.key for f in new[:3])
        more = f" (+{len(new) - 3} more)" if len(new) > 3 else ""
        return _line(False, "static-analysis",
                     f"{len(new)} non-baselined finding(s): {heads}{more} "
                     "-- run python -m fed_tgan_tpu.analysis")
    return _line(True, "static-analysis",
                 f"jaxlint clean: {len(findings)} finding(s) all baselined"
                 f" ({len(stale)} stale baseline entr"
                 f"{'y' if len(stale) == 1 else 'ies'}, "
                 "rules J01-J06 + L01-L04)")


def check_analysis_all(timeout: int = 600) -> bool:
    """The unified analysis gate: shells ``python -m
    fed_tgan_tpu.analysis --all`` (jaxlint+locklint, obslint telemetry
    contracts, hlolint program contracts) and requires the aggregated
    exit code to be 0."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "fed_tgan_tpu.analysis", "--all"],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "analysis-all", f"timed out ({timeout}s)")
    summary = [ln.strip() for ln in proc.stdout.splitlines()
               if ln.strip() and not ln.startswith("analysis --all")]
    if proc.returncode != 0:
        bad = [ln for ln in summary if "ok" not in ln.split()[-1:]]
        return _line(False, "analysis-all",
                     f"exit {proc.returncode}: "
                     + ("; ".join(bad[:3]) or "see python -m "
                        "fed_tgan_tpu.analysis --all"))
    prongs = [ln for ln in summary
              if ln.endswith("ok") and not ln.startswith(("jaxlint:",
                                                          "obslint:"))]
    return _line(True, "analysis-all",
                 f"{len(prongs)} prong(s) clean: "
                 + ", ".join(p.split()[0] for p in prongs))


def check_locklint(timeout: int = 300) -> bool:
    """Both prongs of the concurrency subsystem, end to end.

    Static: a subprocess runs the interprocedural lockset rules
    L01-L04 over the package and must report zero non-baseline
    findings.  Dynamic: a 2-tenant in-process fleet takes a burst of
    concurrent requests with the lockwatch sanitizer armed in record
    mode, and the lock-order graph it builds must close no cycle (and
    no thread may re-enter a non-reentrant lock)."""
    import shutil
    import subprocess
    import tempfile
    import threading
    import urllib.request

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "fed_tgan_tpu.analysis",
             "--rules", "L01,L02,L03,L04"],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "locklint", f"analyzer timed out ({timeout}s)")
    if proc.returncode != 0:
        tail = (proc.stdout or proc.stderr or "").strip().splitlines()
        return _line(False, "locklint",
                     f"static findings: {' | '.join(tail[:2])} -- run "
                     "python -m fed_tgan_tpu.analysis --rules L01-L04")

    tmp = tempfile.mkdtemp(prefix="fed_tgan_doctor_locklint_")
    svc = None
    try:
        from fed_tgan_tpu.analysis import lockwatch
        from fed_tgan_tpu.serve.demo import build_demo_artifact
        from fed_tgan_tpu.serve.fleet import (
            FleetRegistry,
            FleetService,
            ProgramCache,
        )

        with lockwatch.watch(on_deadlock="record"):
            fleet = FleetRegistry(program_cache=ProgramCache(max_entries=8),
                                  log=lambda *a: None)
            for name in ("alpha", "beta"):
                root = os.path.join(tmp, name)
                build_demo_artifact(root, rows=200, epochs=1)
                fleet.load(name, root)
            svc = FleetService(fleet, port=0, reload_interval_s=0,
                               log=lambda *a: None).start()

            def burst(tenant):
                url = f"{svc.url}/t/{tenant}/sample?rows=10&seed=1"
                for _ in range(3):
                    with urllib.request.urlopen(url, timeout=120) as r:
                        r.read()

            threads = [threading.Thread(target=burst, args=(t,))
                       for t in ("alpha", "beta") for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            svc.shutdown(drain=True)
            svc = None
            bad = (lockwatch.reports("cycle")
                   + lockwatch.reports("reentry"))
            summary = lockwatch.summary()
        if bad:
            return _line(False, "locklint",
                         f"{len(bad)} runtime report(s): {bad[0].detail}")
        acq = sum(s["acquisitions"] for s in summary.values())
        return _line(True, "locklint",
                     "L01-L04 clean repo-wide; lockwatch-armed 2-tenant "
                     f"burst: {len(summary)} lock(s) watched, {acq} "
                     "acquisition(s), no order cycles, no re-entry")
    except Exception as exc:
        return _line(False, "locklint", f"{exc!r}")
    finally:
        if svc is not None:
            try:
                svc.shutdown(drain=False)
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def check_program_contracts(timeout: int = 300) -> bool:
    """The hlolint gate: a subprocess lowers every contracted entrypoint
    on an 8-virtual-device CPU mesh and diffs the StableHLO fingerprints
    against the checked-in contracts (collectives, transfer surface,
    dtype census).  Subprocess because lowering must own backend
    initialization, exactly like :func:`check_virtual_mesh`."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "fed_tgan_tpu.analysis", "--contracts"],
            capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "program-contracts",
                     f"timed out after {timeout}s")
    tail = (proc.stdout or proc.stderr or "").strip().splitlines()
    summary = tail[-1] if tail else "no output"
    if proc.returncode == 2:
        return _line(False, "program-contracts",
                     f"lowering unavailable: {summary}")
    if proc.returncode != 0:
        heads = " | ".join(tail[:2])
        return _line(False, "program-contracts",
                     f"{heads} -- run python -m fed_tgan_tpu.analysis "
                     "--contracts --explain")
    return _line(True, "program-contracts", summary)


def check_precision(timeout: int = 300) -> bool:
    """The mixed-precision path lowers with the contracted dtype census.

    A subprocess (lowering must own backend init, like the contract gate)
    lowers the bf16 fused federated epoch next to its f32 twin and asserts
    the three facts the bf16 mode is sold on: bf16 tensors actually appear,
    the f32 islands (GP norm, loss reductions, BN stats, master params) are
    still present, and the aggregation collectives move at most 0.6x the
    f32 payload bytes.  Catches a silently-degraded policy (e.g. a cast
    refactor that turns the whole program back to f32, or one that casts
    the islands away) before a training run does."""
    import json
    import subprocess

    code = (
        "import json\n"
        "from fed_tgan_tpu.analysis.contracts.harness import (\n"
        "    ENTRYPOINT_FAMILIES, require_mesh)\n"
        "from fed_tgan_tpu.analysis.contracts.ir import (\n"
        "    fingerprint_text, total_collective_bytes)\n"
        "require_mesh()\n"
        "fams = ENTRYPOINT_FAMILIES['train_federated']\n"
        "out = {}\n"
        "for name in ('fused_epoch[weighted]', 'fused_epoch[weighted@bf16]'):\n"
        "    low = fams[name]()\n"
        "    fp = fingerprint_text(low if isinstance(low, str)\n"
        "                          else low.as_text())\n"
        "    out[name] = {'bf16': fp.dtypes.get('bf16', 0),\n"
        "                 'f32': fp.dtypes.get('f32', 0),\n"
        "                 'cbytes': total_collective_bytes(fp)}\n"
        "print(json.dumps(out))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "precision", f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "precision", " | ".join(tail) or "lowering failed")
    try:
        census = json.loads(proc.stdout.strip().splitlines()[-1])
        f32p = census["fused_epoch[weighted]"]
        bf16p = census["fused_epoch[weighted@bf16]"]
    except Exception as exc:
        return _line(False, "precision", f"unparseable census: {exc!r}")
    if bf16p["bf16"] <= 0:
        return _line(False, "precision",
                     "bf16 epoch lowered with NO bf16 tensors — the "
                     "precision policy is not being applied")
    if bf16p["f32"] <= 0:
        return _line(False, "precision",
                     "bf16 epoch lost its f32 islands (GP norm / loss "
                     "reductions / BN stats / master params)")
    if not bf16p["cbytes"] <= 0.6 * f32p["cbytes"]:
        return _line(False, "precision",
                     f"bf16 collectives move {bf16p['cbytes']}B vs f32 "
                     f"{f32p['cbytes']}B — payload compression lost")
    return _line(True, "precision",
                 f"bf16 epoch: {bf16p['bf16']} bf16 + {bf16p['f32']} f32 "
                 f"tensor sites, collective payload {bf16p['cbytes']}B "
                 f"vs f32 {f32p['cbytes']}B "
                 f"({bf16p['cbytes'] / max(1, f32p['cbytes']):.2f}x)")


def check_scan_rounds(timeout: int = 300) -> bool:
    """Scan-over-rounds fusion holds its two load-bearing properties.

    A subprocess (lowering must own backend init, like the contract gate)
    lowers ``fused_rounds[4]`` next to ``fused_rounds[1]`` and asserts the
    contract require block's invariant directly: IR collective bytes are
    EQUAL (collectives inside the round scan lower once, so logical
    traffic scales exactly K× — growth means the scan unrolled, any other
    delta means the payload re-widened).  It then runs the rounds=2
    program against two sequential rounds=1 dispatches on the harness's
    synthetic stacks and asserts the resulting params are bit-identical —
    the ``--rounds-per-program`` K=1 parity the trainer's fused chunks
    depend on."""
    import json
    import subprocess

    code = (
        "import json\n"
        "import numpy as np\n"
        "import jax\n"
        "from fed_tgan_tpu.analysis.contracts.harness import (\n"
        "    ENTRYPOINT_FAMILIES, N_DEVICES, require_mesh,\n"
        "    _client_stacks, _stacked_models, _toy_cfg, _toy_spec)\n"
        "from fed_tgan_tpu.analysis.contracts.ir import (\n"
        "    fingerprint_text, total_collective_bytes)\n"
        "require_mesh()\n"
        "fams = ENTRYPOINT_FAMILIES['fused_rounds']\n"
        "out = {}\n"
        "for name in ('fused_rounds[1]', 'fused_rounds[4]'):\n"
        "    fp = fingerprint_text(fams[name]().as_text())\n"
        "    out[name] = total_collective_bytes(fp)\n"
        "from fed_tgan_tpu.parallel.mesh import client_mesh\n"
        "from fed_tgan_tpu.train.federated import make_federated_epoch\n"
        "spec, cfg = _toy_spec(), _toy_cfg()\n"
        "mesh = client_mesh(N_DEVICES)\n"
        "data, cond, rows, steps, weights = _client_stacks(spec, cfg)\n"
        "_one, models = _stacked_models(spec, cfg)\n"
        "mk = lambda r: make_federated_epoch(\n"
        "    spec, cfg, max_steps=int(steps.max()), mesh=mesh, k=1,\n"
        "    rounds=r)\n"
        "key = jax.random.key(0)\n"
        "m_f, _m, _k, _fin = mk(2)(models, data, cond, rows, steps,\n"
        "                          weights, key)\n"
        "f1 = mk(1)\n"
        "m_s, _m, k1, _fin = f1(models, data, cond, rows, steps,\n"
        "                       weights, key)\n"
        "m_s, _m, _k2, _fin = f1(m_s, data, cond, rows, steps,\n"
        "                        weights, k1)\n"
        "out['parity'] = bool(all(\n"
        "    np.array_equal(np.asarray(a), np.asarray(b))\n"
        "    for a, b in zip(jax.tree.leaves(m_f), jax.tree.leaves(m_s))))\n"
        "print(json.dumps(out))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "scan-rounds", f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "scan-rounds",
                     " | ".join(tail) or "lowering failed")
    try:
        res = json.loads(proc.stdout.strip().splitlines()[-1])
        b1, b4 = res["fused_rounds[1]"], res["fused_rounds[4]"]
    except Exception as exc:
        return _line(False, "scan-rounds", f"unparseable result: {exc!r}")
    if b4 != b1:
        hint = ("round scan unrolled?" if b4 >= 4 * b1
                else "per-round payload re-widened?")
        return _line(False, "scan-rounds",
                     f"fused_rounds[4] collectives move {b4}B vs "
                     f"fused_rounds[1] {b1}B — must be EQUAL ({hint})")
    if not res.get("parity"):
        return _line(False, "scan-rounds",
                     "rounds=2 program is NOT bit-identical to two "
                     "sequential rounds=1 dispatches")
    return _line(True, "scan-rounds",
                 f"fused_rounds[4] == fused_rounds[1] collective bytes "
                 f"({b1}B -> logical 4x scaling); rounds=2 bit-identical "
                 "to 2 sequential dispatches")


def check_onboarding(timeout: int = 300) -> bool:
    """Cohort-batched onboarding holds its three load-bearing properties.

    A subprocess (init owns backend bring-up) runs a small population
    through ``federated_initialize`` and asserts:

    - **batched-fit parity**: the cohort-batched fit (``batch_fit=True``,
      one shape-bucketed dispatch for the whole population) produces
      bit-identical client matrices to the per-client dispatch path —
      vmap semantics, same pow2-padded program;
    - **cache round-trip**: a warm re-run against the same ``InitCache``
      directory restores bit-identical matrices and weights (content-
      hashed entries; a hit IS the same computation);
    - **schema invalidation**: mutating a shard's data or schema changes
      its content fingerprint, so the stale entry can never be looked up
      — invalidation by construction, no TTLs to misconfigure."""
    import json
    import subprocess

    code = (
        "import json, tempfile\n"
        "import numpy as np\n"
        "import pandas as pd\n"
        "from fed_tgan_tpu.data.ingest import TablePreprocessor\n"
        "from fed_tgan_tpu.federation.init import federated_initialize\n"
        "from fed_tgan_tpu.federation.init_cache import (\n"
        "    InitCache, shard_fingerprint)\n"
        "def mk(seed, shift=0.0):\n"
        "    r = np.random.default_rng(seed)\n"
        "    return TablePreprocessor(frame=pd.DataFrame({\n"
        "        'a': r.normal(size=96) + shift,\n"
        "        'b': r.normal(2.0, 0.5, size=96),\n"
        "        'c': r.choice(['x', 'y', 'z'], size=96)}),\n"
        "        name='DoctorOnboard', categorical_columns=['c'])\n"
        "clients = [mk(i) for i in range(6)]\n"
        "seq = federated_initialize(clients, seed=0, backend='jax',\n"
        "                           batch_fit=False)\n"
        "bat = federated_initialize(clients, seed=0, backend='jax',\n"
        "                           batch_fit=True)\n"
        "out = {}\n"
        "out['batched_parity'] = bool(\n"
        "    all(np.array_equal(a, b) for a, b in\n"
        "        zip(seq.client_matrices, bat.client_matrices))\n"
        "    and np.allclose(seq.weights, bat.weights, atol=1e-9))\n"
        "with tempfile.TemporaryDirectory() as d:\n"
        "    cache = InitCache(d)\n"
        "    cold = federated_initialize(clients, seed=0, backend='jax',\n"
        "                                cache=cache)\n"
        "    warm = federated_initialize(clients, seed=0, backend='jax',\n"
        "                                cache=cache)\n"
        "    out['warm_bit_identical'] = bool(\n"
        "        all(np.array_equal(a, b) for a, b in\n"
        "            zip(cold.client_matrices, warm.client_matrices))\n"
        "        and np.array_equal(cold.weights, warm.weights))\n"
        "    fp = lambda c: shard_fingerprint(c, n_components=10,\n"
        "                                     backend='jax', seed=0)\n"
        "    fp0 = fp(clients[0])\n"
        "    fp_data = fp(mk(0, shift=1.0))\n"
        "    alt = TablePreprocessor(frame=clients[0].frame,\n"
        "        name='DoctorOnboard', categorical_columns=[])\n"
        "    out['schema_invalidation'] = bool(\n"
        "        fp_data != fp0 and fp(alt) != fp0\n"
        "        and cache.load_client(fp0) is not None\n"
        "        and cache.load_client(fp_data) is None)\n"
        "print(json.dumps(out))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "onboarding", f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "onboarding",
                     " | ".join(tail) or "onboarding run failed")
    try:
        res = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:
        return _line(False, "onboarding", f"unparseable result: {exc!r}")
    if not res.get("batched_parity"):
        return _line(False, "onboarding",
                     "cohort-batched fit is NOT bit-identical to the "
                     "per-client dispatch path")
    if not res.get("warm_bit_identical"):
        return _line(False, "onboarding",
                     "warm cache restore is NOT bit-identical to the "
                     "cold fit (stale or lossy cache entries)")
    if not res.get("schema_invalidation"):
        return _line(False, "onboarding",
                     "shard fingerprint did not move under a data/schema "
                     "change — stale cache entries would be served")
    return _line(True, "onboarding",
                 "batched fit bit-identical to per-client path; warm "
                 "cache restore bit-identical; data/schema changes "
                 "invalidate by fingerprint")


def check_cohort_scale(timeout: int = 300) -> bool:
    """Cohort-sampled partial participation holds its two load-bearing
    properties.

    A subprocess (lowering must own backend init, like the contract gate)
    lowers ``cohort_rounds[n16]`` next to ``cohort_rounds[n64]`` — the
    same cohort C over a 4x larger resident population — and asserts the
    contract require block's invariant directly: IR collective bytes are
    EQUAL (the round payload is O(cohort) + O(model); growth with N means
    something collected over the population axis).  It then lowers the
    C=N configuration next to the cohort=0 legacy program and asserts the
    StableHLO text is byte-identical — full participation must remain the
    exact pre-cohort program, which is what makes ``--cohort`` safe to
    default off."""
    import json
    import subprocess

    code = (
        "import json\n"
        "import jax\n"
        "from fed_tgan_tpu.analysis.contracts.harness import (\n"
        "    ENTRYPOINT_FAMILIES, N_DEVICES, require_mesh,\n"
        "    _client_stacks, _stacked_models, _toy_cfg, _toy_spec)\n"
        "from fed_tgan_tpu.analysis.contracts.ir import (\n"
        "    fingerprint_text, total_collective_bytes)\n"
        "require_mesh()\n"
        "fams = ENTRYPOINT_FAMILIES['cohort_rounds']\n"
        "out = {}\n"
        "for name in ('cohort_rounds[n16]', 'cohort_rounds[n64]'):\n"
        "    fp = fingerprint_text(fams[name]().as_text())\n"
        "    out[name] = total_collective_bytes(fp)\n"
        "from fed_tgan_tpu.parallel.mesh import client_mesh\n"
        "from fed_tgan_tpu.train.federated import make_federated_epoch\n"
        "spec = _toy_spec()\n"
        "mesh = client_mesh(N_DEVICES)\n"
        "texts = []\n"
        "for cohort in (0, 2 * N_DEVICES):\n"
        "    cfg = _toy_cfg(cohort=cohort)\n"
        "    data, cond, rows, steps, weights = _client_stacks(\n"
        "        spec, cfg, 2 * N_DEVICES)\n"
        "    _one, models = _stacked_models(spec, cfg, 2 * N_DEVICES)\n"
        "    fn = make_federated_epoch(spec, cfg,\n"
        "        max_steps=int(steps.max()), mesh=mesh, k=2, rounds=2)\n"
        "    texts.append(fn.lower(models, data, cond, rows, steps,\n"
        "                          weights, jax.random.key(0)).as_text())\n"
        "out['full_participation_identical'] = texts[0] == texts[1]\n"
        "print(json.dumps(out))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "cohort-scale", f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "cohort-scale",
                     " | ".join(tail) or "lowering failed")
    try:
        res = json.loads(proc.stdout.strip().splitlines()[-1])
        b16, b64 = res["cohort_rounds[n16]"], res["cohort_rounds[n64]"]
    except Exception as exc:
        return _line(False, "cohort-scale", f"unparseable result: {exc!r}")
    if b64 != b16:
        return _line(False, "cohort-scale",
                     f"cohort_rounds[n64] collectives move {b64}B vs "
                     f"cohort_rounds[n16] {b16}B — must be EQUAL "
                     "(collected over the population axis?)")
    if not res.get("full_participation_identical"):
        return _line(False, "cohort-scale",
                     "cohort=N program is NOT byte-identical to the "
                     "cohort=0 legacy program — full participation drifted")
    return _line(True, "cohort-scale",
                 f"collective bytes N-independent ({b16}B at N=16 and "
                 "N=64, cohort 8); cohort=N lowers byte-identical to the "
                 "legacy full-participation program")


def check_robust_aggregation() -> bool:
    """Each robust aggregator rejects a poisoned client on a tiny pytree.

    4 clients with near-identical updates, one shipping NaNs and (second
    scenario) one shipping a 1000x-scaled delta: the gated aggregate must
    stay finite and land near the clean clients' mean.  Host-side variants
    — the same math as the in-graph path, no device needed."""
    import numpy as np

    from fed_tgan_tpu.parallel.fedavg import host_robust_aggregate

    prev = {"w": np.zeros((3, 2), np.float32), "b": np.zeros(3, np.float32)}
    rng = np.random.default_rng(0)
    clean = [
        {"w": prev["w"] + 0.1 + 0.01 * rng.standard_normal((3, 2)).astype(np.float32),
         "b": prev["b"] - 0.1 + 0.01 * rng.standard_normal(3).astype(np.float32)}
        for _ in range(4)
    ]
    weights = np.full(4, 0.25)
    poisons = {
        "nan": {k: np.full_like(v, np.nan) for k, v in clean[3].items()},
        "scale": {k: prev[k] + 1000.0 * (v - prev[k])
                  for k, v in clean[3].items()},
    }
    clean_mean = {
        k: np.mean([c[k] for c in clean[:3]], axis=0) for k in prev
    }
    try:
        for pname, poison in poisons.items():
            trees = clean[:3] + [poison]
            for agg in ("weighted", "clipped", "trimmed", "median"):
                out, quar = host_robust_aggregate(
                    prev, trees, weights, aggregator=agg)
                if not quar[3] or quar[:3].any():
                    return _line(False, "robust-agg",
                                 f"{agg}/{pname}: gate flagged {quar} "
                                 "(expected only client 3)")
                for k in prev:
                    if not np.isfinite(out[k]).all():
                        return _line(False, "robust-agg",
                                     f"{agg}/{pname}: non-finite {k}")
                    if np.abs(out[k] - clean_mean[k]).max() > 0.05:
                        return _line(False, "robust-agg",
                                     f"{agg}/{pname}: {k} strayed "
                                     f"{np.abs(out[k] - clean_mean[k]).max():.3f} "
                                     "from the clean mean")
    except Exception as exc:
        return _line(False, "robust-agg", f"{exc!r}")
    return _line(True, "robust-agg",
                 "weighted/clipped/trimmed/median all quarantined the "
                 "poisoned client (nan + 1000x-scale) and stayed on the "
                 "clean mean")


def check_serving() -> bool:
    """The serving subsystem round-trips the demo table with decode parity.

    Builds a tiny --save-model artifact, serves it on an ephemeral port,
    fetches rows over HTTP, and verifies the response bytes are identical
    to what the one-shot ``--sample-from`` path (a FRESH engine, so this
    also proves the compiled path is seed-deterministic across engines)
    writes for the same (rows, seed)."""
    import json
    import shutil
    import tempfile
    import urllib.request

    tmp = tempfile.mkdtemp(prefix="fed_tgan_doctor_serve_")
    svc = None
    try:
        from types import SimpleNamespace

        from fed_tgan_tpu import cli
        from fed_tgan_tpu.serve.demo import build_demo_artifact
        from fed_tgan_tpu.serve.registry import ModelRegistry
        from fed_tgan_tpu.serve.service import SamplingService

        build_demo_artifact(tmp, rows=200, epochs=1)
        svc = SamplingService(ModelRegistry(tmp, log=lambda *a: None),
                              port=0, log=lambda *a: None).start()
        with urllib.request.urlopen(f"{svc.url}/healthz", timeout=30) as r:
            health = json.loads(r.read())
        if health.get("status") != "ok":
            return _line(False, "serving", f"healthz said {health}")
        with urllib.request.urlopen(
                f"{svc.url}/sample?rows=40&seed=7", timeout=120) as r:
            served = r.read()
        out_dir = os.path.join(tmp, "oneshot")
        rc = cli._run_sample_from(SimpleNamespace(
            sample_from=tmp, sample_rows=40, seed=7, out_dir=out_dir,
            quiet=True, allow_meta_mismatch=False))
        if rc != 0:
            return _line(False, "serving", f"--sample-from path rc={rc}")
        with open(os.path.join(out_dir, "demo_synthesis_sampled.csv"),
                  "rb") as f:
            oneshot = f.read()
        if served != oneshot:
            return _line(False, "serving",
                         "served bytes differ from the one-shot "
                         f"--sample-from CSV ({len(served)} vs "
                         f"{len(oneshot)} bytes)")
        return _line(True, "serving",
                     f"model {health['model_id']} served 40 rows on "
                     f"{svc.url}; response byte-identical to the one-shot "
                     "--sample-from path")
    except Exception as exc:
        return _line(False, "serving", f"{exc!r}")
    finally:
        if svc is not None:
            try:
                svc.shutdown(drain=False)
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def check_serving_fleet() -> bool:
    """The multi-tenant fleet shares compiled programs and keeps parity.

    Loads TWO tenants from identically-built demo artifacts into one
    fleet (shared program LRU), asserts the second tenant's draw is a
    cache HIT (cross-tenant program sharing — equal layouts resolve to
    one compiled program), then serves both over HTTP and verifies each
    tenant's bytes are identical to a fresh single-model engine's for
    the same (rows, seed) — the per-tenant decode-parity criterion."""
    import json
    import shutil
    import tempfile
    import threading
    import urllib.request

    tmp = tempfile.mkdtemp(prefix="fed_tgan_doctor_fleet_")
    svc = None
    try:
        from fed_tgan_tpu.serve.demo import build_demo_artifact
        from fed_tgan_tpu.serve.engine import SamplingEngine
        from fed_tgan_tpu.serve.fleet import (
            FleetRegistry,
            FleetService,
            ProgramCache,
        )
        from fed_tgan_tpu.serve.registry import ModelRegistry

        roots = {}
        for name in ("alpha", "beta"):
            root = os.path.join(tmp, name)
            build_demo_artifact(root, rows=200, epochs=1)
            roots[name] = root
        cache = ProgramCache(max_entries=16)
        fleet = FleetRegistry(program_cache=cache, log=lambda *a: None)
        for name, root in roots.items():
            fleet.load(name, root)
        # cross-tenant sharing: alpha's draw builds the bucket program
        # (miss), beta's identical-layout draw must reuse it (hit)
        a = fleet.get("alpha").engine.sample_csv_bytes(25, seed=3)
        misses_after_a = cache.stats()["misses"]
        b = fleet.get("beta").engine.sample_csv_bytes(25, seed=3)
        st = cache.stats()
        if st["misses"] != misses_after_a or st["hits"] < 1:
            return _line(False, "serving-fleet",
                         f"no cross-tenant program reuse: {st}")
        if a != b:
            return _line(False, "serving-fleet",
                         "identically-built tenants disagree byte-wise "
                         "through the shared program")
        svc = FleetService(fleet, port=0, reload_interval_s=0,
                           log=lambda *a: None).start()
        results: dict = {}

        def fetch(tenant):
            url = f"{svc.url}/t/{tenant}/sample?rows=25&seed=3"
            with urllib.request.urlopen(url, timeout=120) as r:
                results[tenant] = r.read()

        threads = [threading.Thread(target=fetch, args=(t,)) for t in roots]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        single = SamplingEngine(
            ModelRegistry(roots["alpha"], log=lambda *a: None).get())
        want = single.sample_csv_bytes(25, seed=3)
        for tenant in roots:
            if results.get(tenant) != want:
                return _line(False, "serving-fleet",
                             f"tenant {tenant!r} bytes differ from the "
                             "single-model engine path")
        with urllib.request.urlopen(f"{svc.url}/fleet", timeout=30) as r:
            status = json.loads(r.read())
        return _line(True, "serving-fleet",
                     f"{len(status['tenants'])} tenants shared "
                     f"{st['entries']} compiled program(s) "
                     f"({st['hits']} hit(s), {st['misses']} miss(es)); "
                     "per-tenant bytes identical to the single-model "
                     "engine")
    except Exception as exc:
        return _line(False, "serving-fleet", f"{exc!r}")
    finally:
        if svc is not None:
            try:
                svc.shutdown(drain=False)
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def check_front_door() -> bool:
    """The multi-worker front door preserves bytes and fills batches.

    Runs the two load-bearing claims of the N-worker serving pipeline
    deterministically, in process and off the network: (1) a 4-worker
    fleet serving a pre-enqueued backlog returns byte-for-byte what a
    single-worker fleet returns for the same 32 requests — the
    multi-worker refactor changed scheduling, never content; (2) that
    same backlog coalesces into full batches, batch_occupancy >= 4
    (the occupancy-driven-admission criterion; BENCH_r09's starved
    single-worker coalescer sat at 1.02)."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="fed_tgan_doctor_frontdoor_")
    try:
        from fed_tgan_tpu.serve.demo import build_demo_artifact
        from fed_tgan_tpu.serve.fleet import (
            FleetRegistry,
            FleetService,
            ProgramCache,
            _FleetRequest,
        )

        root = os.path.join(tmp, "alpha")
        build_demo_artifact(root, rows=200, epochs=1)

        def run(workers: int):
            fleet = FleetRegistry(program_cache=ProgramCache(max_entries=16),
                                  log=lambda *a: None)
            fleet.load("alpha", root)
            svc = FleetService(fleet, port=0, max_batch=8, queue_size=64,
                               max_lanes=4, reload_interval_s=0,
                               workers=workers, log=lambda *a: None)
            reqs = [_FleetRequest(tenant="alpha", n=5, seed=2, offset=5 * i,
                                  condition=None, header=True)
                    for i in range(32)]
            for r in reqs:
                err = svc.submit(fleet.get("alpha"), r)
                if err is not None:
                    raise RuntimeError(f"submit shed a request: {err}")
            svc.start_workers()
            for r in reqs:
                if not r.done.wait(timeout=300) or r.status != 200:
                    raise RuntimeError(
                        f"request failed: status={r.status} err={r.error}")
            svc.shutdown(drain=True)
            return [r.result for r in reqs], svc.metrics.snapshot()

        multi, snap = run(4)
        single, _ = run(1)
        if multi != single:
            return _line(False, "front-door",
                         "4-worker bytes differ from the single-worker "
                         "path for the same requests")
        if snap["batch_occupancy"] < 4.0:
            return _line(False, "front-door",
                         "coalescer starved under backlog: occupancy "
                         f"{snap['batch_occupancy']} < 4")
        return _line(True, "front-door",
                     "4-worker bytes == single-worker bytes for 32 "
                     "requests; batch_occupancy "
                     f"{snap['batch_occupancy']} >= 4")
    except Exception as exc:
        return _line(False, "front-door", f"{exc!r}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_quality_canary() -> bool:
    """The canary promotion gate promotes clean bytes and rejects damage.

    Builds the demo artifact, republishes a clean generation and verifies
    the gate promotes it; then degrades the published checkpoint in place
    (structurally valid, quality-destroyed — exactly the failure the
    immediate reload path waves through) and verifies the gate rejects it
    with per-column forensics while the promoted model keeps serving.
    Clean-first ordering matters: ``republish_demo_candidate`` derives
    its generation from the published bytes, so degrading first would
    poison the "clean" republish too."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="fed_tgan_doctor_canary_")
    try:
        from fed_tgan_tpu.serve.canary import CanaryConfig, CanaryGate
        from fed_tgan_tpu.serve.demo import (
            build_demo_artifact,
            republish_demo_candidate,
        )
        from fed_tgan_tpu.serve.engine import SamplingEngine
        from fed_tgan_tpu.serve.registry import ModelRegistry
        from fed_tgan_tpu.testing.faults import degrade_checkpoint

        build_demo_artifact(tmp, rows=200, epochs=1)
        registry = ModelRegistry(tmp, log=lambda *a: None)
        engine = SamplingEngine(registry.get())
        gate = CanaryGate(registry, engine,
                          config=CanaryConfig(shadow_rows=128),
                          log=lambda *a: None)
        first_id = registry.get().model_id

        republish_demo_candidate(tmp)
        clean = gate.consider()
        if clean is None or not clean["promoted"]:
            return _line(False, "quality-canary",
                         f"clean republish not promoted ({clean})")
        if registry.get().model_id == first_id:
            return _line(False, "quality-canary",
                         "promotion did not install the new generation")
        engine.adopt(registry.get())
        promoted_id = registry.get().model_id

        degrade_checkpoint(os.path.join(tmp, "models", "synthesizer"),
                           100.0)
        decision = gate.consider()
        if decision is None or decision["promoted"]:
            return _line(False, "quality-canary",
                         f"degraded checkpoint not rejected ({decision})")
        if registry.get().model_id != promoted_id:
            return _line(False, "quality-canary",
                         "rejected candidate replaced the serving model")
        if not decision["tripped"] or not decision["per_column"]:
            return _line(False, "quality-canary",
                         "rejection carried no forensics "
                         f"({decision['tripped']})")
        return _line(True, "quality-canary",
                     f"clean generation promoted to {promoted_id}; "
                     f"degraded generation rejected (tripped "
                     f"{decision['tripped']}, {promoted_id} kept serving)")
    except Exception as exc:
        return _line(False, "quality-canary", f"{exc!r}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def wait_healthy(timeout_min: float = 0.0, quiet_min: float = 45.0,
                 probe_timeout_s: int = 120,
                 _probe=None, _load=None, _sleep=None, _log=print) -> bool:
    """Block until the accelerator backend answers a probe.

    Returns True the moment a probe succeeds, False when ``timeout_min``
    (0 = wait forever) elapses first.  Encodes the observed wedge model of
    the tunneled backend (PARITY.md): a probe killed mid-handshake (e.g.
    slow only because the host is loaded) can wedge the tunnel, and a
    wedged tunnel heals only after a sustained quiet period with no
    connection attempts.  So this waiter never probes while the host is
    busy — 1-min load average >= max(1, 0.75 x CPU count), i.e. most cores
    occupied (defer 2 min instead) — and after a failed probe it holds a
    ``quiet_min``-minute quiet window rather than hammering the backend:
    probing more often can keep the wedge alive.

    ``_probe``/``_load``/``_sleep``/``_log`` are test seams.
    """
    import time as _time

    from fed_tgan_tpu.parallel.mesh import probe_backend_responsive

    # ignore_cache: a stamp from before a fresh wedge must not let the
    # waiter vouch for a backend it never contacted
    probe = _probe or (
        lambda: probe_backend_responsive(timeout_s=probe_timeout_s,
                                         ignore_cache=True))
    load = _load or (lambda: os.getloadavg()[0])
    sleep = _sleep or _time.sleep
    # one busy CPU on a many-core host is idle for probing purposes
    busy_at = max(1.0, 0.75 * (os.cpu_count() or 1))
    deadline = (_time.monotonic() + timeout_min * 60.0) if timeout_min > 0 \
        else None

    def pause(seconds: float) -> bool:
        """Sleep, capped to the remaining deadline; False = deadline hit."""
        if deadline is not None:
            seconds = min(seconds, deadline - _time.monotonic())
            if seconds <= 0:
                return False
        sleep(seconds)
        return deadline is None or _time.monotonic() < deadline

    while True:
        cur = load()
        if cur >= busy_at:
            _log(f"doctor: host busy (load {cur:.2f} >= {busy_at:.2f}); "
                 "deferring probe 2 min")
            if not pause(120):
                break
            continue
        ok, reason = probe()
        if ok:
            _log("doctor: accelerator backend healthy")
            return True
        _log(f"doctor: probe failed ({reason}); "
             f"quiet window {quiet_min:.1f} min")
        if not pause(quiet_min * 60.0):
            break
    _log("doctor: wait-healthy timed out")
    return False


def check_observability() -> bool:
    """The obs layer is importable pre-jax, durable, and self-describing.

    Three properties, each in the cheapest form that still proves it:
    importing ``fed_tgan_tpu.obs`` in a fresh interpreter must not drag in
    jax (the registry/journal are crash-path tools — they have to work
    when jax itself is the thing that is broken); the JSONL journal must
    round-trip an event through the real file path; and the ``obs report``
    CLI must summarize a synthetic journal from a fresh process."""
    import json
    import shutil
    import subprocess
    import tempfile

    tmp = tempfile.mkdtemp(prefix="fed_tgan_doctor_obs_")
    try:
        # 1. pre-jax import.  Compare the sys.modules DELTA instead of
        # asserting absence: on site-hooked hosts jax is already imported
        # at interpreter startup, and that must not fail this check.
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; had = 'jax' in sys.modules; "
             "import fed_tgan_tpu.obs; "
             "assert ('jax' in sys.modules) == had, 'obs import pulled jax'; "
             "print('ok')"],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 0 or "ok" not in proc.stdout:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            return _line(False, "observability",
                         "obs import check failed: "
                         + (" | ".join(tail) or f"rc={proc.returncode}"))

        # 2. journal round-trip through the real append/flush path.
        from fed_tgan_tpu.obs.journal import RunJournal, read_journal

        jpath = os.path.join(tmp, "journal.jsonl")
        with RunJournal(jpath, run_id="doctor", validate=True) as j:
            j.emit("round", first=0, last=0, rounds=1, per_round_s=0.01)
        if j.schema_violations:
            return _line(False, "observability",
                         f"{j.schema_violations} journal schema "
                         "violation(s) -- run python -m "
                         "fed_tgan_tpu.analysis --telemetry")
        events = list(read_journal(jpath))
        types = [e.get("type") for e in events]
        if types != ["run_start", "round", "run_end"]:
            return _line(False, "observability",
                         f"journal round-trip produced {types}")

        # 3. the report CLI, from a fresh process, on that same journal.
        proc = subprocess.run(
            [sys.executable, "-m", "fed_tgan_tpu.obs", "report", jpath,
             "--format", "json"],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            return _line(False, "observability",
                         "report CLI failed: "
                         + (" | ".join(tail) or f"rc={proc.returncode}"))
        summary = json.loads(proc.stdout)
        if summary.get("events") != 3 or summary.get("run_id") != "doctor":
            return _line(False, "observability",
                         f"report CLI summary wrong: {summary}")
        return _line(True, "observability",
                     "obs imports without jax; journal round-trips; "
                     "report CLI summarized 3 events")
    except Exception as exc:
        return _line(False, "observability", f"{exc!r}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_observatory(timeout: int = 300) -> bool:
    """The live observation plane observes a real instrumented run.

    A subprocess (backend init must stay out of the doctor process) runs
    two federated rounds on a 2-device virtual mesh with the run journal
    installed and the in-trainer HTTP exporter bound to an ephemeral
    port, then scrapes itself: ``/metrics`` must carry the per-client
    contribution ledger series (``fed_tgan_client_weight{client=...}``),
    ``/healthz`` must report the training round progress, and
    ``/journal`` must stream one ``client_contribution`` event per
    round -- the live plane end-to-end, not just its parts."""
    import json
    import subprocess

    code = (
        "import json\n"
        "import tempfile\n"
        "import urllib.request\n"
        "from fed_tgan_tpu.parallel.mesh import (client_mesh,\n"
        "                                        provision_virtual_cpu)\n"
        "provision_virtual_cpu(2)\n"
        "import numpy as np\n"
        "import pandas as pd\n"
        "from fed_tgan_tpu.data.ingest import TablePreprocessor\n"
        "from fed_tgan_tpu.data.sharding import shard_dataframe\n"
        "from fed_tgan_tpu.federation.init import federated_initialize\n"
        "from fed_tgan_tpu.obs.exporter import TelemetryExporter, get_health\n"
        "from fed_tgan_tpu.obs.journal import RunJournal, set_journal\n"
        "from fed_tgan_tpu.train.federated import FederatedTrainer\n"
        "from fed_tgan_tpu.train.steps import TrainConfig\n"
        "rng = np.random.default_rng(7)\n"
        "n = 240\n"
        "frame = pd.DataFrame({\n"
        "    'amount': np.exp(rng.normal(2.0, 1.0, n)).round(2),\n"
        "    'color': rng.choice(['red', 'green', 'blue'], n)})\n"
        "shards = shard_dataframe(frame, 2, 'iid', seed=9)\n"
        "clients = [TablePreprocessor(frame=s, name='doctor',\n"
        "                             categorical_columns=['color'],\n"
        "                             non_negative_columns=['amount'])\n"
        "           for s in shards]\n"
        "init = federated_initialize(clients, seed=0)\n"
        "cfg = TrainConfig(embedding_dim=8, gen_dims=(16, 16),\n"
        "                  dis_dims=(16, 16), batch_size=40, pac=4)\n"
        "tr = FederatedTrainer(init, config=cfg, mesh=client_mesh(2),\n"
        "                      seed=0)\n"
        "with tempfile.TemporaryDirectory() as td:\n"
        "    journal = RunJournal(td + '/journal.jsonl', run_id='doctor')\n"
        "    set_journal(journal)\n"
        "    with TelemetryExporter(port=0) as exp:\n"
        "        tr.fit(2)\n"
        "        get = lambda p: urllib.request.urlopen(\n"
        "            exp.url + p, timeout=10).read().decode()\n"
        "        metrics, tail = get('/metrics'), get('/journal')\n"
        "        health = json.loads(get('/healthz'))\n"
        "    set_journal(None)\n"
        "    journal.close()\n"
        "print(json.dumps({\n"
        "    'weight_series': 'fed_tgan_client_weight{' in metrics,\n"
        "    'strike_series': 'fed_tgan_client_strikes{' in metrics,\n"
        "    'health_round': health.get('round'),\n"
        "    'health_status': health.get('status'),\n"
        "    'contrib_events': sum(1 for l in tail.splitlines()\n"
        "                          if '\"client_contribution\"' in l)}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "observatory", f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "observatory",
                     " | ".join(tail) or "instrumented run failed")
    try:
        res = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:
        return _line(False, "observatory", f"unparseable result: {exc!r}")
    if not res.get("weight_series") or not res.get("strike_series"):
        return _line(False, "observatory",
                     "/metrics is missing the per-client ledger series "
                     "(fed_tgan_client_weight / fed_tgan_client_strikes)")
    if res.get("health_status") != "training" or res.get("health_round") != 1:
        return _line(False, "observatory",
                     f"/healthz wrong: status={res.get('health_status')!r} "
                     f"round={res.get('health_round')!r} (want training/1)")
    if res.get("contrib_events") != 2:
        return _line(False, "observatory",
                     f"/journal streamed {res.get('contrib_events')} "
                     "client_contribution events for 2 rounds")
    return _line(True, "observatory",
                 "live exporter scraped mid-run: per-client ledger on "
                 "/metrics, round progress on /healthz, 2 "
                 "client_contribution events on /journal")


def check_cost_ledger(timeout: int = 300) -> bool:
    """The device cost ledger reports real figures and the SLO gate
    accepts the repo's own checked-in bench records.

    Two subprocesses (lowering must own backend init, like the contract
    gate): ``obs ledger`` compiles one contracted family and every entry
    must carry nonzero flops / bytes-accessed / peak bytes; then ``obs
    slo`` replays the checked-in fleet and cohort bench records against
    the packaged budgets -- exit 0 means the budgets still describe the
    artifacts this repo ships."""
    import json
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "fed_tgan_tpu.obs", "ledger", "--json",
             "--family", "train_federated"],
            capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=root,
        )
    except subprocess.TimeoutExpired:
        return _line(False, "cost-ledger", f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stdout or proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "cost-ledger",
                     "obs ledger failed: " + (" | ".join(tail)
                                              or f"rc={proc.returncode}"))
    try:
        entries = json.loads(proc.stdout)
    except ValueError:
        return _line(False, "cost-ledger", "obs ledger emitted non-JSON")
    if not entries:
        return _line(False, "cost-ledger", "obs ledger returned no entries")
    hollow = [n for n, e in entries.items()
              if not (e.get("flops") and e.get("bytes_accessed")
                      and e.get("peak_bytes"))]
    if hollow:
        return _line(False, "cost-ledger",
                     f"zero-cost entries: {sorted(hollow)[:3]}")
    checked = []
    for rec in ("BENCH_r10.json", "BENCH_r15.json"):
        path = os.path.join(root, rec)
        if not os.path.exists(path):
            continue  # bench records are repo artifacts, not a package part
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "fed_tgan_tpu.obs", "slo", path],
                capture_output=True, text=True, timeout=timeout, cwd=root,
            )
        except subprocess.TimeoutExpired:
            return _line(False, "cost-ledger",
                         f"obs slo {rec} timed out after {timeout}s")
        if proc.returncode != 0:
            tail = (proc.stdout or "").strip().splitlines()[-2:]
            return _line(False, "cost-ledger",
                         f"obs slo {rec} rc={proc.returncode}: "
                         + " | ".join(tail))
        checked.append(rec)
    slo_note = (f"slo gate passed {', '.join(checked)}" if checked
                else "no bench records on disk; slo gate skipped")
    return _line(True, "cost-ledger",
                 f"{len(entries)} train_federated programs with nonzero "
                 f"flops/bytes/peak; {slo_note}")


def check_elastic_federation(timeout: int = 420) -> bool:
    """Join / leave / drift mini-soak on a 2-client elastic trainer.

    A subprocess trains a capacity-4 trainer through the full membership
    lifecycle and asserts the three load-bearing properties:

    - **zero-recompile join**: admitting a newcomer inside capacity
      re-uploads data only — the armed compile counter sees no new
      ``epoch_local`` trace;
    - **departure renormalization**: after a leave, the survivor weights
      renormalize to sum 1 with the departed slot at exactly 0;
    - **drift detected and handled**: a schema-stable distribution shift
      raises a ``drift_alarm`` in the next window and the refit +
      weight recompute land in that same window."""
    import json
    import subprocess

    code = (
        "import json\n"
        "import numpy as np\n"
        "import pandas as pd\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from fed_tgan_tpu.analysis.sanitizers import sanitize\n"
        "from fed_tgan_tpu.data.ingest import TablePreprocessor\n"
        "from fed_tgan_tpu.federation.init import federated_initialize\n"
        "from fed_tgan_tpu.federation.streaming import OnboardingSession\n"
        "from fed_tgan_tpu.federation.elastic import (\n"
        "    DriftConfig, ElasticFederation)\n"
        "from fed_tgan_tpu.train.federated import FederatedTrainer\n"
        "from fed_tgan_tpu.train.steps import TrainConfig\n"
        "def mk(seed):\n"
        "    r = np.random.default_rng(seed)\n"
        "    return TablePreprocessor(frame=pd.DataFrame({\n"
        "        'a': r.normal(size=120),\n"
        "        'b': r.normal(2.0, 0.5, size=120),\n"
        "        'c': r.choice(['x', 'y', 'z'], size=120)}),\n"
        "        name='DoctorElastic', categorical_columns=['c'])\n"
        "clients = [mk(0), mk(1)]\n"
        "init = federated_initialize(clients, seed=0, backend='jax',\n"
        "                            similarity='sketch')\n"
        "cfg = TrainConfig(embedding_dim=8, gen_dims=(16,), dis_dims=(16,),\n"
        "                  batch_size=40, pac=4)\n"
        "out = {}\n"
        "with sanitize(transfer_guard=False) as counter:\n"
        "    tr = FederatedTrainer(init, config=cfg, seed=3, capacity=4)\n"
        "    sess = OnboardingSession(init)\n"
        "    ef = ElasticFederation(tr, sess, clients,\n"
        "                           config=DriftConfig(detect_every=1))\n"
        "    tr.fit(1)\n"
        "    before = counter.count('epoch_local')\n"
        "    ef.join([mk(2)])\n"
        "    tr.fit(1)\n"
        "    out['join_compiles'] = counter.count('epoch_local') - before\n"
        "    out['joined_pop'] = int(ef.population)\n"
        "ef.leave(1)\n"
        "w = np.asarray(tr.weights, dtype=np.float64)\n"
        "out['leave_renorm'] = bool(abs(w.sum() - 1.0) < 1e-5\n"
        "                           and w[1] == 0.0)\n"
        "ef.detect(1)  # post-membership window: WD suppressed, re-baselines\n"
        "ef.apply_drift(0, shift=2.5, seed=7)\n"
        "rec = ef.detect(2)\n"
        "out['drift_alarmed'] = bool(0 in rec['drifted'])\n"
        "out['recompute_lag'] = rec['recompute_lag_rounds']\n"
        "tr.fit(1)\n"
        "out['finished'] = int(tr.completed_epochs)\n"
        "print(json.dumps(out))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "elastic-federation",
                     f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "elastic-federation",
                     " | ".join(tail) or "mini-soak failed")
    try:
        res = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:
        return _line(False, "elastic-federation",
                     f"unparseable result: {exc!r}")
    if res.get("join_compiles", 1):
        return _line(False, "elastic-federation",
                     f"a join inside capacity recompiled the round program "
                     f"{res.get('join_compiles')} time(s) — the pow2 "
                     f"population bucket is not holding")
    if not res.get("leave_renorm"):
        return _line(False, "elastic-federation",
                     "survivor weights did not renormalize to sum 1 with "
                     "the departed slot zeroed")
    if not res.get("drift_alarmed"):
        return _line(False, "elastic-federation",
                     "a shift=2.5 scripted drift raised no drift_alarm in "
                     "the next detection window")
    if res.get("recompute_lag") != 0:
        return _line(False, "elastic-federation",
                     "similarity-weight recompute did not land in the "
                     "window that detected the drift "
                     f"(lag={res.get('recompute_lag')!r})")
    return _line(True, "elastic-federation",
                 f"{res.get('joined_pop')}-client population after a "
                 "zero-recompile join; departure renormalized; drift "
                 "alarmed and refit within one window")


def check_backend_seam(timeout: int = 300) -> bool:
    """The ``runtime/backend.py`` seam: plugin specs fail fast with a
    named error before any jax import, and the cpu ``Backend`` provisions
    the same 8-device platform the pre-seam mesh path did — proven by
    lowering one contracted family through ``Backend.provision()`` and
    comparing the fingerprints against the checked-in contract JSON."""
    import json
    import subprocess

    from fed_tgan_tpu.runtime.backend import (
        PluginRegistrationError,
        get_backend,
        plugin_env_var,
    )

    var = plugin_env_var("doesnotexist")
    try:
        get_backend("plugin:doesnotexist").provision()
        return _line(False, "backend-seam",
                     "plugin:doesnotexist provisioned with no PJRT library "
                     "-- expected PluginRegistrationError")
    except PluginRegistrationError as exc:
        if var not in str(exc):
            return _line(False, "backend-seam",
                         f"plugin error does not name {var}: {exc}")

    code = (
        "import json\n"
        "from fed_tgan_tpu.runtime.backend import get_backend\n"
        "get_backend('cpu').provision(8)\n"
        "from fed_tgan_tpu.analysis.contracts.check import load_contracts\n"
        "from fed_tgan_tpu.analysis.contracts.harness import (\n"
        "    ENTRYPOINT_FAMILIES, lower_fingerprints)\n"
        "fam = 'parallel_fedavg'\n"
        "cur = lower_fingerprints({fam: ENTRYPOINT_FAMILIES[fam]})\n"
        "stored = load_contracts([fam])[fam]['programs']\n"
        "bad = []\n"
        "for name, fp in cur[fam].items():\n"
        "    want = {k: v for k, v in stored.get(name, {}).items()\n"
        "            if k != 'require'}\n"
        "    if fp.to_dict() != want:\n"
        "        bad.append(name)\n"
        "print(json.dumps({'programs': len(cur[fam]), 'bad': bad}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except subprocess.TimeoutExpired:
        return _line(False, "backend-seam", f"timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return _line(False, "backend-seam",
                     " | ".join(tail) or "seam lowering failed")
    try:
        res = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:
        return _line(False, "backend-seam", f"unparseable result: {exc!r}")
    if res.get("bad"):
        return _line(False, "backend-seam",
                     "cpu Backend lowering diverged from the checked-in "
                     f"contracts: {', '.join(res['bad'])}")
    return _line(True, "backend-seam",
                 f"plugin fail-fast names {var}; cpu Backend lowered "
                 f"{res.get('programs')} contracted programs byte-identical")


def check_launch_pod(timeout: int = 60) -> bool:
    """``scripts/launch_pod.py --dry-run`` prints the full rank/port/env
    plan from a jax-free parent — planning a pod must never cost a
    backend init (or an import of the package) in the supervisor."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "launch_pod.py")
    try:
        proc = subprocess.run(
            [sys.executable, script, "--processes", "3", "--dry-run"],
            capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=repo)
    except subprocess.TimeoutExpired:
        return _line(False, "launch-pod",
                     f"--dry-run timed out after {timeout}s")
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-2:]
        return _line(False, "launch-pod",
                     " | ".join(tail) or "--dry-run failed")
    lines = proc.stdout.splitlines()
    ranks = [ln for ln in lines if ln.startswith("rank ")]
    if len(ranks) != 3:
        return _line(False, "launch-pod",
                     f"expected 3 rank plan lines, got {len(ranks)}")
    if "parent_jax_imported=False" not in proc.stdout:
        return _line(False, "launch-pod",
                     "the planning parent imported jax "
                     "(parent_jax_imported=False missing)")
    roles = [ln.split("role=")[1].split()[0] for ln in ranks]
    if roles != ["coordinator", "participant", "participant"]:
        return _line(False, "launch-pod", f"unexpected roles {roles}")
    return _line(True, "launch-pod",
                 "3-process plan (1 coordinator + 2 participants) printed "
                 "from a jax-free parent")


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="diagnose the runtime this framework depends on, "
                    "bottom-up; exit 0 = all checks passed")
    ap.add_argument("--probe-timeout", type=int, default=120,
                    help="accelerator probe timeout in seconds")
    ap.add_argument("--wait-healthy", action="store_true",
                    help="instead of the one-shot diagnosis, block until "
                         "the accelerator backend answers a probe (wedge-"
                         "aware: defers under host load, holds long quiet "
                         "windows between failed probes); exit 0 = healthy")
    ap.add_argument("--wait-timeout-min", type=float, default=0.0,
                    help="--wait-healthy: give up after this many minutes "
                         "(0 = wait forever)")
    ap.add_argument("--quiet-window-min", type=float, default=45.0,
                    help="--wait-healthy: quiet window after a failed probe")
    ap.add_argument("--mesh-devices", type=int, default=2,
                    help="virtual CPU mesh size for the collective check")
    ap.add_argument("--backend", choices=["cpu"], default=None,
                    help="cpu = pin this diagnosis to the cpu platform "
                         "(same semantics as the CLI flag; skips the "
                         "accelerator probe).  NOTE: the in-process config "
                         "pin, not the env var — on site-hooked hosts the "
                         "env var does not reach a fresh interpreter")
    args = ap.parse_args(argv)
    if args.wait_healthy:
        return 0 if wait_healthy(
            timeout_min=args.wait_timeout_min,
            quiet_min=args.quiet_window_min,
            probe_timeout_s=args.probe_timeout,
        ) else 1
    if args.backend == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    checks = [
        check_runtime(),
        check_backend(args.probe_timeout),
        check_virtual_mesh(args.mesh_devices),
        check_transport(),
        check_robust_aggregation(),
        check_compile_cache(),
        check_static_analysis(),
        check_locklint(),
        check_program_contracts(),
        check_analysis_all(),
        check_precision(),
        check_scan_rounds(),
        check_cohort_scale(),
        check_onboarding(),
        check_observability(),
        check_observatory(),
        check_cost_ledger(),
        check_serving(),
        check_serving_fleet(),
        check_front_door(),
        check_quality_canary(),
        check_elastic_federation(),
        check_backend_seam(),
        check_launch_pod(),
    ]
    bad = checks.count(False)
    print(f"{len(checks) - bad}/{len(checks)} checks passed")
    return 0 if bad == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
