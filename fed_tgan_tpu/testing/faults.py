"""Deterministic fault injection for the fault-tolerance layer.

A ``FaultPlan`` describes failures to inject at exact, reproducible points:

- ``kill_client:rank=R,round=E`` — drop client ``R`` (1-based transport
  rank) at the start of training round ``E`` (1-based).  In-process
  training consumes this via ``FederatedTrainer``; multihost clients
  ``os._exit`` to simulate a hard crash.
- ``delay_msg:ms=M`` — sleep ``M`` ms before every transport send
  (uniform message delay, exercises deadline slack).
- ``sever_conn:rank=R,after=N`` — client ``R`` severs its own live TCP
  connection after its ``N``-th successful send, exercising
  reconnect-with-backoff + sequence resync on both sides.
- ``crash_checkpoint:save=N`` — the ``N``-th ``save_federated`` call in
  this process raises ``FaultInjected`` mid-write (after some files are
  on disk, before the atomic publish), simulating a crash that must leave
  the previous checkpoint loadable.
- ``nan_update:rank=R[,round=E][,until=U]`` — client ``R`` (1-based)
  ships all-NaN parameters after its local training each round in the
  window [E, U] (E defaults to 1, U=0 means forever) — the classic
  diverged/hostile update the aggregation gate must quarantine.
- ``scale_update:factor=F,rank=R[,round=E][,until=U]`` (bare
  ``scale_update:F`` reads F positionally, rank defaults to 1) — client
  ``R`` scales its parameter DELTA by ``F`` (model-poisoning shape:
  finite but norm-anomalous).
- ``stuck_update:rank=R[,round=E][,until=U]`` — client ``R`` replays its
  stale pre-round parameters (zero delta), the silent-failure shape the
  low-norm side of the outlier test catches.
- ``corrupt_cache:nth=N`` — the ``N``-th init-cache entry stored in this
  process is silently truncated after the atomic publish (default the
  first), simulating bit-rot on the onboarding cache volume; the digest
  manifest must catch it on the next read and force a refit.
- ``degrade_snapshot:factor=F[,nth=N]`` (bare ``degrade_snapshot:100``
  reads F positionally, like ``scale_update``) — the ``N``-th published
  generator checkpoint (``save_synthesizer``, default the first) is
  degraded IN PLACE on disk: its first 2-D float parameter leaf is
  scaled by ``F``.  The checkpoint stays structurally valid (it loads,
  its fingerprint changes), so only quality scoring — the canary
  promotion gate — can catch it; this is the drift/corruption shape the
  quality control plane exists to auto-reject.
- ``straggle:rank=R,delay=D[,round=E][,until=U]`` — client ``R`` (1-based)
  is a scripted straggler over rounds [E, U]: under buffered aggregation
  (``TrainConfig.aggregation="buffered"``) it sits out each round's
  barrier and its delta lands ``D`` rounds later, staleness-discounted;
  under sync aggregation the fault is inert (a real straggler would
  simply stall the barrier, which is the behavior buffered mode exists
  to remove).
- ``join:round=E[,count=N]`` — ``N`` (default 1) newcomers are admitted
  to the live federation at the start of round ``E`` (1-based).  The
  churn driver (``federation/elastic.py``) consumes this: it registers
  the scripted newcomer shards via ``OnboardingSession.register_clients``
  and repacks them into the resident population between rounds.
- ``leave:client=C,round=E`` — resident client ``C`` (0-based population
  index) departs at the start of round ``E``, routed through the
  dropout/heartbeat path with survivor weight renormalization.
- ``drift:client=C,round=E[,shift=S]`` — client ``C``'s shard is swapped
  for a schema-stable, distribution-shifted version (continuous columns
  translated by ``S`` local standard deviations, categorical masses
  re-skewed; ``S`` defaults to 1.0) at the start of round ``E``.  The
  swap is silent — only the per-window drift detector (sketch-scored
  similarity vs the frozen references) can catch it; repeated kinds
  accumulate, so several ``drift:`` entries script a trajectory.

The churn kinds are host-side membership events consumed between fused
round chunks (the round program itself never sees them); the chunked
``fit`` loop lands chunk boundaries on scheduled churn rounds via
:meth:`FaultPlan.next_churn_round`, the same edge-clipping contract as
:func:`update_fault_window`.

The update faults are baked into the jitted epoch program at trace time;
the trainers force chunk boundaries at the window edges so fused rounds
stay deterministic (see :func:`update_fault_window`).

Plans parse from a spec string (``;``-separated faults, ``,``-separated
``key=value`` args) passed through the ``--faults`` CLI flag or the
``FED_TGAN_TPU_FAULTS`` env var (the env var reaches multihost
subprocesses).  Production code paths consult :func:`active_plan`, which
is None unless a plan was installed — the harness costs nothing when off.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger("fed_tgan_tpu.faults")

ENV_VAR = "FED_TGAN_TPU_FAULTS"


class FaultInjected(RuntimeError):
    """Raised at an injection point that simulates an in-process crash."""


@dataclasses.dataclass
class FaultPlan:
    """Parsed fault spec; all counters are per-process and thread-safe."""

    kill_rank: int = 0          # 0 = no kill fault
    kill_round: int = 0
    delay_ms: int = 0
    sever_rank: int = 0         # 0 = no sever fault
    sever_after: int = 0
    crash_save: int = 0         # 0 = no checkpoint-crash fault
    update_kind: str = ""       # "" = no update fault; nan | scale | stuck
    update_rank: int = 0        # 1-based client rank shipping bad updates
    update_factor: float = 1.0  # delta scale for kind == "scale"
    update_round: int = 1       # first faulty round (1-based)
    update_until: int = 0       # last faulty round (0 = forever)
    straggle_rank: int = 0      # 0 = no straggler fault
    straggle_delay: int = 1     # rounds the buffered delta arrives late
    straggle_round: int = 1     # first straggling round (1-based)
    straggle_until: int = 0     # last straggling round (0 = forever)
    corrupt_cache_nth: int = 0  # 0 = no cache-corruption fault
    degrade_factor: float = 0.0  # 0 = no snapshot-degrade fault
    degrade_nth: int = 1        # which published snapshot to degrade
    # churn schedule: host-side membership events, 1-based rounds
    joins: list = dataclasses.field(default_factory=list)   # [(round, count)]
    leaves: list = dataclasses.field(default_factory=list)  # [(round, client)]
    drifts: list = dataclasses.field(default_factory=list)  # [(round, client, shift)]

    VALID_KINDS = ("corrupt_cache", "crash_checkpoint", "degrade_snapshot",
                   "delay_msg", "drift", "join", "kill_client", "leave",
                   "nan_update", "scale_update", "sever_conn", "straggle",
                   "stuck_update")

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        self._save_calls = 0
        self._cache_stores = 0
        self._snapshot_saves = 0
        self._severed = False
        self._killed = False

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        plan = cls()
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            name, _, argstr = part.partition(":")
            if name not in cls.VALID_KINDS:
                # fail fast BEFORE arg parsing: a typo like 'nan_updat'
                # must not silently no-op, and a typo'd kind with a
                # positional factor ('scale_updat:100') must name the
                # real problem, not die on int('')
                raise ValueError(
                    f"unknown fault {name!r} in spec {spec!r}; valid "
                    f"kinds: {', '.join(cls.VALID_KINDS)}"
                )
            args = {}
            for kv in filter(None, (a.strip() for a in argstr.split(","))):
                k, eq, v = kv.partition("=")
                if not eq and name in ("scale_update", "degrade_snapshot"):
                    # reference-style positional factor: scale_update:100
                    args["factor"] = float(k)
                    continue
                k = k.strip()
                args[k] = float(v) if k in ("factor", "shift") else int(v)
            if name == "kill_client":
                plan.kill_rank = args["rank"]
                plan.kill_round = args["round"]
            elif name == "delay_msg":
                plan.delay_ms = args["ms"]
            elif name == "sever_conn":
                plan.sever_rank = args["rank"]
                plan.sever_after = args["after"]
            elif name == "crash_checkpoint":
                plan.crash_save = args.get("save", 1)
            elif name == "corrupt_cache":
                plan.corrupt_cache_nth = int(args.get("nth", 1))
            elif name == "degrade_snapshot":
                if "factor" not in args:
                    # fail fast like the unknown-kind check: a factorless
                    # degrade fault would silently no-op
                    raise ValueError(
                        f"degrade_snapshot needs a factor in spec {spec!r} "
                        "(degrade_snapshot:100 or degrade_snapshot:"
                        "factor=100)"
                    )
                plan.degrade_factor = float(args["factor"])
                plan.degrade_nth = int(args.get("nth", 1))
            elif name == "join":
                if "round" not in args:
                    # fail fast like the unknown-kind check: an unscheduled
                    # join would silently never fire
                    raise ValueError(
                        f"join needs a round in spec {spec!r} "
                        "(join:round=5 or join:round=5,count=2)"
                    )
                plan.joins.append((int(args["round"]),
                                   max(1, int(args.get("count", 1)))))
            elif name == "leave":
                missing = [k for k in ("client", "round") if k not in args]
                if missing:
                    raise ValueError(
                        f"leave needs {' and '.join(missing)} in spec "
                        f"{spec!r} (leave:client=2,round=8)"
                    )
                plan.leaves.append((int(args["round"]), int(args["client"])))
            elif name == "drift":
                missing = [k for k in ("client", "round") if k not in args]
                if missing:
                    raise ValueError(
                        f"drift needs {' and '.join(missing)} in spec "
                        f"{spec!r} (drift:client=1,round=10,shift=2.0)"
                    )
                plan.drifts.append((int(args["round"]), int(args["client"]),
                                    float(args.get("shift", 1.0))))
            elif name == "straggle":
                plan.straggle_rank = int(args["rank"])
                plan.straggle_delay = max(1, int(args.get("delay", 1)))
                plan.straggle_round = int(args.get("round", 1))
                plan.straggle_until = int(args.get("until", 0))
            elif name in ("nan_update", "scale_update", "stuck_update"):
                plan.update_kind = name.split("_", 1)[0]
                plan.update_rank = int(args.get("rank", 1))
                plan.update_factor = float(args.get("factor", 1.0))
                plan.update_round = int(args.get("round", 1))
                plan.update_until = int(args.get("until", 0))
            else:  # a kind in VALID_KINDS with no dispatch branch
                raise ValueError(
                    f"fault kind {name!r} is valid but unhandled — "
                    "parse() dispatch is missing a branch"
                )
        return plan

    # -- injection points -----------------------------------------------------

    def maybe_delay(self) -> None:
        if self.delay_ms > 0:
            time.sleep(self.delay_ms / 1000.0)

    def should_sever(self, rank: int, sent_count: int) -> bool:
        if self.sever_rank != rank or sent_count < self.sever_after:
            return False
        with self._lock:
            if self._severed:
                return False
            self._severed = True
            return True

    def should_kill(self, rank: int, round_1based: int) -> bool:
        """True exactly once, for client ``rank`` at ``round_1based``."""
        if self.kill_rank != rank or round_1based < self.kill_round:
            return False
        with self._lock:
            if self._killed:
                return False
            self._killed = True
            return True

    def on_checkpoint_write(self, path: str) -> None:
        """Called mid-``save_federated`` after partial state is on disk."""
        if self.crash_save <= 0:
            return
        with self._lock:
            self._save_calls += 1
            fire = self._save_calls == self.crash_save
        if fire:
            log.warning("FAULT: crashing checkpoint save #%d mid-write (%s)",
                        self.crash_save, path)
            raise FaultInjected(f"checkpoint save crashed mid-write: {path}")

    def on_cache_store(self, path: str) -> bool:
        """Called after an init-cache payload is published; truncates the
        ``nth`` stored file in place (bit-rot, not a crash — the store
        itself reports success).  Returns True when the fault fired."""
        if self.corrupt_cache_nth <= 0:
            return False
        with self._lock:
            self._cache_stores += 1
            fire = self._cache_stores == self.corrupt_cache_nth
        if not fire:
            return False
        log.warning("FAULT: corrupting init-cache store #%d (%s)",
                    self.corrupt_cache_nth, path)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return True

    def on_snapshot_publish(self, path: str) -> bool:
        """Called after ``save_synthesizer`` publishes a sampling
        checkpoint; degrades the ``nth`` published one in place (the save
        itself reports success and the artifact stays loadable — only the
        canary's quality scoring can catch the damage).  Returns True
        when the fault fired."""
        if self.degrade_factor == 0.0:
            return False
        with self._lock:
            self._snapshot_saves += 1
            fire = self._snapshot_saves == self.degrade_nth
        if not fire:
            return False
        log.warning("FAULT: degrading published snapshot #%d by x%g (%s)",
                    self.degrade_nth, self.degrade_factor, path)
        degrade_checkpoint(path, self.degrade_factor)
        return True

    # -- churn schedule (host-side, consumed between fused chunks) ------------

    def has_churn(self) -> bool:
        return bool(self.joins or self.leaves or self.drifts)

    def churn_events(self, e0: int) -> list:
        """Membership events due at the start of 0-based round ``e0``.

        Returns ``("join", count)`` / ``("leave", client)`` /
        ``("drift", client, shift)`` tuples in spec order (joins first,
        then leaves, then drifts — so a scripted leave at the same round
        as a join acts on the pre-join population only if spec'd with a
        lower client index, which stays stable either way: leaves name
        population indices, joins append).
        """
        due: list = []
        due += [("join", n) for r, n in self.joins if r - 1 == e0]
        due += [("leave", c) for r, c in self.leaves if r - 1 == e0]
        due += [("drift", c, s) for r, c, s in self.drifts if r - 1 == e0]
        return due

    def next_churn_round(self, e0: int) -> Optional[int]:
        """Smallest 0-based round ``>= e0`` with a scheduled churn event,
        or None.  The chunked fit loop clips fused chunks to this edge so
        membership mutation always lands on a chunk boundary — the same
        window contract as :func:`update_fault_window`."""
        rounds = [r - 1 for r, *_ in (*self.joins, *self.leaves, *self.drifts)
                  if r - 1 >= e0]
        return min(rounds) if rounds else None


def update_fault_window(
    plan: Optional[FaultPlan], e0: int, size: int
) -> tuple[Optional[tuple[str, int, float]], int]:
    """Resolve the update fault for a chunk of fused rounds.

    ``e0`` is the 0-based index of the first round in the chunk and ``size``
    its length.  Returns ``(fault, clipped_size)`` where ``fault`` is
    ``(kind, client_idx0, factor)`` if EVERY round in the (possibly clipped)
    chunk lies inside the fault window, else None.  ``clipped_size`` shrinks
    the chunk so fault activity never flips mid-chunk — the fault is a
    trace-time constant of the fused epoch program.
    """
    if plan is None or not plan.update_kind:
        return None, size
    lo = plan.update_round - 1                       # 0-based first faulty
    hi = plan.update_until - 1 if plan.update_until else None  # 0-based last
    # boundaries where activity flips, relative to e0
    for edge in sorted(x for x in (lo, (hi + 1) if hi is not None else None)
                       if x is not None and e0 < x < e0 + size):
        size = edge - e0
        break
    active = e0 >= lo and (hi is None or e0 <= hi)
    fault = ((plan.update_kind, plan.update_rank - 1, plan.update_factor)
             if active else None)
    return fault, size


def straggle_window(
    plan: Optional[FaultPlan], e0: int, size: int
) -> tuple[Optional[tuple[int, int]], int]:
    """Resolve the straggler fault for a chunk of fused rounds.

    Same window contract as :func:`update_fault_window`: returns
    ``(straggler, clipped_size)`` where ``straggler`` is
    ``(client_idx0, delay_rounds)`` if EVERY round of the (possibly
    clipped) chunk lies inside the straggle window, else None —
    ``clipped_size`` lands chunk boundaries at the window edges so the
    straggler output (a trace-time property of the fused program) never
    flips mid-chunk.
    """
    if plan is None or not plan.straggle_rank:
        return None, size
    lo = plan.straggle_round - 1                    # 0-based first straggle
    hi = plan.straggle_until - 1 if plan.straggle_until else None
    for edge in sorted(x for x in (lo, (hi + 1) if hi is not None else None)
                       if x is not None and e0 < x < e0 + size):
        size = edge - e0
        break
    active = e0 >= lo and (hi is None or e0 <= hi)
    straggler = ((plan.straggle_rank - 1, plan.straggle_delay)
                 if active else None)
    return straggler, size


def drift_frame(frame, shift: float, seed: int):
    """Deterministically drift a client shard, schema-stable.

    Continuous columns are translated by ``shift`` local standard
    deviations (mean/mode structure moves, support stays finite);
    categorical columns keep their exact vocabulary but re-skew toward
    a seeded permutation of it (probability mass rotates, no new
    categories — the frozen-reference screen in streaming registration
    must keep accepting the shard).  Same (frame, shift, seed) → same
    output, bit-for-bit; dtypes and column order are preserved.
    """
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(seed)
    out = {}
    for col in frame.columns:
        s = frame[col]
        if pd.api.types.is_numeric_dtype(s) and s.nunique() > 2:
            std = float(s.std())
            out[col] = (s + shift * (std if std > 0 else 1.0)).astype(s.dtype)
        else:
            vals = np.asarray(sorted(pd.unique(s.astype(str))))
            # rotate mass: each row flips to the "next" category with
            # probability min(0.8, 0.35 * shift) — vocabulary unchanged
            nxt = {v: vals[(i + 1) % len(vals)]
                   for i, v in enumerate(vals)}
            flip = rng.random(len(s)) < min(0.8, 0.35 * abs(shift))
            drifted = s.astype(str).to_numpy().copy()
            if flip.any():
                drifted[flip] = np.array([nxt[v] for v in drifted[flip]])
            out[col] = pd.Series(drifted, index=s.index).astype(s.dtype)
    return pd.DataFrame(out, index=frame.index)[list(frame.columns)]


def degrade_checkpoint(path: str, factor: float) -> str:
    """Deterministically degrade a published generator checkpoint in place.

    Scales the FIRST 2-D float leaf in ``arrays.npz`` (the generator's
    first dense kernel — ``params_g`` leaves flatten first, and the
    conditional sampler's probability tables come later) by ``factor``
    and rewrites the archive.  No randomness, no truncation: the
    checkpoint remains structurally valid and loadable with a NEW
    content fingerprint, so the serving registry sees a legitimate new
    generation whose outputs are garbage — exactly the shape the canary
    gate must auto-reject.  Returns the rewritten npz path.
    """
    import numpy as np

    npz = os.path.join(path, "arrays.npz")
    with np.load(npz) as z:
        data = {k: z[k] for k in z.files}
    for key in sorted(data):
        arr = data[key]
        if key.startswith("leaf_") and arr.ndim == 2 \
                and np.issubdtype(arr.dtype, np.floating):
            data[key] = (arr * factor).astype(arr.dtype)
            break
    else:
        raise ValueError(f"{npz}: no 2-D float leaf to degrade")
    with open(npz, "wb") as f:
        np.savez(f, **data)
        f.flush()
        os.fsync(f.fileno())
    return npz


_active: Optional[FaultPlan] = None
_env_checked = False


def install_plan(plan: Optional[FaultPlan]) -> None:
    """Install (or clear, with None) the process-wide plan."""
    global _active, _env_checked
    _active = plan
    _env_checked = True  # an explicit install wins over the env var


def active_plan() -> Optional[FaultPlan]:
    """The process-wide plan: explicitly installed, or lazily parsed from
    ``FED_TGAN_TPU_FAULTS`` on first use."""
    global _active, _env_checked
    if not _env_checked:
        _env_checked = True
        spec = os.environ.get(ENV_VAR)
        if spec:
            _active = FaultPlan.parse(spec)
            log.warning("fault injection active from %s=%r", ENV_VAR, spec)
    return _active
