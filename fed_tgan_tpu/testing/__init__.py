"""Deterministic fault-injection utilities (see ``faults``)."""
