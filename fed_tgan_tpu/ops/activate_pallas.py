"""Fused Pallas TPU kernel for the CTGAN output activation.

``apply_activate`` (reference Server/dtds/synthesizers/ctgan.py:67-82) is the
per-step elementwise+reduction hot op applied to every generator output: tanh
on continuous scalar dims, gumbel-softmax (tau=0.2) within every one-hot
segment.  The XLA path (`ops.segments.apply_activate`) lowers the segmented
softmax to gather/segment_sum chains; this module instead fuses the whole op
— noise add, numerically-stable segmented softmax, tanh, and the mask select
— into ONE Pallas kernel with a single HBM read and write per tensor.

TPU mapping:
- the segmented reduction is expressed as two small matmuls against a static
  0/1 membership matrix ``M`` (dim x n_softmax_segments):
  ``seg_sum = e @ M`` and ``broadcast-back = (e @ M) @ M.T`` — both land on
  the MXU instead of scatter/gather on the VPU;
- per-row numerical stability uses the ROW-GLOBAL max: subtracting one
  constant per row cancels inside every segment's softmax, so no per-segment
  max pass is needed;
- the backward pass is an analytic kernel (custom_vjp): for softmax dims
  ``dx = soft * (dy - seg_sum(dy * soft)) / tau``, for tanh dims
  ``dx = (1 - out^2) * dy`` — the forward OUTPUT is the only residual.

Gumbel noise is generated outside the kernel with ``jax.random`` (XLA fuses
it into the surrounding graph); that keeps the Pallas and XLA paths
bit-comparable under the same key and sidesteps ``pltpu.prng_*``'s lack of an
interpret-mode lowering on CPU, where the test suite runs.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from fed_tgan_tpu.ops.segments import GUMBEL_TAU, SegmentSpec

_LANE = 128  # TPU lane width: last-dim tiles are always 128 wide
_SUBLANE = 8  # float32 sublane quantum
_DEF_BLOCK_ROWS = 256


def _round_up(n: int, q: int) -> int:
    return ((n + q - 1) // q) * q


@functools.lru_cache(maxsize=64)
def _spec_constants(spec: SegmentSpec):
    """Padded static operands for a given table layout.

    Returns (dim_p, nseg_p, membership M (dim_p, nseg_p) f32,
    aux (2, dim_p) f32 with row0 = tanh mask, row1 = valid-lane mask).
    Only softmax segments get a column in M; tanh dims (and padding lanes)
    have an all-zero row, so their denominator broadcast is 0 and the kernel
    selects the tanh/zero branch for them instead.
    """
    dim_p = _round_up(max(spec.dim, _LANE), _LANE)
    softmax_segments = [s for s, (_, kind) in enumerate(spec.output_info) if kind == "softmax"]
    nseg_p = _round_up(max(len(softmax_segments), _LANE), _LANE)
    col_of = {seg: j for j, seg in enumerate(softmax_segments)}
    m = np.zeros((dim_p, nseg_p), dtype=np.float32)
    for d in range(spec.dim):
        seg = int(spec.segment_ids[d])
        if not spec.is_tanh_dim[d]:
            m[d, col_of[seg]] = 1.0
    aux = np.zeros((2, dim_p), dtype=np.float32)
    aux[0, : spec.dim] = spec.is_tanh_dim.astype(np.float32)
    aux[1, : spec.dim] = 1.0
    # softmax-column id per dim (nseg_p = "no segment" bucket, dropped after
    # the segment_max that feeds the kernel's stabilization input)
    col_ids = np.full(dim_p, nseg_p, dtype=np.int32)
    for d in range(spec.dim):
        if not spec.is_tanh_dim[d]:
            col_ids[d] = col_of[int(spec.segment_ids[d])]
    return dim_p, nseg_p, m, aux, col_ids


def _fwd_kernel(x_ref, g_ref, smax_ref, m_ref, aux_ref, out_ref):
    x = x_ref[:]
    tanh_mask = aux_ref[0, :][None, :]
    valid = aux_ref[1, :][None, :]
    softmax_mask = valid * (1.0 - tanh_mask)
    noisy = (x + g_ref[:]) * (1.0 / GUMBEL_TAU) * softmax_mask
    # per-segment max (precomputed on host graph) broadcast back to dims via
    # the membership matmul: each dim belongs to at most one segment.  A
    # row-global max would let a far-away tanh dim or another segment push
    # exp() into float32 underflow and zero out a whole segment.
    m_bcast = jnp.dot(
        smax_ref[:], m_ref[:].T,
        preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST,
    )
    e = jnp.exp(noisy - m_bcast) * softmax_mask
    seg = jnp.dot(e, m_ref[:], preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)
    denom = jnp.dot(seg, m_ref[:].T, preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)
    soft = e / (denom + (denom == 0.0))
    out_ref[:] = jnp.where(tanh_mask > 0.0, jnp.tanh(x), soft) * valid


def _bwd_kernel(dy_ref, out_ref, m_ref, aux_ref, dx_ref):
    dy = dy_ref[:]
    out = out_ref[:]
    tanh_mask = aux_ref[0, :][None, :]
    valid = aux_ref[1, :][None, :]
    soft = jnp.where(tanh_mask > 0.0, 0.0, out)  # softmax dims of the fwd output
    t = dy * soft
    seg = jnp.dot(t, m_ref[:], preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)
    inner = jnp.dot(seg, m_ref[:].T, preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)
    dx_soft = soft * (dy - inner) * (1.0 / GUMBEL_TAU)
    dx_tanh = (1.0 - out * out) * dy
    dx_ref[:] = jnp.where(tanh_mask > 0.0, dx_tanh, dx_soft) * valid


def _call(kernel, a, b, m, aux, interpret: bool):
    """Shared pallas_call wrapper: grid over row blocks, operands padded.

    ``b`` is either the gumbel noise (fwd, paired with the per-segment max)
    or the upstream cotangent (bwd); row-shaped operands share one BlockSpec.
    """
    rows_p, dim_p = a.shape
    bb = min(_DEF_BLOCK_ROWS, rows_p)
    grid = (rows_p // bb,)
    row_block = lambda i: (i, 0)
    fixed = lambda i: (0, 0)
    row_operands = [a] + list(b if isinstance(b, tuple) else (b,))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, dim_p), jnp.float32),
        grid=grid,
        in_specs=[pl.BlockSpec((bb, x.shape[1]), row_block) for x in row_operands]
        + [
            pl.BlockSpec(m.shape, fixed),
            pl.BlockSpec(aux.shape, fixed),
        ],
        out_specs=pl.BlockSpec((bb, dim_p), row_block),
        interpret=interpret,
    )(*row_operands, m, aux)


def _pad(x: jax.Array, rows_p: int, dim_p: int) -> jax.Array:
    rows, dim = x.shape
    return jnp.pad(x.astype(jnp.float32), ((0, rows_p - rows), (0, dim_p - dim)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _activate_padded(x, g, spec: SegmentSpec, interpret: bool):
    out, _ = _activate_padded_fwd(x, g, spec, interpret)
    return out


def _activate_padded_fwd(x, g, spec, interpret):
    _, nseg_p, m, aux, col_ids = _spec_constants(spec)
    # per-softmax-segment max of the scaled logits, computed in the
    # surrounding XLA graph (cheap; fuses with the noise generation) and fed
    # to the kernel for numerically exact per-segment stabilization
    softmax_mask = jnp.asarray((aux[1] > 0) & (aux[0] == 0))[None, :]
    noisy = jnp.where(softmax_mask, (x + g) * (1.0 / GUMBEL_TAU), -jnp.inf)
    smax = jax.ops.segment_max(
        noisy.T, jnp.asarray(col_ids), num_segments=nseg_p + 1, indices_are_sorted=False
    ).T[:, :nseg_p]
    smax = jnp.where(jnp.isfinite(smax), smax, 0.0)
    out = _call(
        _fwd_kernel, x, (g, smax), jnp.asarray(m), jnp.asarray(aux), interpret
    )
    return out, out  # the forward output is the only residual


def _activate_padded_bwd(spec, interpret, out, dy):
    _, _, m, aux, _ = _spec_constants(spec)
    dx = _call(_bwd_kernel, dy, out, jnp.asarray(m), jnp.asarray(aux), interpret)
    # noise enters as (x + g)/tau: softmax dims share dx, tanh dims ignore g
    tanh_mask = jnp.asarray(aux[0, :] > 0.0)[None, :]
    dg = jnp.where(tanh_mask, 0.0, dx)
    return dx, dg


_activate_padded.defvjp(_activate_padded_fwd, _activate_padded_bwd)


def fused_apply_activate(
    data: jax.Array, spec: SegmentSpec, key: jax.Array, *, interpret: bool = False
) -> jax.Array:
    """Drop-in Pallas equivalent of ``ops.segments.apply_activate``.

    Same gumbel draw (``jax.random.uniform`` under ``key``) as the XLA path,
    so both produce identical outputs for identical inputs.
    """
    rows, dim = data.shape
    dim_p = _spec_constants(spec)[0]
    rows_p = _round_up(max(rows, _SUBLANE), _SUBLANE)
    if rows_p > _DEF_BLOCK_ROWS:
        rows_p = _round_up(rows_p, _DEF_BLOCK_ROWS)
    g = -jnp.log(-jnp.log(jax.random.uniform(key, data.shape) + 1e-20) + 1e-20)
    out = _activate_padded(_pad(data, rows_p, dim_p), _pad(g, rows_p, dim_p), spec, interpret)
    return out[:rows, :dim].astype(data.dtype)


def dispatch_mode() -> str:
    """How ``ops.segments.apply_activate`` should route.

    ``FED_TGAN_TPU_PALLAS`` = ``auto`` (default: kernel on TPU, XLA
    elsewhere) | ``off`` | ``force`` | ``interpret`` (kernel in interpret
    mode — used by the test suite to exercise this path on CPU).
    """
    mode = os.environ.get("FED_TGAN_TPU_PALLAS", "auto")
    if mode not in ("auto", "off", "force", "interpret"):
        raise ValueError(f"FED_TGAN_TPU_PALLAS={mode!r} not in auto/off/force/interpret")
    if mode == "auto":
        return "force" if jax.default_backend() == "tpu" else "off"
    return mode
