"""Gradient-flow diagnostics.

The reference ships (commented-out) matplotlib gradient-flow plotting inside
its training loop for debugging vanishing/exploding gradients (reference
Server/dtds/synthesizers/ctgan.py:261-306, call sites :432,:438).  Here the
same diagnostic is a pure function over one training step's gradients —
computed on device in one jitted call, summarized per layer — plus an
optional matplotlib rendering.  It never touches the hot loop: call it
ad hoc on a trainer's current state when a run misbehaves.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fed_tgan_tpu.models.ctgan import discriminator_apply, generator_apply
from fed_tgan_tpu.models.losses import gradient_penalty
from fed_tgan_tpu.ops.segments import SegmentSpec, apply_activate, cond_loss
from fed_tgan_tpu.train.sampler import CondSampler, RowSampler
from fed_tgan_tpu.train.steps import ModelBundle, TrainConfig


def summarize_grads(grads) -> dict:
    """{leaf_path: {"avg_abs": float, "max_abs": float}} — the same per-layer
    statistics the reference's plot collects (ave_grads/max_grads)."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]:
        name = "/".join(
            getattr(p, "name", None) or str(getattr(p, "idx", p)) for p in path
        )
        arr = np.asarray(leaf)
        out[name] = {
            "avg_abs": float(np.abs(arr).mean()),
            "max_abs": float(np.abs(arr).max()),
        }
    return out


def gradient_flow(
    models: ModelBundle,
    data,
    cond: CondSampler,
    rows: RowSampler,
    spec: SegmentSpec,
    cfg: TrainConfig,
    key: jax.Array,
) -> dict:
    """Per-layer gradient statistics for one D step and one G step, from the
    same batch-construction path the real train step uses."""
    keys = jax.random.split(key, 13)
    B = cfg.batch_size
    has_cond = spec.n_discrete > 0

    z = jax.random.normal(keys[0], (B, cfg.embedding_dim))
    if has_cond:
        c1, m1, col, opt_idx = cond.sample_train(keys[1], B)
        perm = jax.random.permutation(keys[2], B)
        row_idx = rows.sample_rows(keys[3], col[perm], opt_idx[perm])
        c2 = c1[perm]
        gen_in = jnp.concatenate([z, c1], axis=1)
    else:
        row_idx = rows.sample_uniform(keys[3], B)
        gen_in = z
    real = jnp.asarray(data)[row_idx]

    fake_raw, state_g2 = generator_apply(
        models.params_g, models.state_g, gen_in, train=True
    )
    fake_act = apply_activate(fake_raw, spec, keys[4])
    if has_cond:
        fake_cat = jnp.concatenate([fake_act, c1], axis=1)
        real_cat = jnp.concatenate([real, c2], axis=1)
    else:
        fake_cat, real_cat = fake_act, real
    fake_cat = jax.lax.stop_gradient(fake_cat)

    def d_loss(params_d):
        y_fake = discriminator_apply(params_d, fake_cat, keys[5], cfg.pac)
        y_real = discriminator_apply(params_d, real_cat, keys[6], cfg.pac)
        pen = gradient_penalty(
            lambda x: discriminator_apply(params_d, x, keys[7], cfg.pac),
            real_cat, fake_cat, keys[8], pac=cfg.pac,
        )
        return jnp.mean(y_fake) - jnp.mean(y_real) + pen

    def g_loss(params_g):
        raw, _ = generator_apply(params_g, state_g2, gen_in, train=True)
        act = apply_activate(raw, spec, keys[11])
        d_in = jnp.concatenate([act, c1], axis=1) if has_cond else act
        y_fake = discriminator_apply(models.params_d, d_in, keys[12], cfg.pac)
        ce = cond_loss(raw, spec, c1, m1) if has_cond else 0.0
        return -jnp.mean(y_fake) + ce

    grads_d = jax.jit(jax.grad(d_loss))(models.params_d)
    grads_g = jax.jit(jax.grad(g_loss))(models.params_g)
    return {
        "discriminator": summarize_grads(grads_d),
        "generator": summarize_grads(grads_g),
    }


def plot_gradient_flow(stats: dict, path: Optional[str] = None):
    """Render the reference's gradient-flow bar chart (avg+max abs per layer).

    Requires matplotlib; returns the figure (saved to ``path`` if given)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, len(stats), figsize=(7 * len(stats), 4))
    if len(stats) == 1:
        axes = [axes]
    for ax, (net, layers) in zip(axes, stats.items()):
        names = list(layers)
        avg = [layers[n]["avg_abs"] for n in names]
        mx = [layers[n]["max_abs"] for n in names]
        x = np.arange(len(names))
        ax.bar(x, mx, alpha=0.4, label="max |grad|")
        ax.bar(x, avg, alpha=0.8, label="avg |grad|")
        ax.set_xticks(x)
        ax.set_xticklabels(names, rotation=90, fontsize=6)
        ax.set_yscale("log")
        ax.set_title(f"gradient flow: {net}")
        ax.legend()
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig
