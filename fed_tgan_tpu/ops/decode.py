"""On-device inverse transform (encoded layout -> numeric column values).

The reference decodes 40k sampled rows per epoch on the host with per-column
numpy loops (reference Server/dtds/features/transformers.py:430-464).  Doing
the argmax + mode-denormalization on device shrinks the device->host
transfer from (n, encoded_dim) one-hots to (n, n_columns) scalars and fuses
the whole generation+decode into one XLA program — the per-epoch snapshot
then costs one host round-trip.

Semantics identical to ``ModeNormalizer.inverse_transform``:
continuous: ``clip(u,-1,1) * 4 sigma_k + mu_k`` for the argmax active mode k;
discrete: argmax slot -> integer code.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fed_tgan_tpu.features.transformer import (
    SCALE,
    ContinuousColumn,
    DiscreteColumn,
)


def make_device_decode(columns: Sequence) -> Callable[[jax.Array], jax.Array]:
    """Build a jit-friendly decoder from ``ModeNormalizer.columns``.

    The per-column walk happens at trace time (static layout); the returned
    function is pure gathers/argmaxes.
    """
    plan = []
    st = 0
    for col in columns:
        if isinstance(col, ContinuousColumn):
            gmm = col.gmm
            active = np.flatnonzero(gmm.active)
            plan.append(
                (
                    "cont",
                    st,
                    len(active),
                    np.asarray(gmm.means[active], dtype=np.float32),
                    np.asarray(gmm.stds[active], dtype=np.float32),
                )
            )
            st += 1 + len(active)
        else:
            assert isinstance(col, DiscreteColumn)
            plan.append(("disc", st, col.size, np.asarray(col.codes, dtype=np.int32), None))
            st += col.size
    total_dim = st

    def decode(encoded: jax.Array) -> jax.Array:
        assert encoded.shape[-1] == total_dim, (encoded.shape, total_dim)
        outs = []
        for kind, start, size, a, b in plan:
            if kind == "cont":
                u = jnp.clip(encoded[:, start], -1.0, 1.0)
                v = encoded[:, start + 1 : start + 1 + size]
                k = jnp.argmax(v, axis=1)
                outs.append(u * SCALE * jnp.asarray(b)[k] + jnp.asarray(a)[k])
            else:
                v = encoded[:, start : start + size]
                codes = jnp.asarray(a)[jnp.argmax(v, axis=1)]
                outs.append(codes.astype(jnp.float32))
        return jnp.stack(outs, axis=1)

    return decode


def decode_layout(columns: Sequence) -> tuple:
    """The SHAPE of a transformer's decode plan, constants excluded:
    ``("cont", n_active_modes)`` / ``("disc", n_options)`` per column.

    Two models with equal layouts trace to identical decode programs when
    the mode means/stds and code tables ride in as runtime arguments
    (:func:`make_layout_decode`) — the property the serving fleet's
    cross-tenant program sharing and the engine's keep-programs-on-reload
    check both key on."""
    out = []
    for col in columns:
        if isinstance(col, ContinuousColumn):
            out.append(("cont", int(np.count_nonzero(col.gmm.active))))
        else:
            assert isinstance(col, DiscreteColumn)
            out.append(("disc", int(col.size)))
    return tuple(out)


def decode_tables(columns: Sequence) -> tuple:
    """The runtime constants matching :func:`decode_layout`: per column,
    ``(means, stds)`` float32 arrays over the active modes for continuous
    columns, ``(codes,)`` int32 for discrete ones.  Passed as program
    arguments, so new constants (a hot-reloaded model that kept its
    layout) are just new arguments to an already-compiled program."""
    tabs = []
    for col in columns:
        if isinstance(col, ContinuousColumn):
            active = np.flatnonzero(col.gmm.active)
            tabs.append((np.asarray(col.gmm.means[active], dtype=np.float32),
                         np.asarray(col.gmm.stds[active], dtype=np.float32)))
        else:
            assert isinstance(col, DiscreteColumn)
            tabs.append((np.asarray(col.codes, dtype=np.int32),))
    return tuple(tabs)


def make_layout_decode(layout: tuple):
    """Build ``decode(encoded, tables) -> (n, n_columns) float32`` from a
    static :func:`decode_layout`.

    Semantics are exactly :func:`make_device_decode`'s (same clip /
    argmax / ``u * 4 sigma_k + mu_k`` formula, so outputs are
    bit-identical for matching tables) — only the constants moved from
    trace-time closures into the ``tables`` argument, which is what lets
    same-layout tenants share one compiled program."""
    starts, st = [], 0
    for kind, size in layout:
        starts.append(st)
        st += (1 + size) if kind == "cont" else size
    total_dim = st

    def decode(encoded: jax.Array, tables) -> jax.Array:
        assert encoded.shape[-1] == total_dim, (encoded.shape, total_dim)
        outs = []
        for (kind, size), start, tab in zip(layout, starts, tables):
            if kind == "cont":
                means, stds = tab
                u = jnp.clip(encoded[:, start], -1.0, 1.0)
                v = encoded[:, start + 1 : start + 1 + size]
                k = jnp.argmax(v, axis=1)
                outs.append(u * SCALE * stds[k] + means[k])
            else:
                (codes,) = tab
                v = encoded[:, start : start + size]
                outs.append(codes[jnp.argmax(v, axis=1)].astype(jnp.float32))
        return jnp.stack(outs, axis=1)

    return decode


def make_device_decode_packed(columns: Sequence):
    """Like ``make_device_decode`` but with a transfer-minimal output layout.

    Returns ``(decode_fn, assemble)``:

    - ``decode_fn(encoded) -> {"cont": (n, n_cont) float32,
      "disc": (n, n_disc) int8|int16}`` — discrete codes are exact small
      ints, so shipping them as float32 wastes 2-4x the bytes.  On a
      tunneled device the per-round snapshot transfer is the wall-clock
      floor; this packing cuts it by ~25-40% for mixed tables.
    - ``assemble(parts) -> (n, n_columns) float64`` — host-side scatter of
      the two blocks back into original column order; output is identical
      to ``make_device_decode``'s (then cast to float64).
    """
    cont_pos, disc_pos = [], []
    for i, col in enumerate(columns):
        if isinstance(col, ContinuousColumn):
            cont_pos.append(i)
        else:
            assert isinstance(col, DiscreteColumn)
            disc_pos.append(i)
    int_dtype = _disc_int_dtype(columns)
    full = make_device_decode(columns)  # reuse the per-column plan/semantics
    n_cols = len(columns)
    cont_idx = np.asarray(cont_pos, dtype=np.int32)
    disc_idx = np.asarray(disc_pos, dtype=np.int32)

    def decode(encoded: jax.Array) -> dict:
        vals = full(encoded)
        return {
            "cont": vals[:, cont_idx] if len(cont_pos) else jnp.zeros(
                (encoded.shape[0], 0), jnp.float32
            ),
            "disc": vals[:, disc_idx].astype(int_dtype) if len(disc_pos)
            else jnp.zeros((encoded.shape[0], 0), int_dtype),
        }

    return decode, _make_assemble(cont_idx, disc_idx, n_cols)


U_SCALE = 32767  # int16 quantization of the clipped tanh output u in [-1, 1]


def _disc_int_dtype(columns: Sequence):
    """Smallest signed int dtype holding every discrete column's codes
    (fit()-path codes are raw column values and may be negative)."""
    max_code, min_code = 0, 0
    for col in columns:
        if isinstance(col, DiscreteColumn) and col.size:
            max_code = max(max_code, int(np.max(col.codes)))
            min_code = min(min_code, int(np.min(col.codes)))
    if -128 <= min_code and max_code <= 127:
        return jnp.int8
    if -32768 <= min_code and max_code <= 32767:
        return jnp.int16
    return jnp.int32


def make_device_decode_packed16(columns: Sequence):
    """Transfer-minimal variant of ``make_device_decode_packed``: continuous
    columns ship as (int16 quantized u, int8 active-mode index) and the
    mode denormalization ``u * 4 sigma_k + mu_k`` happens on HOST in float64.

    3 bytes/continuous value instead of 4 — on a tunneled device the
    snapshot D2H transfer is the round's floor, so this buys ~20% of the
    continuous block.  Quantization error is <= 4 sigma / 32767 per value
    (~1e-4 of a mode's std), far below any reported metric precision; use
    ``make_device_decode_packed`` where bit-exactness with the on-device
    f32 decode matters (e.g. multihost receivers that rebuild ``assemble``
    from TableMeta alone — the mu/sigma tables here live in the closure).
    """
    return _make_device_decode_packed_q(columns, u_dtype=jnp.int16,
                                        u_scale=U_SCALE)


def make_device_decode_packed8(columns: Sequence):
    """int8 variant of ``make_device_decode_packed16``: u ships as int8
    (scale 127), halving the u block — 2 bytes/continuous value, ~25% off
    the whole packed row for mixed tables like Intrusion.  Quantization
    error is <= 4 sigma / 127 (~3% of a mode's std): visible in the 3rd
    decimal of Avg_WD at most.  This is the DEFAULT snapshot layout since
    the round-4 drift bound measured the full 500-epoch protocol
    metric-identical to packed16 (PARITY.md); pin
    ``FED_TGAN_TPU_DECODE=packed16|exact`` for lower quantization error or
    byte-stable CSVs.
    """
    return _make_device_decode_packed_q(columns, u_dtype=jnp.int8,
                                        u_scale=127)


def _make_device_decode_packed_q(columns: Sequence, u_dtype, u_scale: int):
    cont_pos, disc_pos = [], []
    means_pad, stds_pad = [], []
    plan = []  # (kind, start, n_active, codes) per column, in table order
    st = 0
    max_modes = 1
    for i, col in enumerate(columns):
        if isinstance(col, ContinuousColumn):
            active = np.flatnonzero(col.gmm.active)
            cont_pos.append(i)
            means_pad.append(np.asarray(col.gmm.means[active], dtype=np.float64))
            stds_pad.append(np.asarray(col.gmm.stds[active], dtype=np.float64))
            max_modes = max(max_modes, len(active))
            plan.append(("cont", st, len(active), None))
            st += 1 + len(active)
        else:
            assert isinstance(col, DiscreteColumn)
            disc_pos.append(i)
            plan.append(("disc", st, col.size, np.asarray(col.codes, dtype=np.int32)))
            st += col.size
    if max_modes > 127:
        raise ValueError(
            f"int8 mode index supports <= 127 active GMM modes, got {max_modes} "
            "(use make_device_decode_packed for such a transformer)"
        )
    total_dim = st
    n_cols = len(columns)
    cont_idx = np.asarray(cont_pos, dtype=np.int32)
    disc_idx = np.asarray(disc_pos, dtype=np.int32)
    mu = np.zeros((len(cont_pos), max_modes), dtype=np.float64)
    sg = np.zeros((len(cont_pos), max_modes), dtype=np.float64)
    for j, (m, s) in enumerate(zip(means_pad, stds_pad)):
        mu[j, : len(m)] = m
        sg[j, : len(s)] = s
    int_dtype = _disc_int_dtype(columns)

    def decode(encoded: jax.Array) -> dict:
        assert encoded.shape[-1] == total_dim, (encoded.shape, total_dim)
        us, ks, ds = [], [], []
        for kind, start, size, codes in plan:
            if kind == "cont":
                u = jnp.clip(encoded[:, start], -1.0, 1.0)
                us.append(jnp.round(u * u_scale).astype(u_dtype))
                ks.append(
                    jnp.argmax(encoded[:, start + 1 : start + 1 + size], axis=1)
                    .astype(jnp.int8)
                )
            else:
                sel = jnp.argmax(encoded[:, start : start + size], axis=1)
                ds.append(jnp.asarray(codes)[sel].astype(int_dtype))
        n = encoded.shape[0]
        return {
            "u": jnp.stack(us, axis=1) if us else jnp.zeros((n, 0), u_dtype),
            "k": jnp.stack(ks, axis=1) if ks else jnp.zeros((n, 0), jnp.int8),
            "disc": jnp.stack(ds, axis=1) if ds else jnp.zeros((n, 0), int_dtype),
        }

    tables = {
        "mu": mu, "sg": sg, "cont_idx": cont_idx, "disc_idx": disc_idx,
        "n_cols": n_cols, "u_scale": u_scale,
    }
    # plain-array tables attached so a REMOTE receiver of the packed parts
    # (multihost rank 0) can rebuild the assemble from one pickled message
    # instead of needing the transformer closure
    decode.tables = tables
    return decode, make_assemble_packed_q(tables)


def make_assemble_packed_q(tables: dict):
    """Host-side assemble for quantized packed parts, built from the plain
    numpy TABLES a quantized decode carries (``decode.tables``) rather than
    a transformer closure — picklable, so the multihost server can decode
    snapshots shipped in the transfer-minimal layout after receiving the
    tables once."""
    mu = np.asarray(tables["mu"], dtype=np.float64)
    sg = np.asarray(tables["sg"], dtype=np.float64)
    cont_idx = np.asarray(tables["cont_idx"], dtype=np.int32)
    disc_idx = np.asarray(tables["disc_idx"], dtype=np.int32)
    n_cols = int(tables["n_cols"])
    u_scale = int(tables["u_scale"])

    def assemble(parts: dict) -> np.ndarray:
        u = np.asarray(parts["u"], dtype=np.float64) / u_scale
        k = np.asarray(parts["k"], dtype=np.int64)
        disc = np.asarray(parts["disc"])
        n = u.shape[0] if len(cont_idx) else disc.shape[0]
        out = np.empty((n, n_cols), dtype=np.float64)
        if len(cont_idx):
            sig = np.take_along_axis(sg[None, :, :], k[:, :, None], axis=2)[..., 0]
            m = np.take_along_axis(mu[None, :, :], k[:, :, None], axis=2)[..., 0]
            out[:, cont_idx] = u * SCALE * sig + m
        if len(disc_idx):
            out[:, disc_idx] = disc
        return out

    return assemble


def _make_assemble(cont_idx: np.ndarray, disc_idx: np.ndarray, n_cols: int):
    def assemble(parts: dict) -> np.ndarray:
        cont = np.asarray(parts["cont"])
        disc = np.asarray(parts["disc"])
        n = cont.shape[0] if len(cont_idx) else disc.shape[0]
        out = np.empty((n, n_cols), dtype=np.float64)
        if len(cont_idx):
            out[:, cont_idx] = cont
        if len(disc_idx):
            out[:, disc_idx] = disc
        return out

    return assemble


def assemble_for_meta(meta):
    """Host-side ``assemble`` built from a ``TableMeta`` alone — for
    receivers of packed snapshot parts that never saw the transformer (e.g.
    the multihost rank-0 server).  Column order in the packed blocks follows
    the table's column order, which both the transformer's ``columns`` list
    and ``meta.column_names`` share (decode_matrix relies on the same
    invariant)."""
    # discrete = categorical OR ordinal (both become DiscreteColumns in the
    # transformer); partition on the column kind, not the categorical list
    disc = [i for i, c in enumerate(meta.columns) if not c.is_continuous]
    cont = [i for i, c in enumerate(meta.columns) if c.is_continuous]
    return _make_assemble(
        np.asarray(cont, dtype=np.int32),
        np.asarray(disc, dtype=np.int32),
        len(meta.column_names),
    )


def select_snapshot_decode(columns: Sequence):
    """The trainers' snapshot decode: quantized packed8 by default,
    overridable per run with ``FED_TGAN_TPU_DECODE=exact|packed16|packed8``
    (or the ``FED_TGAN_TPU_EXACT_DECODE=1`` shorthand for ``exact``).

    The quantized layouts mean snapshot CSVs are not byte-identical to the
    exact f32 decode.  packed8's error (<= 4 sigma / 127 per continuous
    value) was bounded in round 4: the full 500-epoch protocol lands
    metric-identical to packed16 (PARITY.md), so the transfer-minimal
    layout became the default — on a tunneled chip the snapshot D2H copy
    is the round's floor, and packed8 is the measured 81x headline.
    Golden values recorded against the exact path (or users needing
    bit-stable CSVs across versions) can pin ``exact``; ``packed16``
    quantizes at 1e-4-of-sigma if the 8-bit error budget is uncomfortable.
    """
    import os

    mode = os.environ.get("FED_TGAN_TPU_DECODE", "")
    if not mode and os.environ.get("FED_TGAN_TPU_EXACT_DECODE", "") == "1":
        mode = "exact"
    if mode == "exact":
        _log_decode_layout("exact")
        return make_device_decode_packed(columns)
    if mode in ("", "packed8"):
        _log_decode_layout("packed8" + (" (default)" if not mode else ""))
        return make_device_decode_packed8(columns)
    if mode == "packed16":
        _log_decode_layout("packed16")
        return make_device_decode_packed16(columns)
    raise ValueError(
        f"FED_TGAN_TPU_DECODE={mode!r}: expected exact, packed16 or packed8"
    )


_decode_layout_logged = False


def _log_decode_layout(layout: str) -> None:
    """One line per process naming the active snapshot decode layout, so a
    run's logs show which quantization (and therefore which CSV parity
    contract) its snapshots carry without reverse-engineering env vars."""
    global _decode_layout_logged
    if _decode_layout_logged:
        return
    _decode_layout_logged = True
    import logging

    logging.getLogger("fed_tgan_tpu.decode").info(
        "snapshot decode layout: %s (override with "
        "FED_TGAN_TPU_DECODE=exact|packed16|packed8)", layout)
