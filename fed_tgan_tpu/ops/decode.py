"""On-device inverse transform (encoded layout -> numeric column values).

The reference decodes 40k sampled rows per epoch on the host with per-column
numpy loops (reference Server/dtds/features/transformers.py:430-464).  Doing
the argmax + mode-denormalization on device shrinks the device->host
transfer from (n, encoded_dim) one-hots to (n, n_columns) scalars and fuses
the whole generation+decode into one XLA program — the per-epoch snapshot
then costs one host round-trip.

Semantics identical to ``ModeNormalizer.inverse_transform``:
continuous: ``clip(u,-1,1) * 4 sigma_k + mu_k`` for the argmax active mode k;
discrete: argmax slot -> integer code.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fed_tgan_tpu.features.transformer import (
    SCALE,
    ContinuousColumn,
    DiscreteColumn,
)


def make_device_decode(columns: Sequence) -> Callable[[jax.Array], jax.Array]:
    """Build a jit-friendly decoder from ``ModeNormalizer.columns``.

    The per-column walk happens at trace time (static layout); the returned
    function is pure gathers/argmaxes.
    """
    plan = []
    st = 0
    for col in columns:
        if isinstance(col, ContinuousColumn):
            gmm = col.gmm
            active = np.flatnonzero(gmm.active)
            plan.append(
                (
                    "cont",
                    st,
                    len(active),
                    np.asarray(gmm.means[active], dtype=np.float32),
                    np.asarray(gmm.stds[active], dtype=np.float32),
                )
            )
            st += 1 + len(active)
        else:
            assert isinstance(col, DiscreteColumn)
            plan.append(("disc", st, col.size, np.asarray(col.codes, dtype=np.int32), None))
            st += col.size
    total_dim = st

    def decode(encoded: jax.Array) -> jax.Array:
        assert encoded.shape[-1] == total_dim, (encoded.shape, total_dim)
        outs = []
        for kind, start, size, a, b in plan:
            if kind == "cont":
                u = jnp.clip(encoded[:, start], -1.0, 1.0)
                v = encoded[:, start + 1 : start + 1 + size]
                k = jnp.argmax(v, axis=1)
                outs.append(u * SCALE * jnp.asarray(b)[k] + jnp.asarray(a)[k])
            else:
                v = encoded[:, start : start + size]
                codes = jnp.asarray(a)[jnp.argmax(v, axis=1)]
                outs.append(codes.astype(jnp.float32))
        return jnp.stack(outs, axis=1)

    return decode
