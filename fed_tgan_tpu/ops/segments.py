"""Static segment layout + segment-wise ops for the CTGAN output vector.

The reference walks ``output_info`` with Python loops and dynamic slices at
every forward (reference Server/dtds/synthesizers/ctgan.py:67-82 apply_activate,
:174-194 cond_loss).  Dynamic per-segment slicing is hostile to XLA, so here
the layout is compiled ONCE into static index arrays and every segment op
becomes a fixed gather/segment_sum — one fused elementwise+reduction kernel
per call, no per-column Python in the hot loop.

Layout vocabulary (matches the reference):
- a continuous column contributes a 1-wide 'tanh' segment (the scalar) and an
  n_active-wide 'softmax' segment (the mode one-hot);
- a discrete column contributes one 'softmax' segment (category one-hot);
- the *conditional* vector is the concatenation of ALL softmax segments —
  including the continuous columns' mode one-hots.  The reference's ``Cond``
  skips only 'tanh' segments (ctgan.py:107-118), so training-by-sampling can
  condition on a continuous column being in a particular mode, and
  ``cond_loss`` covers mode one-hots too.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

GUMBEL_TAU = 0.2  # reference ctgan.py:77


@dataclass(frozen=True, eq=False)
class SegmentSpec:
    """Static index arrays describing one table's encoded layout.

    All members are host numpy; they become XLA constants when closed over by
    a jitted function.  Used as pytree *metadata* by the sampler pytrees, so
    equality/hash must be cheap and total: every derived array is a pure
    function of ``output_info``, which therefore serves as the identity.
    """

    output_info: tuple  # ((size, kind), ...) — the reference's output_info
    dim: int  # total encoded width
    n_segments: int
    segment_ids: np.ndarray  # (dim,) segment index per feature position
    is_tanh_dim: np.ndarray  # (dim,) bool
    # conditional view: every softmax segment, in layout order
    n_discrete: int  # number of softmax segments (conditional "columns")
    n_opt: int  # total width of all softmax segments
    discrete_dims: np.ndarray  # (n_opt,) positions of softmax dims in the data layout
    cond_column_ids: np.ndarray  # (n_opt,) conditional-column index per cond position
    cond_offsets: np.ndarray  # (n_discrete,) start of each cond column in cond layout
    cond_sizes: np.ndarray  # (n_discrete,) width of each cond column

    def __eq__(self, other) -> bool:
        return isinstance(other, SegmentSpec) and self.output_info == other.output_info

    def __hash__(self) -> int:
        return hash(self.output_info)

    @classmethod
    def from_output_info(cls, output_info) -> "SegmentSpec":
        output_info = tuple((int(s), str(k)) for s, k in output_info)
        seg_ids, tanh_mask = [], []
        disc_dims, cond_col_ids, cond_offsets, cond_sizes = [], [], [], []
        pos = 0
        n_disc = 0
        for seg, (size, kind) in enumerate(output_info):
            seg_ids += [seg] * size
            tanh_mask += [kind == "tanh"] * size
            if kind == "softmax":
                cond_offsets.append(len(disc_dims))
                cond_sizes.append(size)
                disc_dims += list(range(pos, pos + size))
                cond_col_ids += [n_disc] * size
                n_disc += 1
            elif kind != "tanh":
                raise ValueError(f"unknown segment kind {kind!r}")
            pos += size
        return cls(
            output_info=output_info,
            dim=pos,
            n_segments=len(output_info),
            segment_ids=np.asarray(seg_ids, dtype=np.int32),
            is_tanh_dim=np.asarray(tanh_mask, dtype=bool),
            n_discrete=n_disc,
            n_opt=len(disc_dims),
            discrete_dims=np.asarray(disc_dims, dtype=np.int32),
            cond_column_ids=np.asarray(cond_col_ids, dtype=np.int32),
            cond_offsets=np.asarray(cond_offsets, dtype=np.int32),
            cond_sizes=np.asarray(cond_sizes, dtype=np.int32),
        )


def _segment_softmax(x: jax.Array, segment_ids: np.ndarray, n_segments: int) -> jax.Array:
    """Row-wise softmax within each segment; x is (batch, dim)."""
    m = jax.ops.segment_max(x.T, segment_ids, num_segments=n_segments)
    m = jax.lax.stop_gradient(m)[segment_ids].T
    e = jnp.exp(x - m)
    s = jax.ops.segment_sum(e.T, segment_ids, num_segments=n_segments)
    return e / s[segment_ids].T


def apply_activate_xla(data: jax.Array, spec: SegmentSpec, key: jax.Array) -> jax.Array:
    """tanh on scalar dims, gumbel-softmax (tau=0.2) on one-hot segments.

    Equivalent of reference ctgan.py:67-82 with F.gumbel_softmax semantics
    (soft sample, no straight-through).

    The Gumbel logits are an f32 island under bf16 compute: tau=0.2 scales
    logits by 5x and ``exp()`` of a bf16 difference collapses small
    between-option gaps, so noise/softmax run in f32 and only the result
    is cast back to the compute dtype (no-op casts in f32 mode — the
    Pallas kernel pins the same island internally)."""
    x = data.astype(jnp.float32)
    g = -jnp.log(-jnp.log(jax.random.uniform(key, data.shape) + 1e-20) + 1e-20)
    noisy = (x + g) / GUMBEL_TAU
    soft = _segment_softmax(noisy, spec.segment_ids, spec.n_segments)
    return jnp.where(
        jnp.asarray(spec.is_tanh_dim), jnp.tanh(x), soft
    ).astype(data.dtype)


def apply_activate(data: jax.Array, spec: SegmentSpec, key: jax.Array) -> jax.Array:
    """Dispatch: fused Pallas kernel on TPU, XLA segment ops elsewhere.

    Both paths draw the same gumbel noise from ``key`` and produce identical
    values; see ``ops.activate_pallas`` for the kernel."""
    from fed_tgan_tpu.ops import activate_pallas  # local import: avoids cycle

    mode = activate_pallas.dispatch_mode()
    if data.ndim == 2 and mode != "off":
        return activate_pallas.fused_apply_activate(data, spec, key, interpret=mode == "interpret")
    return apply_activate_xla(data, spec, key)


def segment_argmax_onehot(data: jax.Array, spec: SegmentSpec) -> jax.Array:
    """Hard version of the softmax segments (used for deterministic decode)."""
    m = jax.ops.segment_max(data.T, spec.segment_ids, num_segments=spec.n_segments)
    hard = (data == m[spec.segment_ids].T).astype(data.dtype)
    return jnp.where(jnp.asarray(spec.is_tanh_dim), jnp.tanh(data), hard)


def cond_loss(
    data: jax.Array, spec: SegmentSpec, cond_vec: jax.Array, mask: jax.Array
) -> jax.Array:
    """Masked cross-entropy between generated discrete logits and the
    conditioning one-hot (reference ctgan.py:174-194).

    data: (batch, dim) raw generator output; cond_vec: (batch, n_opt);
    mask: (batch, n_discrete) — 1 for the column each row conditioned on.

    The logsumexp / cross-entropy reduction is an f32 island under bf16
    compute (the cast is a traced no-op for f32 inputs).
    """
    data = data.astype(jnp.float32)
    logits = data[:, jnp.asarray(spec.discrete_dims)]  # (batch, n_opt)
    col_ids = spec.cond_column_ids
    m = jax.ops.segment_max(
        jax.lax.stop_gradient(logits).T, col_ids, num_segments=spec.n_discrete
    )  # (n_discrete, batch)
    shifted = logits - m[col_ids].T
    lse = (
        jnp.log(jax.ops.segment_sum(jnp.exp(shifted).T, col_ids, num_segments=spec.n_discrete))
        + m
    ).T  # (batch, n_discrete)
    target_logit = jax.ops.segment_sum(
        (logits * cond_vec).T, col_ids, num_segments=spec.n_discrete
    ).T
    ce = lse - target_logit
    return (ce * mask).sum() / data.shape[0]
