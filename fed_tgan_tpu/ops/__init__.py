from fed_tgan_tpu.ops.segments import SegmentSpec, apply_activate, cond_loss

__all__ = ["SegmentSpec", "apply_activate", "cond_loss"]
