"""Statistical similarity between a real and a synthetic table.

Same metric definitions as the reference's offline script
(reference Server/similarity_analysis.py:15-82):

- categorical column -> Jensen-Shannon distance (base 2) between category
  frequency vectors, real categories absent from the fake side contributing
  zeros;
- continuous column -> Wasserstein distance after min-max scaling fitted on
  the REAL column;
- averages reported per kind (Avg_JSD, Avg_WD).

Output CSV format matches the reference's
``*_statistical_similarity_analysis.csv`` so downstream tooling is
unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd
from scipy.spatial import distance as _sdistance
from scipy.stats import wasserstein_distance


def column_similarity(
    real: pd.Series, fake: pd.Series, categorical: bool
) -> float:
    if categorical:
        real_counts = real.astype(str).value_counts(normalize=True)
        fake_counts = fake.astype(str).value_counts(normalize=True)
        cats = sorted(real_counts.index.tolist())
        p = [real_counts[c] for c in cats]
        q = [fake_counts.get(c, 0.0) for c in cats]
        # fake-only categories contribute no real mass; the reference ignores
        # them the same way (fake categories outside the real vocabulary do
        # not appear in its sorted_categories walk)
        return float(_sdistance.jensenshannon(p, q, 2.0))
    r = real.astype(float).to_numpy()
    f = fake.astype(float).to_numpy()
    lo, hi = r.min(), r.max()
    span = hi - lo if hi > lo else 1.0
    return float(wasserstein_distance((r - lo) / span, (f - lo) / span))


def statistical_similarity(
    real: pd.DataFrame,
    fake: pd.DataFrame,
    categorical_columns: Sequence[str],
) -> tuple[float, float, dict]:
    """Returns (avg_jsd, avg_wd, per_column)."""
    cat = set(categorical_columns)
    per_column = {}
    for col in real.columns:
        per_column[col] = column_similarity(real[col], fake[col], col in cat)
    jsds = [v for c, v in per_column.items() if c in cat]
    wds = [v for c, v in per_column.items() if c not in cat]
    avg_jsd = float(np.mean(jsds)) if jsds else float("nan")
    avg_wd = float(np.mean(wds)) if wds else float("nan")
    return avg_jsd, avg_wd, per_column


def similarity_report(
    real_path: str,
    fake_paths: Sequence[str],
    categorical_columns: Sequence[str],
    epoch_times: Optional[Sequence[float]] = None,
) -> pd.DataFrame:
    """Per-epoch report, column-compatible with the reference script output
    (Epoch_No., Avg_JSD, Avg_WD, time_stamp cumulative seconds)."""
    real = pd.read_csv(real_path)
    rows = []
    for i, fp in enumerate(fake_paths):
        fake = pd.read_csv(fp)
        avg_jsd, avg_wd, _ = statistical_similarity(real, fake, categorical_columns)
        rows.append([i, avg_jsd, avg_wd])
    df = pd.DataFrame(rows, columns=["Epoch_No.", "Avg_JSD", "Avg_WD"])
    if epoch_times is not None:
        df["time_stamp"] = np.cumsum(np.asarray(epoch_times, dtype=float))
    return df
