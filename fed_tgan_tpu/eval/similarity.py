"""Statistical similarity between a real and a synthetic table.

Same metric definitions as the reference's offline script
(reference Server/similarity_analysis.py:15-82):

- categorical column -> Jensen-Shannon distance (base 2) between category
  frequency vectors, real categories absent from the fake side contributing
  zeros;
- continuous column -> Wasserstein distance after min-max scaling fitted on
  the REAL column;
- averages reported per kind (Avg_JSD, Avg_WD).

Output CSV format matches the reference's
``*_statistical_similarity_analysis.csv`` so downstream tooling is
unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd
from scipy.spatial import distance as _sdistance
from scipy.stats import wasserstein_distance


def column_similarity(
    real: pd.Series, fake: pd.Series, categorical: bool
) -> float:
    if categorical:
        real_counts = real.astype(str).value_counts(normalize=True)
        fake_counts = fake.astype(str).value_counts(normalize=True)
        cats = sorted(real_counts.index.tolist())
        p = [real_counts[c] for c in cats]
        q = [fake_counts.get(c, 0.0) for c in cats]
        # fake-only categories contribute no real mass; the reference ignores
        # them the same way (fake categories outside the real vocabulary do
        # not appear in its sorted_categories walk)
        return float(_sdistance.jensenshannon(p, q, 2.0))
    r = real.astype(float).to_numpy()
    f = fake.astype(float).to_numpy()
    lo, hi = r.min(), r.max()
    span = hi - lo if hi > lo else 1.0
    return float(wasserstein_distance((r - lo) / span, (f - lo) / span))


def statistical_similarity(
    real: pd.DataFrame,
    fake: pd.DataFrame,
    categorical_columns: Sequence[str],
) -> tuple[float, float, dict]:
    """Returns (avg_jsd, avg_wd, per_column)."""
    cat = set(categorical_columns)
    per_column = {}
    for col in real.columns:
        per_column[col] = column_similarity(real[col], fake[col], col in cat)
    jsds = [v for c, v in per_column.items() if c in cat]
    wds = [v for c, v in per_column.items() if c not in cat]
    avg_jsd = float(np.mean(jsds)) if jsds else float("nan")
    avg_wd = float(np.mean(wds)) if wds else float("nan")
    return avg_jsd, avg_wd, per_column


def similarity_report(
    real_path: str,
    fake_paths: Sequence[str],
    categorical_columns: Sequence[str],
    epoch_times: Optional[Sequence[float]] = None,
) -> pd.DataFrame:
    """Per-epoch report, column-compatible with the reference script output
    (Epoch_No., Avg_JSD, Avg_WD, time_stamp cumulative seconds)."""
    real = pd.read_csv(real_path)
    rows = []
    for i, fp in enumerate(fake_paths):
        fake = pd.read_csv(fp)
        avg_jsd, avg_wd, _ = statistical_similarity(real, fake, categorical_columns)
        rows.append([i, avg_jsd, avg_wd])
    df = pd.DataFrame(rows, columns=["Epoch_No.", "Avg_JSD", "Avg_WD"])
    if epoch_times is not None:
        df["time_stamp"] = np.cumsum(np.asarray(epoch_times, dtype=float))
    return df


def _main(argv=None) -> int:
    """Offline similarity analysis over a run's per-epoch snapshots — the
    reference's ``similarity_analysis.py`` workflow (reference
    Server/similarity_analysis.py:88-118) as a module CLI."""
    import argparse
    import glob
    import os
    import re

    p = argparse.ArgumentParser(
        description="Per-epoch Avg_JSD/Avg_WD report over synthesis snapshots"
    )
    p.add_argument("--real", required=True, help="real table CSV")
    p.add_argument("--result-dir", required=True,
                   help="directory with <name>_synthesis_epoch_<i>.csv files")
    p.add_argument("--name", required=True, help="run/dataset name prefix")
    p.add_argument("--categorical", nargs="*", default=[])
    p.add_argument("--timing", default=None,
                   help="timestamp_experiment.csv (one wall-clock per round)")
    p.add_argument("-o", "--out", default=None,
                   help="output CSV (default <result-dir>/"
                        "<name>_statistical_similarity_analysis.csv)")
    args = p.parse_args(argv)

    pat = re.compile(rf"{re.escape(args.name)}_synthesis_epoch_(\d+)\.csv$")
    found = []
    for f in glob.glob(os.path.join(args.result_dir, f"{args.name}_synthesis_epoch_*.csv")):
        m = pat.search(f)
        if m:
            found.append((int(m.group(1)), f))
    if not found:
        print(f"no {args.name}_synthesis_epoch_*.csv under {args.result_dir}")
        return 2
    found.sort()
    epochs, paths = zip(*found)

    use_timing = False
    if args.timing:
        with open(args.timing) as f:
            per_round = [float(line.split(",")[0]) for line in f if line.strip()]
        if per_round:
            # snapshots may be sparser than rounds (--sample-every); charge
            # each snapshot the cumulative time up to its round
            cum = np.cumsum(per_round)
            cum_at = [cum[min(e, len(cum) - 1)] for e in epochs]
            use_timing = True
        else:
            print(f"note: {args.timing} is empty; omitting time_stamp column")

    df = similarity_report(args.real, list(paths), args.categorical)
    df["Epoch_No."] = list(epochs)
    if use_timing:
        df["time_stamp"] = cum_at
    out = args.out or os.path.join(
        args.result_dir, f"{args.name}_statistical_similarity_analysis.csv"
    )
    df.to_csv(out, index=False)
    print(df.to_string(index=False))
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
