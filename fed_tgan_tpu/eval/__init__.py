"""Evaluation suite: statistical similarity + ML utility.

Lazy re-exports: ``python -m fed_tgan_tpu.eval.utility`` would otherwise
import the submodule through this package first and trip runpy's
already-in-sys.modules warning."""


def __getattr__(name):
    import importlib

    if name in ("utility", "similarity"):  # submodule attribute access
        return importlib.import_module(f"{__name__}.{name}")
    if name in ("ml_utility", "utility_difference"):
        return getattr(importlib.import_module(f"{__name__}.utility"), name)
    if name == "statistical_similarity":
        return importlib.import_module(f"{__name__}.similarity").statistical_similarity
    raise AttributeError(name)


__all__ = ["ml_utility", "statistical_similarity", "utility_difference"]
