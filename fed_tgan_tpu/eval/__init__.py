from fed_tgan_tpu.eval.similarity import statistical_similarity
from fed_tgan_tpu.eval.utility import ml_utility, utility_difference

__all__ = ["ml_utility", "statistical_similarity", "utility_difference"]
