"""ML-utility evaluation (train-on-synthetic, test-on-real).

Same protocol as the reference (reference Server/utility_analysis.py:15-119):
label-encode categoricals on real-train ∪ real-test, StandardScaler fitted on
the full real table, then LR / DecisionTree / RandomForest / MLP classifiers
(class_weight balanced where supported, random_state 69); report accuracy and
weighted F1, and the real-minus-synthetic difference.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import pandas as pd

RANDOM_STATE = 69


def ml_utility(
    reference_frame: pd.DataFrame,
    train: pd.DataFrame,
    test: pd.DataFrame,
    target_column: str,
    categorical_columns: Sequence[str],
) -> list[list[float]]:
    """[ [acc, weighted_f1] for LR, DT, RF, MLP ] trained on ``train``.

    ``reference_frame`` is the union of real train+test — encoders and the
    scaler are fitted on it (reference utility_analysis.py:32-51)."""
    from sklearn import ensemble, linear_model, metrics, preprocessing, tree
    from sklearn.metrics import f1_score
    from sklearn.neural_network import MLPClassifier

    ref = reference_frame.copy()
    train = train.copy()
    test = test.copy()

    for col in categorical_columns:
        le = preprocessing.LabelEncoder()
        for df in (ref, train, test):
            df[col] = df[col].astype(str)
        le.fit(ref[col].values)
        for df in (ref, train, test):
            df[col] = le.transform(df[col])

    y_train = train[target_column]
    x_train = train.drop(columns=[target_column])
    y_test = test[target_column]
    x_test = test.drop(columns=[target_column])
    ref = ref.drop(columns=[target_column])

    scaler = preprocessing.StandardScaler().fit(ref.values)
    x_train = scaler.transform(x_train)
    x_test = scaler.transform(x_test)

    models = [
        linear_model.LogisticRegression(class_weight="balanced", random_state=RANDOM_STATE),
        tree.DecisionTreeClassifier(class_weight="balanced", random_state=RANDOM_STATE),
        ensemble.RandomForestClassifier(class_weight="balanced", random_state=RANDOM_STATE),
        MLPClassifier(random_state=RANDOM_STATE),
    ]
    out = []
    for model in models:
        model.fit(x_train, y_train)
        pred = model.predict(x_test)
        out.append(
            [
                float(metrics.accuracy_score(y_test, pred)),
                float(f1_score(y_test, pred, average="weighted")),
            ]
        )
    return out


def utility_difference(
    real_train: pd.DataFrame,
    synthetic: pd.DataFrame,
    test: pd.DataFrame,
    target_column: str,
    categorical_columns: Sequence[str],
) -> dict:
    """Real-vs-synthetic utility gap; ``delta_f1`` is the headline number
    the reference README reports (README.md:67)."""
    reference_frame = pd.concat([real_train, test])
    real_u = np.asarray(
        ml_utility(reference_frame, real_train, test, target_column, categorical_columns)
    )
    fake_u = np.asarray(
        ml_utility(reference_frame, synthetic, test, target_column, categorical_columns)
    )
    diff = real_u - fake_u
    return {
        "real": real_u.tolist(),
        "synthetic": fake_u.tolist(),
        "difference": diff.tolist(),
        "delta_accuracy": float(diff.mean(axis=0)[0]),
        "delta_f1": float(diff.mean(axis=0)[1]),
    }
