"""ML-utility evaluation (train-on-synthetic, test-on-real).

Same protocol as the reference (reference Server/utility_analysis.py:15-119):
label-encode categoricals on real-train ∪ real-test, StandardScaler fitted on
the full real table, then LR / DecisionTree / RandomForest / MLP classifiers
(class_weight balanced where supported, random_state 69); report accuracy and
weighted F1, and the real-minus-synthetic difference.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import pandas as pd

RANDOM_STATE = 69


def ml_utility(
    reference_frame: pd.DataFrame,
    train: pd.DataFrame,
    test: pd.DataFrame,
    target_column: str,
    categorical_columns: Sequence[str],
) -> list[list[float]]:
    """[ [acc, weighted_f1] for LR, DT, RF, MLP ] trained on ``train``.

    ``reference_frame`` is the union of real train+test — encoders and the
    scaler are fitted on it (reference utility_analysis.py:32-51)."""
    from sklearn import ensemble, linear_model, metrics, preprocessing, tree
    from sklearn.metrics import f1_score
    from sklearn.neural_network import MLPClassifier

    ref = reference_frame.copy()
    train = train.copy()
    test = test.copy()

    for col in categorical_columns:
        le = preprocessing.LabelEncoder()
        for df in (ref, train, test):
            df[col] = df[col].astype(str)
        le.fit(ref[col].values)
        for df in (ref, train, test):
            df[col] = le.transform(df[col])

    y_train = train[target_column]
    x_train = train.drop(columns=[target_column])
    y_test = test[target_column]
    x_test = test.drop(columns=[target_column])
    ref = ref.drop(columns=[target_column])

    scaler = preprocessing.StandardScaler().fit(ref.values)
    # .values on both sides: fitting on the bare array but transforming a
    # DataFrame triggers sklearn's feature-names warning on every call
    x_train = scaler.transform(x_train.values)
    x_test = scaler.transform(x_test.values)

    models = [
        linear_model.LogisticRegression(class_weight="balanced", random_state=RANDOM_STATE),
        tree.DecisionTreeClassifier(class_weight="balanced", random_state=RANDOM_STATE),
        ensemble.RandomForestClassifier(class_weight="balanced", random_state=RANDOM_STATE),
        MLPClassifier(random_state=RANDOM_STATE),
    ]
    import warnings

    from sklearn.exceptions import ConvergenceWarning

    out = []
    for model in models:
        with warnings.catch_warnings():
            # the reference runs these classifiers at sklearn defaults, where
            # LR/MLP routinely stop at max_iter; keeping the defaults is
            # required for metric parity, so silence the (expected) warnings
            # instead of changing the estimator
            warnings.simplefilter("ignore", ConvergenceWarning)
            model.fit(x_train, y_train)
        pred = model.predict(x_test)
        out.append(
            [
                float(metrics.accuracy_score(y_test, pred)),
                float(f1_score(y_test, pred, average="weighted")),
            ]
        )
    return out


def utility_difference(
    real_train: pd.DataFrame,
    synthetic: pd.DataFrame,
    test: pd.DataFrame,
    target_column: str,
    categorical_columns: Sequence[str],
) -> dict:
    """Real-vs-synthetic utility gap; ``delta_f1`` is the headline number
    the reference README reports (README.md:67)."""
    reference_frame = pd.concat([real_train, test])
    real_u = np.asarray(
        ml_utility(reference_frame, real_train, test, target_column, categorical_columns)
    )
    fake_u = np.asarray(
        ml_utility(reference_frame, synthetic, test, target_column, categorical_columns)
    )
    diff = real_u - fake_u
    return {
        "real": real_u.tolist(),
        "synthetic": fake_u.tolist(),
        "difference": diff.tolist(),
        "delta_accuracy": float(diff.mean(axis=0)[0]),
        "delta_f1": float(diff.mean(axis=0)[1]),
    }


def _main(argv=None) -> int:
    """Train-on-synthetic/test-on-real utility gap — the reference's
    ``utility_analysis.py`` workflow (reference Server/utility_analysis.py:
    94-119) as a module CLI."""
    import argparse
    import json

    p = argparse.ArgumentParser(
        description="ML-utility gap (LR/DT/RF/MLP acc+F1, real minus synthetic)"
    )
    p.add_argument("--real-train", required=True)
    p.add_argument("--real-test", required=True)
    p.add_argument("--synthetic", required=True)
    p.add_argument("--target", required=True)
    p.add_argument("--categorical", nargs="*", default=[])
    p.add_argument("--json", action="store_true", help="machine-readable output")
    args = p.parse_args(argv)

    train = pd.read_csv(args.real_train)
    test = pd.read_csv(args.real_test)
    fake = pd.read_csv(args.synthetic)
    fake = fake[train.columns.tolist()]
    res = utility_difference(train, fake, test, args.target, args.categorical)
    if args.json:
        print(json.dumps(res))
        return 0
    models = ["LR", "DT", "RF", "MLP"]
    print(f"{'model':<6} {'real acc':>9} {'real F1':>8} {'syn acc':>8} {'syn F1':>7}")
    for i, m in enumerate(models):
        ra, rf = res["real"][i]
        sa, sf = res["synthetic"][i]
        print(f"{m:<6} {ra:>9.4f} {rf:>8.4f} {sa:>8.4f} {sf:>7.4f}")
    print(f"delta_accuracy={res['delta_accuracy']:.6f} delta_f1={res['delta_f1']:.6f}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
