"""fed_tgan_tpu — a TPU-native federated tabular-GAN framework.

A from-scratch JAX/XLA re-design of the capabilities of Fed-TGAN
(arXiv:2108.07927; reference implementation `zhao-zilong/Fed-TGAN`):
federated training of a conditional tabular GAN (CTGAN-style, WGAN-GP,
mode-specific normalization) with column-similarity-weighted FedAvg.

Where the reference runs one process per participant glued together with
PyTorch RPC over Gloo/TensorPipe (reference Server/dtds/distributed.py:849-857),
this framework runs ONE SPMD program over a `jax.sharding.Mesh` with a
`clients` axis: each device holds one participant's data shard, local
training is a jitted per-device region, and the per-epoch weighted model
aggregation is a single `lax.psum` collective over ICI.

Layout:
- ``data``       — schema/metadata, CSV ingestion, dates, decode, sharding
- ``features``   — Bayesian-GMM mode-specific normalization (fit/refit/transform)
- ``ops``        — segment ops (gumbel-softmax, segment CE) on static layouts
- ``models``     — CTGAN generator/discriminator as parameter pytrees
- ``train``      — standalone + federated trainers, device-side samplers
- ``federation`` — host-side init: category harmonization, GMM refit, weights
- ``parallel``   — mesh construction, in-graph weighted FedAvg collectives
- ``eval``       — statistical-similarity and ML-utility evaluation
- ``runtime``    — native (C++) host transport for multi-host control plane
"""

__version__ = "0.1.0"
