"""``python -m fed_tgan_tpu`` — the CLI entry point."""

import sys

from fed_tgan_tpu.cli import main

sys.exit(main())
