"""Serving observability: counters behind ``/healthz`` and ``/metrics``.

Re-implemented on the unified :mod:`fed_tgan_tpu.obs.registry` layer
(PR 6): the counters and the latency reservoir are real registry
metrics, so a service's numbers can be merged with the process-wide
training/transport metrics while keeping the exact snapshot keys and
Prometheus text format the serve tests and dashboards were built on.

Thread-safe (the HTTP handler threads record sheds, the batch worker
records completions); locking lives inside the registry metric types.
Latency quantiles come from the histogram's bounded reservoir of the
most recent requests — constant memory under sustained traffic, exact
over any bench-sized window.  Still importable before jax/numpy
warm-up: the obs registry is pure stdlib by contract.
"""

from __future__ import annotations

import time
from typing import Optional

from fed_tgan_tpu.obs.registry import MetricsRegistry


def _quantile(sorted_vals: list, q: float) -> float:
    """Nearest-rank quantile on an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class ServiceMetrics:
    """Request/batch counters for one :class:`~.service.SamplingService`.

    Each instance owns an isolated :class:`MetricsRegistry` by default
    (one service = one scrape target); pass ``registry=`` to publish
    into a shared one instead.
    """

    def __init__(self, reservoir: int = 4096,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.started_at = time.time()
        self._requests = self.registry.counter(
            "requests_total", "sampling requests answered")
        self._rows = self.registry.counter(
            "rows_total", "synthetic rows returned")
        self._batches = self.registry.counter(
            "batches_total", "worker micro-batches executed")
        self._shed = self.registry.counter(
            "shed_total", "requests shed at admission")
        self._errors = self.registry.counter(
            "errors_total", "requests failed")
        self._reloads = self.registry.counter(
            "reloads_total", "model hot reloads")
        # seconds, enqueue -> response ready
        self._latency = self.registry.histogram(
            "latency_seconds", "request latency (s)", reservoir=reservoir)

    # ------------------------------------------------- attribute compat
    # pre-registry callers read these as plain ints

    @property
    def requests_total(self) -> int:
        return int(self._requests.value)

    @property
    def rows_total(self) -> int:
        return int(self._rows.value)

    @property
    def batches_total(self) -> int:
        return int(self._batches.value)

    @property
    def shed_total(self) -> int:
        return int(self._shed.value)

    @property
    def errors_total(self) -> int:
        return int(self._errors.value)

    @property
    def reloads_total(self) -> int:
        return int(self._reloads.value)

    # ---------------------------------------------------------- record

    def record_batch(self, n_requests: int) -> None:
        self._batches.inc()

    def record_request(self, latency_s: float, rows: int) -> None:
        self._requests.inc()
        self._rows.inc(rows)
        self._latency.observe(latency_s)

    def record_shed(self) -> None:
        self._shed.inc()

    def record_error(self) -> None:
        self._errors.inc()

    def record_reload(self) -> None:
        self._reloads.inc()

    # --------------------------------------------------------- export

    def snapshot(self, queue_depth: int = 0) -> dict:
        lat = self._latency.reservoir_values()
        uptime = max(time.time() - self.started_at, 1e-9)
        requests = self.requests_total
        rows = self.rows_total
        batches = self.batches_total
        return {
            "uptime_s": round(uptime, 3),
            "requests_total": requests,
            "rows_total": rows,
            "batches_total": batches,
            "shed_total": self.shed_total,
            "errors_total": self.errors_total,
            "reloads_total": self.reloads_total,
            "queue_depth": queue_depth,
            # requests coalesced per worker cycle; > 1 means
            # micro-batching is actually kicking in under load
            "batch_occupancy": round(requests / batches, 3)
            if batches else 0.0,
            "rows_per_sec": round(rows / uptime, 1),
            "latency_p50_ms": round(_quantile(lat, 0.50) * 1e3, 2),
            "latency_p99_ms": round(_quantile(lat, 0.99) * 1e3, 2),
        }

    def render_prometheus(self, queue_depth: int = 0,
                          prefix: str = "fed_tgan_serving") -> str:
        snap = self.snapshot(queue_depth)
        lines = []
        for key, value in snap.items():
            kind = "counter" if key.endswith("_total") else "gauge"
            lines.append(f"# TYPE {prefix}_{key} {kind}")
            lines.append(f"{prefix}_{key} {value}")
        return "\n".join(lines) + "\n"
