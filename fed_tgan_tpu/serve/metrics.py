"""Serving observability: counters behind ``/healthz`` and ``/metrics``.

Thread-safe (the HTTP handler threads record sheds, the batch worker
records completions).  Latency quantiles come from a bounded reservoir of
the most recent requests — constant memory under sustained traffic, exact
over any bench-sized window.  ``render_prometheus`` emits the plain-text
exposition format so a scraper (or ``curl | grep``) works unmodified.
"""

from __future__ import annotations

import threading
import time
from collections import deque


def _quantile(sorted_vals: list, q: float) -> float:
    """Nearest-rank quantile on an already-sorted list (no numpy: the
    metrics path must stay importable before jax/numpy warm-up)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class ServiceMetrics:
    """Request/batch counters for one :class:`~.service.SamplingService`."""

    def __init__(self, reservoir: int = 4096):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=reservoir)  # seconds, enqueue -> response ready
        self.started_at = time.time()
        self.requests_total = 0
        self.rows_total = 0
        self.batches_total = 0
        self.shed_total = 0
        self.errors_total = 0
        self.reloads_total = 0

    def record_batch(self, n_requests: int) -> None:
        with self._lock:
            self.batches_total += 1

    def record_request(self, latency_s: float, rows: int) -> None:
        with self._lock:
            self.requests_total += 1
            self.rows_total += rows
            self._lat.append(latency_s)

    def record_shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def record_reload(self) -> None:
        with self._lock:
            self.reloads_total += 1

    def snapshot(self, queue_depth: int = 0) -> dict:
        with self._lock:
            lat = sorted(self._lat)
            uptime = max(time.time() - self.started_at, 1e-9)
            return {
                "uptime_s": round(uptime, 3),
                "requests_total": self.requests_total,
                "rows_total": self.rows_total,
                "batches_total": self.batches_total,
                "shed_total": self.shed_total,
                "errors_total": self.errors_total,
                "reloads_total": self.reloads_total,
                "queue_depth": queue_depth,
                # requests coalesced per worker cycle; > 1 means
                # micro-batching is actually kicking in under load
                "batch_occupancy": round(
                    self.requests_total / self.batches_total, 3
                ) if self.batches_total else 0.0,
                "rows_per_sec": round(self.rows_total / uptime, 1),
                "latency_p50_ms": round(_quantile(lat, 0.50) * 1e3, 2),
                "latency_p99_ms": round(_quantile(lat, 0.99) * 1e3, 2),
            }

    def render_prometheus(self, queue_depth: int = 0,
                          prefix: str = "fed_tgan_serving") -> str:
        snap = self.snapshot(queue_depth)
        lines = []
        for key, value in snap.items():
            kind = "counter" if key.endswith("_total") else "gauge"
            lines.append(f"# TYPE {prefix}_{key} {kind}")
            lines.append(f"{prefix}_{key} {value}")
        return "\n".join(lines) + "\n"
