"""Serving observability: counters behind ``/healthz`` and ``/metrics``.

Re-implemented on the unified :mod:`fed_tgan_tpu.obs.registry` layer
(PR 6): the counters and the latency reservoir are real registry
metrics, so a service's numbers can be merged with the process-wide
training/transport metrics while keeping the exact snapshot keys and
Prometheus text format the serve tests and dashboards were built on.

Thread-safe (the HTTP handler threads record sheds, the batch worker
records completions); locking lives inside the registry metric types.
Latency quantiles come from the histogram's bounded reservoir of the
most recent requests — constant memory under sustained traffic, exact
over any bench-sized window.  Still importable before jax/numpy
warm-up: the obs registry is pure stdlib by contract.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from fed_tgan_tpu.obs.registry import MetricsRegistry

#: request lifecycle stages, in order.  ``queue_wait`` = enqueue ->
#: popped by the worker; ``batch_form`` = popped -> this request's own
#: processing starts (absorbs the wait behind earlier batch members, so
#: the five stages sum to ~the full server-side latency); ``dispatch``
#: = device program dispatch + host harvest; ``decode`` = inverse
#: feature transform; ``serialize`` = CSV bytes.
STAGES = ("queue_wait", "batch_form", "dispatch", "decode", "serialize")


class DrainRate:
    """Aggregate worker drain rate (requests/second), EWMA-smoothed.

    Every batch worker notes each batch it completes; the sample interval
    is measured between consecutive notes from ANY worker, so the
    estimate reflects the service's combined drain rate and scales with
    the worker count — the 503 Retry-After hint divides queue depth by
    this instead of assuming one worker's throughput."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rate = 0.0
        self._t = time.monotonic()

    def note(self, n_requests: int) -> None:
        now = time.monotonic()
        with self._lock:
            dt = max(now - self._t, 1e-6)
            self._t = now
            sample = n_requests / dt
            self._rate = sample if self._rate <= 0.0 \
                else 0.2 * sample + 0.8 * self._rate

    def rate(self) -> float:
        with self._lock:
            return self._rate


class QualityStore:
    """Per-tenant canary quality state behind ``/metrics`` and status
    endpoints.

    Kept as a plain locked dict rather than registry gauges: the ISSUE-16
    contract names the series ``fed_tgan_quality_{jsd,wd}{tenant=...}``
    with no service prefix (the same names whether the single-model
    service or the fleet exports them), while the obs registry renders
    bare metric names and :class:`ServiceMetrics` renders from its
    snapshot — so both hosts append these lines manually."""

    def __init__(self):
        # re-entrant: _state takes it again under the recording methods
        self._lock = threading.RLock()
        self._tenants: dict = {}  # tenant -> state dict

    def _state(self, tenant: str) -> dict:
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None:
                state = {"avg_jsd": None, "avg_wd": None,
                         "promotions": 0, "rejections": 0}
                self._tenants[tenant] = state
            return state

    def record_scores(self, tenant: str, avg_jsd, avg_wd) -> None:
        """Latest shadow-scored candidate quality for ``tenant``."""
        if avg_jsd is None or avg_wd is None:
            return
        with self._lock:
            state = self._state(tenant)
            state["avg_jsd"] = float(avg_jsd)
            state["avg_wd"] = float(avg_wd)

    def record_decision(self, tenant: str, promoted: bool) -> None:
        with self._lock:
            state = self._state(tenant)
            state["promotions" if promoted else "rejections"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {tenant: dict(state)
                    for tenant, state in sorted(self._tenants.items())}

    def render_prometheus(self) -> str:
        """The per-tenant quality series, fixed base names (no service
        prefix): ``fed_tgan_quality_jsd{tenant=...}`` etc.  Empty string
        while no canary decision has been scored (immediate-mode output
        stays byte-identical)."""
        snap = self.snapshot()
        if not snap:
            return ""
        lines = []
        for key, kind in (("jsd", "gauge"), ("wd", "gauge"),
                          ("promotions_total", "counter"),
                          ("rejections_total", "counter")):
            field = {"jsd": "avg_jsd", "wd": "avg_wd"}.get(key, key[:-6])
            series = [(t, s[field]) for t, s in snap.items()
                      if s[field] is not None]
            if not series:
                continue
            lines.append(f"# TYPE fed_tgan_quality_{key} {kind}")
            lines.extend(
                f'fed_tgan_quality_{key}{{tenant="{t}"}} {v:g}'
                for t, v in series)
        return "\n".join(lines) + "\n" if lines else ""


def _quantile(sorted_vals: list, q: float) -> float:
    """Nearest-rank quantile on an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _stage_stats(hist) -> dict:
    vals = hist.reservoir_values()
    return {
        "count": int(hist.count),
        "p50_ms": round(_quantile(vals, 0.50) * 1e3, 2),
        "p99_ms": round(_quantile(vals, 0.99) * 1e3, 2),
    }


class ServiceMetrics:
    """Request/batch counters for one :class:`~.service.SamplingService`.

    Each instance owns an isolated :class:`MetricsRegistry` by default
    (one service = one scrape target); pass ``registry=`` to publish
    into a shared one instead.
    """

    def __init__(self, reservoir: int = 4096,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.started_at = time.time()
        self._requests = self.registry.counter(
            "requests_total", "sampling requests answered")
        self._rows = self.registry.counter(
            "rows_total", "synthetic rows returned")
        self._batches = self.registry.counter(
            "batches_total", "worker micro-batches executed")
        self._shed = self.registry.counter(
            "shed_total", "requests shed at admission")
        self._errors = self.registry.counter(
            "errors_total", "requests failed")
        self._reloads = self.registry.counter(
            "reloads_total", "model hot reloads")
        # seconds, enqueue -> response ready
        self._latency = self.registry.histogram(
            "latency_seconds", "request latency (s)", reservoir=reservoir)
        # the queue-depth gauge the module docstring always advertised:
        # sampled by the batch worker each cycle, scrape-time fallback
        # in snapshot() keeps the pre-gauge callers working
        self._queue_depth = self.registry.gauge(
            "queue_depth", "requests parked in the admission queue")
        # per-stage latency attribution (seconds): one labeled series
        # per lifecycle stage, same exact-quantile reservoir contract
        # as the end-to-end histogram
        self._stages = {
            stage: self.registry.histogram(
                "stage_seconds", "request stage latency (s)",
                reservoir=reservoir, labels={"stage": stage})
            for stage in STAGES
        }
        # canary promotion state (empty — and invisible in every export —
        # unless a gate records into it)
        self.quality = QualityStore()

    # ------------------------------------------------- attribute compat
    # pre-registry callers read these as plain ints

    @property
    def requests_total(self) -> int:
        return int(self._requests.value)

    @property
    def rows_total(self) -> int:
        return int(self._rows.value)

    @property
    def batches_total(self) -> int:
        return int(self._batches.value)

    @property
    def shed_total(self) -> int:
        return int(self._shed.value)

    @property
    def errors_total(self) -> int:
        return int(self._errors.value)

    @property
    def reloads_total(self) -> int:
        return int(self._reloads.value)

    # ---------------------------------------------------------- record

    def record_batch(self, n_requests: int) -> None:
        self._batches.inc()

    def record_request(self, latency_s: float, rows: int) -> None:
        self._requests.inc()
        self._rows.inc(rows)
        self._latency.observe(latency_s)

    def record_shed(self) -> None:
        self._shed.inc()

    def record_error(self) -> None:
        self._errors.inc()

    def record_reload(self) -> None:
        self._reloads.inc()

    def record_stages(self, stages: dict) -> None:
        """Observe one request's per-stage seconds ({stage: s})."""
        for stage, seconds in stages.items():
            hist = self._stages.get(stage)
            if hist is not None:
                hist.observe(seconds)

    def set_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(int(depth))

    # --------------------------------------------------------- export

    def stage_snapshot(self) -> dict:
        """{stage: {count, p50_ms, p99_ms}} for stages with data."""
        return {stage: _stage_stats(hist)
                for stage, hist in self._stages.items() if hist.count}

    def snapshot(self, queue_depth: int = 0) -> dict:
        lat = self._latency.reservoir_values()
        uptime = max(time.time() - self.started_at, 1e-9)
        requests = self.requests_total
        rows = self.rows_total
        batches = self.batches_total
        return {
            "uptime_s": round(uptime, 3),
            "requests_total": requests,
            "rows_total": rows,
            "batches_total": batches,
            "shed_total": self.shed_total,
            "errors_total": self.errors_total,
            "reloads_total": self.reloads_total,
            "queue_depth": queue_depth,
            # requests coalesced per worker cycle; > 1 means
            # micro-batching is actually kicking in under load
            "batch_occupancy": round(requests / batches, 3)
            if batches else 0.0,
            "rows_per_sec": round(rows / uptime, 1),
            "latency_p50_ms": round(_quantile(lat, 0.50) * 1e3, 2),
            "latency_p99_ms": round(_quantile(lat, 0.99) * 1e3, 2),
        }

    def render_prometheus(self, queue_depth: int = 0,
                          prefix: str = "fed_tgan_serving") -> str:
        snap = self.snapshot(queue_depth)
        lines = []
        for key, value in snap.items():
            kind = "counter" if key.endswith("_total") else "gauge"
            lines.append(f"# TYPE {prefix}_{key} {kind}")
            lines.append(f"{prefix}_{key} {value}")
        stages = self.stage_snapshot()
        if stages:
            lines.append(f"# TYPE {prefix}_stage_p99_ms gauge")
            for stage, st in stages.items():
                lines.append(f'{prefix}_stage_p99_ms{{stage="{stage}"}} '
                             f"{st['p99_ms']}")
            lines.append(f"# TYPE {prefix}_stage_p50_ms gauge")
            for stage, st in stages.items():
                lines.append(f'{prefix}_stage_p50_ms{{stage="{stage}"}} '
                             f"{st['p50_ms']}")
        return "\n".join(lines) + "\n" + self.quality.render_prometheus()


class FleetMetrics:
    """Per-tenant labeled counters for one :class:`~.fleet.FleetService`.

    Every request-path metric carries a ``tenant`` label (one series per
    tenant on a shared base name, the PR 6 registry's label support), so
    a single ``/metrics`` scrape separates the tenants; sheds addition-
    ally carry ``reason`` ∈ {quota, capacity} — the 429/503 split is an
    admission contract and the metric must be able to prove which side
    fired.  Fleet-level gauges (cache occupancy, tenant count) are
    pushed in at scrape time from the LRU cache's own stats.
    """

    #: per-tenant latency buckets: serving answers in ms-to-seconds
    LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                       0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self, reservoir: int = 4096,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.started_at = time.time()
        self.reservoir = int(reservoir)
        self._tenants: dict = {}   # name -> per-tenant metric bundle
        self._tlock = threading.Lock()
        self._batches = self.registry.counter(
            "batches_total", "worker micro-batches executed")
        self._lane_dispatches = self.registry.counter(
            "lane_dispatches_total",
            "coalesced multi-tenant device dispatches")
        self._lane_requests = self.registry.counter(
            "lane_requests_total",
            "requests answered via a coalesced lane dispatch")
        self._cache_entries = self.registry.gauge(
            "program_cache_entries", "compiled programs held by the LRU")
        self._cache_bytes = self.registry.gauge(
            "program_cache_bytes", "estimated bytes held by the LRU")
        self._cache_hits = self.registry.gauge(
            "program_cache_hits_total", "LRU lookups served from cache")
        self._cache_misses = self.registry.gauge(
            "program_cache_misses_total", "LRU lookups that built")
        self._cache_evictions = self.registry.gauge(
            "program_cache_evictions_total", "LRU entries evicted")
        self._tenant_gauge = self.registry.gauge(
            "tenants", "tenant models currently hot")
        self._queue_depth = self.registry.gauge(
            "queue_depth", "requests parked in the admission queue")
        self._lanes_occupied = self.registry.gauge(
            "lanes_occupied",
            "lanes filled by the most recent coalesced dispatch")
        # row-pool gauges (all zero when no pool is configured): pushed
        # at scrape time from RowPool.stats(), same pattern as the LRU
        self._pool_gauges = {
            key: self.registry.gauge(
                f"row_pool_{key}", f"row pool {key.replace('_', ' ')}")
            for key in ("keys", "chunks", "rows", "hits", "misses",
                        "fills", "evictions")
        }
        # canary promotion state, same fixed-name series as the
        # single-model service exports (see QualityStore)
        self.quality = QualityStore()

    def _bundle(self, tenant: str) -> dict:
        with self._tlock:
            b = self._tenants.get(tenant)
            if b is None:
                lab = {"tenant": tenant}
                reg = self.registry
                b = {
                    "requests": reg.counter(
                        "requests_total", "sampling requests answered",
                        labels=lab),
                    "rows": reg.counter(
                        "rows_total", "synthetic rows returned", labels=lab),
                    "errors": reg.counter(
                        "errors_total", "requests failed", labels=lab),
                    "reloads": reg.counter(
                        "reloads_total", "model hot reloads", labels=lab),
                    "pool_hits": reg.counter(
                        "pool_hits_total",
                        "requests answered from the row pool", labels=lab),
                    "shed_quota": reg.counter(
                        "shed_total", "requests shed at admission",
                        labels={"tenant": tenant, "reason": "quota"}),
                    "shed_capacity": reg.counter(
                        "shed_total", "requests shed at admission",
                        labels={"tenant": tenant, "reason": "capacity"}),
                    "latency": reg.histogram(
                        "latency_seconds", "request latency (s)",
                        buckets=self.LATENCY_BUCKETS,
                        reservoir=self.reservoir, labels=lab),
                    "stages": {
                        stage: reg.histogram(
                            "stage_seconds", "request stage latency (s)",
                            buckets=self.LATENCY_BUCKETS,
                            reservoir=self.reservoir,
                            labels={"tenant": tenant, "stage": stage})
                        for stage in STAGES
                    },
                }
                self._tenants[tenant] = b
            return b

    # ---------------------------------------------------------- record

    def record_batch(self, n_requests: int) -> None:
        self._batches.inc()

    def record_lane_dispatch(self, n_requests: int) -> None:
        self._lane_dispatches.inc()
        self._lane_requests.inc(n_requests)

    def record_request(self, tenant: str, latency_s: float,
                       rows: int) -> None:
        b = self._bundle(tenant)
        b["requests"].inc()
        b["rows"].inc(rows)
        b["latency"].observe(latency_s)

    def record_pool_hit(self, tenant: str, latency_s: float,
                        rows: int) -> None:
        """A request answered from the row pool — it still counts as a
        served request (the bench's headline and the quota math see it),
        but it never reaches a worker batch, so occupancy excludes it."""
        b = self._bundle(tenant)
        b["requests"].inc()
        b["rows"].inc(rows)
        b["pool_hits"].inc()
        b["latency"].observe(latency_s)

    def record_shed(self, tenant: str, reason: str) -> None:
        b = self._bundle(tenant)
        b["shed_quota" if reason == "quota" else "shed_capacity"].inc()

    def record_error(self, tenant: str) -> None:
        self._bundle(tenant)["errors"].inc()

    def record_reload(self, tenant: str) -> None:
        self._bundle(tenant)["reloads"].inc()

    def record_stages(self, tenant: str, stages: dict) -> None:
        """Observe one request's per-stage seconds for ``tenant``."""
        hists = self._bundle(tenant)["stages"]
        for stage, seconds in stages.items():
            hist = hists.get(stage)
            if hist is not None:
                hist.observe(seconds)

    def set_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(int(depth))

    def set_lanes_occupied(self, lanes: int) -> None:
        self._lanes_occupied.set(int(lanes))

    def set_fleet_state(self, n_tenants: int, cache_stats: dict) -> None:
        self._tenant_gauge.set(n_tenants)
        self._cache_entries.set(cache_stats.get("entries", 0))
        self._cache_bytes.set(cache_stats.get("bytes", 0))
        self._cache_hits.set(cache_stats.get("hits", 0))
        self._cache_misses.set(cache_stats.get("misses", 0))
        self._cache_evictions.set(cache_stats.get("evictions", 0))

    def set_pool_state(self, pool_stats: Optional[dict]) -> None:
        for key, gauge in self._pool_gauges.items():
            gauge.set(int((pool_stats or {}).get(key, 0)))

    # --------------------------------------------------------- export

    def stage_snapshots(self) -> dict:
        """{tenant: {stage: {count, p50_ms, p99_ms}}}, tenants with data."""
        with self._tlock:
            bundles = dict(self._tenants)
        out = {}
        for tenant, b in sorted(bundles.items()):
            stages = {stage: _stage_stats(hist)
                      for stage, hist in b["stages"].items() if hist.count}
            if stages:
                out[tenant] = stages
        return out

    def tenant_snapshot(self, tenant: str) -> dict:
        b = self._bundle(tenant)
        lat = b["latency"].reservoir_values()
        stages = {stage: _stage_stats(hist)
                  for stage, hist in b["stages"].items() if hist.count}
        extra = {"stages": stages} if stages else {}
        return {
            **extra,
            "requests_total": int(b["requests"].value),
            "rows_total": int(b["rows"].value),
            "pool_hits_total": int(b["pool_hits"].value),
            "errors_total": int(b["errors"].value),
            "reloads_total": int(b["reloads"].value),
            "shed_quota_total": int(b["shed_quota"].value),
            "shed_capacity_total": int(b["shed_capacity"].value),
            "latency_p50_ms": round(_quantile(lat, 0.50) * 1e3, 2),
            "latency_p99_ms": round(_quantile(lat, 0.99) * 1e3, 2),
        }

    def snapshot(self, queue_depth: int = 0) -> dict:
        with self._tlock:
            names = sorted(self._tenants)
        per_tenant = {name: self.tenant_snapshot(name) for name in names}
        uptime = max(time.time() - self.started_at, 1e-9)
        requests = sum(t["requests_total"] for t in per_tenant.values())
        rows = sum(t["rows_total"] for t in per_tenant.values())
        pool_hits = sum(t["pool_hits_total"] for t in per_tenant.values())
        batches = int(self._batches.value)
        # occupancy is a property of the DISPATCHED path: pool hits never
        # form a batch, so they are excluded from the numerator — a high
        # hit rate cannot mask a starved coalescer
        dispatched = requests - pool_hits
        return {
            "uptime_s": round(uptime, 3),
            "requests_total": requests,
            "rows_total": rows,
            "pool_hits_total": pool_hits,
            "batches_total": batches,
            "lane_dispatches_total": int(self._lane_dispatches.value),
            "lane_requests_total": int(self._lane_requests.value),
            "queue_depth": queue_depth,
            "lanes_occupied": int(self._lanes_occupied.value),
            "batch_occupancy": round(dispatched / batches, 3)
            if batches else 0.0,
            "rows_per_sec": round(rows / uptime, 1),
            "tenants": per_tenant,
        }

    def render_prometheus(self, queue_depth: int = 0,
                          prefix: str = "fed_tgan_fleet") -> str:
        # the registry already renders every labeled series; add the two
        # queue/uptime gauges the registry doesn't own
        head = (f"# TYPE {prefix}_queue_depth gauge\n"
                f"{prefix}_queue_depth {queue_depth}\n"
                f"# TYPE {prefix}_uptime_s gauge\n"
                f"{prefix}_uptime_s "
                f"{max(time.time() - self.started_at, 0.0):g}\n")
        return (head + self.registry.render_prometheus()
                + self.quality.render_prometheus())
