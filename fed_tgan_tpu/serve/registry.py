"""Model registry over run artifacts and keep-K checkpoints.

One trained run leaves three artifacts under ``<out>/models/`` (the layout
both ``--save-model`` paths and the multihost server write):

- ``synthesizer/``                      the sampling checkpoint
  (``runtime.checkpoint.save_synthesizer``: host.pkl + arrays.npz);
- ``<name>.json``                       the global ``TableMeta``;
- ``label_encoders_<name>.pickle``      the harmonized category encoders.

:func:`resolve_artifact` is the ``--sample-from`` discovery logic factored
out of the CLI (same candidate walk, same pairing rules, same messages) so
the one-shot path and the serving registry cannot drift.  A loaded model's
identity is the content hash of its checkpoint bytes
(:func:`runtime.checkpoint.checkpoint_fingerprint`), which makes hot-reload
exact: :meth:`ModelRegistry.maybe_reload` swaps models precisely when a new
checkpoint generation with different bytes has been published (atomic
rename, so a half-written save is never picked up).
"""

from __future__ import annotations

import glob
import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Sequence


class ArtifactError(RuntimeError):
    """No loadable run artifact at the requested root."""


class MetaMismatchError(ArtifactError):
    """The newest meta JSON postdates the saved synthesizer."""


@dataclass(frozen=True)
class ResolvedArtifact:
    """Paths of one run's sampling artifacts (nothing loaded yet)."""

    models_dir: str
    synth_dir: str
    meta_path: str
    enc_path: str
    name: str


def resolve_artifact(root: str, log=print) -> ResolvedArtifact:
    """Locate the synthesizer + meta/encoder pair under ``root``.

    ``root`` may be the run's out-dir, its ``models`` dir, or the
    synthesizer dir itself — the same three candidates the CLI's
    ``--sample-from`` accepted.  Raises :class:`ArtifactError` with the
    train-first hint when nothing loadable exists."""
    root = os.path.abspath(root)
    candidates = [os.path.join(root, "models"), root, os.path.dirname(root)]
    for cand in candidates:
        synth = os.path.join(cand, "synthesizer")
        # a meta JSON counts only with its paired encoder pickle (the two
        # decode artifacts are written together)
        metas = [
            m for m in sorted(glob.glob(os.path.join(cand, "*.json")))
            if os.path.exists(os.path.join(
                cand,
                "label_encoders_"
                f"{os.path.splitext(os.path.basename(m))[0]}.pickle",
            ))
        ]
        if os.path.isdir(synth) and metas:
            if len(metas) > 1:
                # several runs share this models dir; the synthesizer dir
                # holds only the LAST-saved artifact, so take the newest
                # meta (written in the same run) and say so
                metas.sort(key=os.path.getmtime)
                log(
                    "--sample-from: multiple run artifacts in "
                    f"{cand} ({[os.path.basename(m) for m in metas]}); "
                    f"using the newest: {os.path.basename(metas[-1])}"
                )
            name = os.path.splitext(os.path.basename(metas[-1]))[0]
            return ResolvedArtifact(
                models_dir=cand,
                synth_dir=synth,
                meta_path=metas[-1],
                enc_path=os.path.join(cand, f"label_encoders_{name}.pickle"),
                name=name,
            )
    raise ArtifactError(
        f"no synthesizer artifact + meta JSON/encoder pair found under any "
        f"of {candidates} (train once with --save-model first)"
    )


def check_meta_freshness(art: ResolvedArtifact, allow: bool = False,
                         log=print) -> None:
    """Reject a meta JSON newer than the saved synthesizer.

    meta/encoders are written at training START, the synthesizer at the
    END — a later run that crashed (or omitted --save-model) leaves the
    newest meta paired with an OLDER run's synthesizer.  Decoding through
    mismatched artifacts produces wrong categories or a shape error, so
    this is a hard :class:`MetaMismatchError` unless ``allow`` (the
    ``--allow-meta-mismatch`` escape hatch) downgrades it to a warning."""
    try:
        synth_mtime = max(
            os.path.getmtime(os.path.join(art.synth_dir, f))
            for f in os.listdir(art.synth_dir)
        )
        stale = os.path.getmtime(art.meta_path) > synth_mtime
    except (OSError, ValueError):
        return  # unreadable/empty synth dir: load_synthesizer will explain
    if not stale:
        return
    msg = (
        f"meta {os.path.basename(art.meta_path)} is newer than the saved "
        "synthesizer — the run that wrote it likely never saved a model "
        "(crashed or ran without --save-model).  If the schema changed "
        "between runs, sampling through the OLDER synthesizer decodes "
        "wrong categories or fails on shapes"
    )
    if not allow:
        raise MetaMismatchError(
            f"{msg}; pass --allow-meta-mismatch to sample anyway"
        )
    log(f"WARNING: {msg} (proceeding: --allow-meta-mismatch)")


@dataclass
class LoadedModel:
    """One fully-loaded serving model: synthesizer + decode artifacts."""

    model_id: str          # checkpoint content hash (12 hex chars)
    synth: object          # runtime.checkpoint.SavedSynthesizer
    meta: object           # data.schema.TableMeta
    encoders: Sequence     # data.encoders.CategoryEncoder per categorical
    artifact: ResolvedArtifact
    loaded_at: float = field(default_factory=time.time)


def load_model(art: ResolvedArtifact, source_dir: str | None = None) -> LoadedModel:
    """Load the synthesizer + decode artifacts into a :class:`LoadedModel`.

    ``source_dir`` overrides the checkpoint directory (a rotation slot like
    ``synthesizer.1``) while meta/encoders still come from ``art``."""
    from fed_tgan_tpu.data.schema import TableMeta
    from fed_tgan_tpu.runtime.checkpoint import (
        checkpoint_fingerprint,
        load_synthesizer,
    )

    synth_dir = source_dir or art.synth_dir
    model_id = checkpoint_fingerprint(synth_dir)
    synth = load_synthesizer(synth_dir)
    meta = TableMeta.load_json(art.meta_path)
    with open(art.enc_path, "rb") as f:
        encoders = [d["label_encoder"] for d in pickle.load(f)]
    return LoadedModel(
        model_id=model_id, synth=synth, meta=meta, encoders=encoders,
        artifact=art,
    )


class ModelRegistry:
    """Lazily-loaded, hot-reloadable model over one artifact root.

    ``get()`` loads on first use; ``maybe_reload()`` is the cheap poll the
    service worker calls between micro-batches: a stat-signature check
    first (mtimes + sizes of the checkpoint payload and meta), then the
    content fingerprint only when the stats moved, then a full reload only
    when the bytes actually changed AND the new generation is loadable
    (half-published checkpoints and torn writes are skipped — the previous
    model keeps serving)."""

    def __init__(self, root: str, allow_meta_mismatch: bool = False,
                 log=print):
        self.root = root
        self.allow_meta_mismatch = allow_meta_mismatch
        self._log = log
        self._model: LoadedModel | None = None
        self._stat_sig: tuple | None = None

    def _resolve_checked(self) -> ResolvedArtifact:
        art = resolve_artifact(self.root, log=self._log)
        check_meta_freshness(art, allow=self.allow_meta_mismatch,
                             log=self._log)
        return art

    @staticmethod
    def _stat_signature(art: ResolvedArtifact) -> tuple:
        parts = []
        for p in (os.path.join(art.synth_dir, "host.pkl"),
                  os.path.join(art.synth_dir, "arrays.npz"),
                  art.meta_path):
            try:
                st = os.stat(p)
                parts.append((p, st.st_mtime_ns, st.st_size))
            except OSError:
                parts.append((p, None, None))
        return tuple(parts)

    def get(self) -> LoadedModel:
        if self._model is None:
            art = self._resolve_checked()
            self._model = load_model(art)
            self._stat_sig = self._stat_signature(art)
        return self._model

    def maybe_reload(self) -> bool:
        """Swap in a newer checkpoint generation if one landed; returns
        whether a reload happened.  Never raises: a torn or mismatched new
        artifact is logged and the current model keeps serving."""
        if self._model is None:
            return False
        try:
            art = resolve_artifact(self.root, log=lambda *_: None)
        except ArtifactError:
            return False
        sig = self._stat_signature(art)
        if sig == self._stat_sig:
            return False
        from fed_tgan_tpu.runtime.checkpoint import (
            _is_valid_checkpoint,
            checkpoint_fingerprint,
        )

        if not _is_valid_checkpoint(art.synth_dir):
            return False  # mid-publish: catch it on the next poll
        try:
            if checkpoint_fingerprint(art.synth_dir) == self._model.model_id:
                self._stat_sig = sig  # rewrite of identical bytes
                return False
            check_meta_freshness(art, allow=self.allow_meta_mismatch,
                                 log=self._log)
            model = load_model(art)
        except ArtifactError as exc:
            self._log(f"registry: reload skipped ({exc})")
            self._stat_sig = sig  # don't re-log every poll
            return False
        except Exception as exc:  # torn write raced past the validity probe
            self._log(f"registry: reload failed ({exc!r}); keeping "
                      f"{self._model.model_id}")
            # remember the failed generation like the ArtifactError branch:
            # without this a persistently-torn candidate is re-loaded and
            # re-logged on EVERY poll (the next genuinely-new publish moves
            # the signature again and retries)
            self._stat_sig = sig
            from fed_tgan_tpu.obs.journal import emit as _emit_event

            _emit_event("serve_reload_failed",
                        model_id=self._model.model_id, error=repr(exc))
            return False
        self._log(f"registry: hot-reload {self._model.model_id} -> "
                  f"{model.model_id}")
        self._model = model
        self._stat_sig = sig
        return True

    # ------------------------------------------------- canaried promotion
    # the canary gate splits maybe_reload's walk into poll / load /
    # promote-or-dismiss steps so a candidate can be SCORED before (or
    # instead of) being installed; maybe_reload itself is untouched — the
    # default --promote immediate path stays byte-identical

    def poll_candidate(self) -> "CandidateInfo | None":
        """A loadable-looking new generation, without installing it.

        Same stat-signature / validity / fingerprint walk as
        :meth:`maybe_reload`, stopping before the load: returns None when
        nothing new landed (identical-bytes rewrites advance the stat
        signature exactly like ``maybe_reload`` does)."""
        if self._model is None:
            return None
        try:
            art = resolve_artifact(self.root, log=lambda *_: None)
        except ArtifactError:
            return None
        sig = self._stat_signature(art)
        if sig == self._stat_sig:
            return None
        from fed_tgan_tpu.runtime.checkpoint import (
            _is_valid_checkpoint,
            checkpoint_fingerprint,
        )

        if not _is_valid_checkpoint(art.synth_dir):
            return None  # mid-publish: catch it on the next poll
        try:
            fingerprint = checkpoint_fingerprint(art.synth_dir)
        except OSError:
            return None  # torn mid-read; next poll
        if fingerprint == self._model.model_id:
            self._stat_sig = sig  # rewrite of identical bytes
            return None
        return CandidateInfo(artifact=art, sig=sig, fingerprint=fingerprint)

    def load_candidate(self, cand: "CandidateInfo") -> LoadedModel:
        """Fully load a polled candidate (raises on torn/mismatched
        artifacts — the gate turns that into a dismissal, not a crash)."""
        check_meta_freshness(cand.artifact, allow=self.allow_meta_mismatch,
                             log=self._log)
        return load_model(cand.artifact)

    def promote(self, model: LoadedModel, cand: "CandidateInfo") -> None:
        """Install a gate-approved candidate as the serving model."""
        self._log(f"registry: promote {self._model.model_id} -> "
                  f"{model.model_id}")
        self._model = model
        self._stat_sig = cand.sig

    def dismiss(self, cand: "CandidateInfo") -> None:
        """Remember a rejected/unloadable candidate's signature so the
        same bytes are not re-examined every poll — only a genuinely new
        publish moves the signature again."""
        self._stat_sig = cand.sig


@dataclass(frozen=True)
class CandidateInfo:
    """One polled-but-not-installed checkpoint generation."""

    artifact: ResolvedArtifact
    sig: tuple
    fingerprint: str
