"""Synthetic-data serving: registry + compiled sampling engine + HTTP service.

The reference hands consumers per-epoch CSV snapshots; the CLI's
``--sample-from`` regenerates one batch and exits.  This package is the
long-lived, request-driven path the ROADMAP's "serves heavy traffic" north
star needs:

- ``registry``   — resolves run artifacts (the ``--sample-from`` discovery
  logic, factored out of the CLI), content-hashes checkpoints into model
  ids, and hot-reloads when a newer generation lands;
- ``engine``     — one jitted program per (batch-bucket, conditional)
  fusing generator forward + conditional draw + device decode, with a
  deterministic offset-addressable row stream (N rows in K chunks is
  bit-identical to one N-row draw);
- ``service``    — stdlib-only HTTP server with a bounded queue,
  micro-batch coalescing, load shedding, and graceful drain;
- ``metrics``    — request latency (end-to-end and per lifecycle stage:
  queue_wait/batch_form/dispatch/decode/serialize), queue-depth and
  lane-occupancy gauges, batch occupancy, and rows-per-second counters
  behind ``/healthz`` and ``/metrics``;
- ``demo``       — a tiny self-contained artifact builder the doctor
  check, serving bench, and tests share.
"""

from __future__ import annotations

__all__ = ["demo", "engine", "metrics", "registry", "service"]
