"""Multi-tenant serving fleet: many hot models behind one HTTP server.

Three layers on top of the single-model :mod:`~fed_tgan_tpu.serve.service`
shape:

**FleetRegistry** — an ordered map of tenant name -> (per-tenant
:class:`~.registry.ModelRegistry`, per-tenant
:class:`~.engine.SamplingEngine`).  Each tenant keeps its own validity-
gated hot reload (content-hash identity, torn-write tolerance) exactly as
the single-model path does; load/evict are admin operations journaled as
``fleet_load`` / ``fleet_evict``.

**ProgramCache** — a byte- and entry-budgeted LRU of compiled bucket
programs shared by every tenant engine.  Programs are keyed by the full
layout signature (:meth:`SamplingEngine.layout_key`), which is the trace
identity: tenants whose encoded layouts are equal resolve to the SAME
compiled program per (bucket, conditional) pair — N same-schema tenants
cost one compile, not N.  Different-layout tenants get differently-named
programs (the ``_L<tag>`` suffix), so the sanitizer compile budget still
holds per name.

**FleetService** — N batch workers (``workers``) over a sharded bounded
queue (one shard per worker, round-robin admission, so workers never
contend on one queue lock).  Each worker coalesces ACROSS tenants:
queued single-chunk requests are grouped by bucket key ``(steps,
conditional, layout-sig)`` and each group rides ONE vmapped device
dispatch (per-tenant params/tables stacked on a lane axis, output sliced
and decoded per tenant on the way out) — requests from different tenants
with the same encoded layout share a device program launch.  A bounded
``coalesce_window_s`` holds a forming batch briefly when more traffic is
in flight, so lanes actually fill under closed-loop load instead of
dispatching singletons.  Lane programs write into per-worker donated
scratch pools (``donation_required`` is a contract on both; per-worker
pools keep concurrent dispatches from serializing on one scratch lock).
Multi-chunk requests and singleton groups fall back to the tenant
engine's path against a per-batch snapshot, so a hot reload can never
swap a model out from under a batch already formed for it.  The shared
:class:`ProgramCache` coordinates in-flight builds, so N workers racing
to the same bucket still compile it exactly once (the sanitizer compile
budget holds across workers).

An optional :class:`~.pool.RowPool` answers requests whose rows are
already cached as pre-serialized segments WITHOUT touching the queue —
the quota token is charged first, so a quota tenant stays pinned even
when its traffic is all pool hits.

The HTTP layer is selectable: ``http_mode="asyncio"`` (the production
front door — :mod:`~fed_tgan_tpu.serve.frontdoor`, zero-copy segment
streaming) or ``"threaded"`` (the legacy stdlib server, kept for
compatibility; TCP_NODELAY is set either way — stdlib's buffering used
to interact with Nagle + delayed ACK for a flat ~40 ms per response).
Both adapt the same :meth:`FleetService.route` table, so routes cannot
drift between the two.

Admission is per-tenant and two-staged: a token bucket (configured
requests/second + burst) sheds with **429** ``reason=quota`` BEFORE the
queue, and a per-tenant in-flight cap (a share of the queue) plus the
bounded queue itself shed with **503** ``reason=capacity`` — one hot
tenant cannot starve the rest.  Sheds are counted per tenant (labeled
metrics) and journaled as rate-limited ``tenant_shed`` summary events.

Endpoints: ``/t/<tenant>/sample`` (per-tenant sampling, same params as
``/sample``), ``/fleet`` (GET list / POST ``{"action": "load"|"evict"}``
admin), ``/healthz``, ``/metrics`` (per-tenant labeled Prometheus
series), and ``/sample`` as a single-tenant convenience alias.
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
import time
import urllib.parse
from collections import OrderedDict
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple, Union

import numpy as np

from fed_tgan_tpu.analysis.sanitizers import hot_region
from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.serve.engine import (
    ConditionError,
    EngineSnapshot,
    SamplingEngine,
    build_bucket_program,
)
from fed_tgan_tpu.serve.metrics import DrainRate, FleetMetrics
from fed_tgan_tpu.serve.naming import fleet_bucket_name
from fed_tgan_tpu.serve.registry import ArtifactError, ModelRegistry

_STOP = object()


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


# --------------------------------------------------------------- admission


class TokenBucket:
    """Per-tenant admission rate limiter: ``rate`` tokens/second refill up
    to ``burst``; ``allow()`` spends one.  ``rate <= 0`` disables the
    quota (always allows).  Thread-safe — HTTP handler threads race."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def allow(self, amount: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= amount:
                self._tokens -= amount
                return True
            return False

    def retry_after_s(self) -> float:
        """Seconds until one token exists — the 429 Retry-After hint."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            missing = max(0.0, 1.0 - self._tokens)
        return missing / self.rate


# ------------------------------------------------------------ program LRU


class ProgramCache:
    """Entry- and byte-budgeted LRU of compiled programs.

    ``get_or_build(key, builder, est_bytes)`` is the whole contract (the
    engine duck-types against it): a hit moves the entry to the MRU end;
    a miss calls ``builder()`` OUTSIDE the lock (jit construction must
    not serialize the request path) and inserts, then evicts from the
    LRU end until both budgets hold.  The just-inserted entry is never
    evicted — a program the caller is about to dispatch must survive its
    own insertion even when ``est_bytes`` alone exceeds the budget.

    In-flight builds are coordinated: the first thread to miss a key
    registers a build event under the lock and runs ``builder()``; any
    other thread missing the SAME key waits on that event and then
    re-reads the cache instead of compiling a duplicate.  That is what
    keeps the sanitizer compile budget (one compile per program name) an
    invariant across N concurrent batch workers, not just per worker."""

    def __init__(self, max_entries: int = 64,
                 max_bytes: int = 256 * 1024 * 1024):
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # key -> (program, bytes)
        self._building: dict = {}  # key -> threading.Event (build in flight)
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get_or_build(self, key, builder: Callable, est_bytes: int = 0):
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    return entry[0]
                in_flight = self._building.get(key)
                if in_flight is None:
                    done = threading.Event()
                    self._building[key] = done
                    break
            # another worker is compiling this key right now: wait for it
            # to land, then re-read (on builder failure the loop retries
            # the build here instead of propagating a foreign exception)
            in_flight.wait()
        try:
            program = builder()
        except BaseException:
            with self._lock:
                del self._building[key]
            done.set()
            raise
        with self._lock:
            del self._building[key]
            self.misses += 1
            self._entries[key] = (program, int(est_bytes))
            self._bytes += int(est_bytes)
            while self._entries and len(self._entries) > 1 and (
                    len(self._entries) > self.max_entries
                    or self._bytes > self.max_bytes):
                _, (_, b) = self._entries.popitem(last=False)
                self._bytes -= b
                self.evictions += 1
        done.set()
        return program

    def keys(self) -> list:
        with self._lock:
            return list(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


# ------------------------------------------------------------- fleet state


@dataclass
class TenantRuntime:
    """One hot tenant: its registry, its engine (sharing the fleet program
    cache), and its admission token bucket."""

    name: str
    root: str
    registry: ModelRegistry
    engine: SamplingEngine
    bucket: TokenBucket
    # canary promotion gate (None under the default immediate policy)
    gate: object = None


class FleetRegistry:
    """Ordered map of hot tenants over one shared :class:`ProgramCache`.

    ``load`` constructs the tenant's ModelRegistry + SamplingEngine (the
    model loads eagerly — a tenant is either hot or absent, never
    half-loaded) and journals ``fleet_load``; ``evict`` drops the tenant
    and journals ``fleet_evict``.  Compiled programs are NOT dropped on
    evict: other tenants may share them, and orphaned ones age out of
    the LRU."""

    def __init__(self, program_cache: Optional[ProgramCache] = None,
                 quota_rps: float = 0.0, quota_burst: Optional[float] = None,
                 max_chunk_steps: int = 128,
                 allow_meta_mismatch: bool = False,
                 promote: str = "immediate", log=print):
        self.cache = program_cache if program_cache is not None \
            else ProgramCache()
        self.quota_rps = float(quota_rps)
        self.quota_burst = quota_burst
        self.max_chunk_steps = int(max_chunk_steps)
        self.allow_meta_mismatch = allow_meta_mismatch
        self.promote = str(promote)
        self._log = log
        self._lock = threading.RLock()
        self._tenants: OrderedDict = OrderedDict()  # name -> TenantRuntime

    def load(self, name: str, root: str) -> TenantRuntime:
        """Load (or replace) tenant ``name`` from artifact ``root``.
        Raises :class:`ArtifactError` when nothing loadable exists —
        the fleet's state is unchanged in that case."""
        registry = ModelRegistry(root,
                                 allow_meta_mismatch=self.allow_meta_mismatch,
                                 log=self._log)
        model = registry.get()  # eager: fail here, not on first request
        engine = SamplingEngine(model, max_chunk_steps=self.max_chunk_steps,
                                program_cache=self.cache)
        gate = None
        if self.promote == "canary":
            from fed_tgan_tpu.serve.canary import CanaryGate

            gate = CanaryGate(registry, engine, tenant=name, log=self._log)
        rt = TenantRuntime(
            name=name, root=str(root), registry=registry, engine=engine,
            bucket=TokenBucket(self.quota_rps, self.quota_burst),
            gate=gate,
        )
        with self._lock:
            self._tenants[name] = rt
        _emit_event("fleet_load", tenant=name, model_id=model.model_id,
                    root=str(root))
        self._log(f"fleet: loaded tenant {name!r} "
                  f"(model {model.model_id})")
        return rt

    def evict(self, name: str) -> bool:
        with self._lock:
            rt = self._tenants.pop(name, None)
        if rt is None:
            return False
        _emit_event("fleet_evict", tenant=name,
                    model_id=rt.registry.get().model_id)
        self._log(f"fleet: evicted tenant {name!r}")
        return True

    def get(self, name: str) -> Optional[TenantRuntime]:
        with self._lock:
            return self._tenants.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._tenants)

    def items(self) -> List[Tuple[str, TenantRuntime]]:
        with self._lock:
            return list(self._tenants.items())

    def sole(self) -> Optional[TenantRuntime]:
        """The single hot tenant, when exactly one is — the ``/sample``
        alias only routes unambiguously."""
        with self._lock:
            if len(self._tenants) == 1:
                return next(iter(self._tenants.values()))
            return None


# ----------------------------------------------------------- request path


@dataclass
class _FleetRequest:
    tenant: str
    n: int
    seed: int
    offset: int
    condition: int | None
    header: bool
    enqueued_at: float = field(default_factory=time.time)
    done: threading.Event = field(default_factory=threading.Event)
    result: bytes | None = None
    error: str | None = None
    status: int = 500
    # request-scoped trace context (see service._Request): stamped by
    # the worker at pop time, stage seconds accumulate host-side only
    popped_at: float = 0.0
    stages: dict = field(default_factory=dict)
    # completion callback (set BEFORE submit, called after done.set()):
    # the asyncio front door bridges it onto its event loop instead of
    # parking a thread on the event
    on_done: Callable | None = None


@dataclass
class Response:
    """One materialized HTTP response from :meth:`FleetService.route`.

    ``body`` is either ``bytes`` or a list of byte segments — the asyncio
    front door streams a segment list with ``writelines`` (no join); the
    stdlib adapter joins (one ``send`` per response is what its
    unbuffered ``wfile`` wants)."""

    status: int
    body: Union[bytes, list]
    ctype: str = "application/json"
    headers: Optional[dict] = None

    def body_bytes(self) -> bytes:
        return self.body if isinstance(self.body, bytes) \
            else b"".join(self.body)

    def content_length(self) -> int:
        return len(self.body) if isinstance(self.body, bytes) \
            else sum(len(s) for s in self.body)


@dataclass
class Pending:
    """A routed request parked on the worker queue: the HTTP layer waits
    for ``req.done`` (or bridges ``req.on_done``) and then renders
    :meth:`FleetService.response_for`."""

    req: _FleetRequest


def _json_response(status: int, obj: dict,
                   headers: Optional[dict] = None) -> Response:
    return Response(status, json.dumps(obj).encode(),
                    "application/json", headers)


class _ScratchPool:
    """Per-worker donated-scratch rotation (at most 2 dead buffers per
    shape, same discipline as the engine's pool).  Each batch worker owns
    one, so concurrent lane dispatches never contend on a shared scratch
    lock — the lock below is uncontended by construction but still taken
    (handler threads never touch these; J05 keeps us honest)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bufs: dict = {}

    def take(self, shape: tuple):
        import jax.numpy as jnp

        with self._lock:
            bufs = self._bufs.get(shape)
            if bufs:
                return bufs.pop()
        return jnp.zeros(shape, jnp.float32)

    def give(self, buf) -> None:
        shape = tuple(buf.shape)
        with self._lock:
            bufs = self._bufs.setdefault(shape, [])
            if len(bufs) < 2:
                bufs.append(buf)


@dataclass
class _Member:
    """One request bound to the tenant snapshot its batch formed under."""

    req: _FleetRequest
    rt: TenantRuntime
    snap: EngineSnapshot
    first_step: int
    skip: int


def _stack_pytrees(trees: list):
    """Stack a list of structurally-identical pytrees leaf-wise along a
    new leading lane axis.  Unflattens with the FIRST tree's treedef, so
    aux-data equality across tenants (e.g. spec objects that compare by
    identity) is never consulted — group membership already guarantees
    trace-equal structure."""
    import jax
    import jax.numpy as jnp

    leaves0, treedef = jax.tree.flatten(trees[0])
    cols = [jax.tree.flatten(t)[0] for t in trees]
    stacked = [jnp.stack([col[i] for col in cols])
               for i in range(len(leaves0))]
    return jax.tree.unflatten(treedef, stacked)


class FleetService:
    """N coalescing batch workers over a sharded bounded queue.

    ``workers=1`` (the default) preserves the PR 9 single-worker shape
    exactly; higher counts shard the queue round-robin and run
    independent batch workers against the shared :class:`ProgramCache`
    and per-worker scratch pools.  ``coalesce_window_s`` bounds how long
    a worker holds a forming batch waiting for more traffic;
    ``row_pool`` (a :class:`~.pool.RowPool`) short-circuits covered
    requests before the queue; ``http_mode`` picks the front door
    (``"asyncio"`` or the legacy ``"threaded"``)."""

    def __init__(self, fleet: FleetRegistry, host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 16, queue_size: int = 128,
                 max_lanes: int = 8, queue_share: float = 0.5,
                 request_timeout_s: float = 120.0,
                 reload_interval_s: float = 5.0, workers: int = 1,
                 coalesce_window_s: float = 0.0, row_pool=None,
                 http_mode: str = "threaded", log=print):
        self.fleet = fleet
        self.metrics = FleetMetrics()
        self.max_batch = max(1, int(max_batch))
        self.max_lanes = max(1, int(max_lanes))
        self.queue_share = min(1.0, max(0.0, float(queue_share)))
        self.request_timeout_s = request_timeout_s
        self.reload_interval_s = reload_interval_s
        self.workers = max(1, int(workers))
        self.coalesce_window_s = max(0.0, float(coalesce_window_s))
        self.row_pool = row_pool
        if http_mode not in ("threaded", "asyncio"):
            raise ValueError(f"http_mode={http_mode!r}: "
                             "want 'threaded' or 'asyncio'")
        self.http_mode = http_mode
        self._log = log
        self._host, self._port = host, port
        # one queue shard per worker: admission round-robins across
        # shards, each worker drains only its own — no shared queue lock
        # on the hot path, aggregate capacity stays `queue_size`
        total = max(1, int(queue_size))
        per = -(-total // self.workers)
        self._queue_size = per * self.workers
        self._queues: List[queue.Queue] = [
            queue.Queue(maxsize=per) for _ in range(self.workers)]
        self._rr = itertools.count()
        self._drain_rate = DrainRate()
        self._draining = threading.Event()
        self._last_reload_check = time.monotonic()
        # first stage summary goes out with the first batch
        self._last_stage_emit = float("-inf")
        # per-tenant in-flight counts (admission fairness) + shed
        # accumulators for the rate-limited tenant_shed journal events
        self._adm_lock = threading.Lock()
        self._inflight: dict = {}
        self._shed_acc: dict = {}
        self._scratch_pools = [_ScratchPool() for _ in range(self.workers)]
        self._httpd: ThreadingHTTPServer | None = None
        self._frontdoor = None
        self._worker_threads: List[threading.Thread] = []
        self._serve_thread: threading.Thread | None = None

    # ----------------------------------------------------------- lifecycle

    def start_workers(self) -> "FleetService":
        """Start only the batch workers (no HTTP, no pool filler) — the
        deterministic seam: tests and the doctor enqueue a backlog first,
        then start workers and observe the batching that MUST happen."""
        self._worker_threads = [
            threading.Thread(target=self._worker, args=(i,),
                             name=f"fleet-batch-worker-{i}", daemon=True)
            for i in range(self.workers)
        ]
        for t in self._worker_threads:
            t.start()
        return self

    def start(self) -> "FleetService":
        self.start_workers()
        if self.row_pool is not None:
            self.row_pool.start()
        if self.http_mode == "asyncio":
            from fed_tgan_tpu.serve.frontdoor import AsyncFrontDoor

            self._frontdoor = AsyncFrontDoor(
                self, host=self._host, port=self._port,
                request_timeout_s=self.request_timeout_s)
            self._frontdoor.start()
        else:
            handler = _make_fleet_handler(self)
            self._httpd = ThreadingHTTPServer((self._host, self._port),
                                              handler)
            self._httpd.daemon_threads = True
            self._serve_thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.05},
                name="fleet-http", daemon=True)
            self._serve_thread.start()
        return self

    @property
    def port(self) -> int:
        if self._frontdoor is not None:
            return self._frontdoor.port
        assert self._httpd is not None, "start() first"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def shutdown(self, drain: bool = True) -> None:
        self._draining.set()
        if self.row_pool is not None:
            self.row_pool.stop()
        if not drain:
            for q in self._queues:
                while True:
                    try:
                        req = q.get_nowait()
                    except queue.Empty:
                        break
                    if req is not _STOP:
                        req.error, req.status = "server shutting down", 503
                        self._finish(req)
        for q in self._queues:
            try:
                q.put_nowait(_STOP)
            except queue.Full:
                pass  # that worker is alive and draining; _draining exits it
        for t in self._worker_threads:
            t.join(timeout=max(self.request_timeout_s, 10))
        if self._frontdoor is not None:
            self._frontdoor.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10)

    # ----------------------------------------------------------- admission

    def tenant_cap(self) -> int:
        """Max in-flight requests one tenant may hold — its fair share of
        the bounded queue (all shards combined)."""
        return max(1, int(self._queue_size * self.queue_share))

    def submit(self, rt: TenantRuntime,
               req: _FleetRequest) -> Optional[str]:
        """Admit + enqueue; returns None on success or the shed reason
        (``"quota"`` -> 429, ``"capacity"`` -> 503)."""
        if self._draining.is_set():
            return "capacity"
        if not rt.bucket.allow():
            self._shed(req.tenant, "quota")
            return "quota"
        return self.submit_admitted(req)

    def submit_admitted(self, req: _FleetRequest) -> Optional[str]:
        """Capacity-only admission (the quota token was already spent —
        the route path charges it before the row-pool lookup)."""
        if self._draining.is_set():
            return "capacity"
        cap = self.tenant_cap()
        with self._adm_lock:
            over_cap = self._inflight.get(req.tenant, 0) >= cap
            if not over_cap:
                self._inflight[req.tenant] = \
                    self._inflight.get(req.tenant, 0) + 1
        if over_cap:  # shed OUTSIDE _adm_lock: _shed re-acquires it
            self._shed(req.tenant, "capacity")
            return "capacity"
        # round-robin across shards; on a full shard, try the rest before
        # shedding (a single hot shard must not fake global exhaustion)
        start = next(self._rr) % self.workers
        for j in range(self.workers):
            try:
                self._queues[(start + j) % self.workers].put_nowait(req)
                return None
            except queue.Full:
                continue
        with self._adm_lock:
            self._inflight[req.tenant] -= 1
        self._shed(req.tenant, "capacity")
        return "capacity"

    def capacity_retry_after(self) -> float:
        """503 Retry-After: queued work divided by the fleet's measured
        aggregate drain rate (scales with the worker count), clamped to
        a sane band; before any batch has completed, fall back to 1 s."""
        rate = self._drain_rate.rate()
        if rate <= 0.0:
            return 1.0
        return min(30.0, max(0.05, (self.queue_depth() + 1) / rate))

    def _shed(self, tenant: str, reason: str) -> None:
        self.metrics.record_shed(tenant, reason)
        # journal at most ~1 event/second/tenant, carrying counts — a
        # shed storm must not turn the journal into a per-request log
        with self._adm_lock:
            acc = self._shed_acc.setdefault(
                tenant, {"quota": 0, "capacity": 0, "last": 0.0})
            acc[reason] += 1
            now = time.monotonic()
            if now - acc["last"] < 1.0:
                return
            quota, capacity = acc["quota"], acc["capacity"]
            acc["quota"] = acc["capacity"] = 0
            acc["last"] = now
        _emit_event("tenant_shed", tenant=tenant, count=quota + capacity,
                    quota=quota, capacity=capacity)

    def queue_depth(self) -> int:
        return sum(q.qsize() for q in self._queues)

    def _finish(self, req: _FleetRequest) -> None:
        with self._adm_lock:
            n = self._inflight.get(req.tenant, 0)
            if n > 0:
                self._inflight[req.tenant] = n - 1
        req.done.set()
        cb = req.on_done
        if cb is not None:
            cb(req)

    def _fail(self, req: _FleetRequest, status: int, msg: str) -> None:
        req.error, req.status = msg, status
        self.metrics.record_error(req.tenant)
        self._finish(req)

    # -------------------------------------------------------------- worker

    def _worker(self, wid: int = 0) -> None:
        q = self._queues[wid]
        while True:
            try:
                item = q.get(timeout=0.05)
            except queue.Empty:
                if self._draining.is_set():
                    return
                if wid == 0:  # one reload poller is enough for the fleet
                    self._maybe_reload()
                continue
            if item is _STOP:
                self._process(self._drain_remaining(q), wid)
                self._emit_stages(force=True)
                return
            item.popped_at = time.time()
            batch = [item]
            stop = False
            # occupancy-driven admission: once a batch is forming, hold it
            # for at most coalesce_window_s while the queue is quiet —
            # under closed-loop load the waiting clients land in THIS
            # batch instead of each riding a singleton dispatch
            deadline = (time.monotonic() + self.coalesce_window_s
                        if self.coalesce_window_s > 0 else 0.0)
            while len(batch) < self.max_batch:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    wait = deadline - time.monotonic()
                    if wait <= 0 or self._draining.is_set():
                        break
                    try:
                        nxt = q.get(timeout=wait)
                    except queue.Empty:
                        break
                if nxt is _STOP:
                    stop = True
                    break
                nxt.popped_at = time.time()
                batch.append(nxt)
            self._process(batch, wid)
            if stop:
                self._process(self._drain_remaining(q), wid)
                self._emit_stages(force=True)
                return
            if wid == 0:
                self._maybe_reload()

    def _drain_remaining(self, q: queue.Queue) -> list:
        batch = []
        while True:
            try:
                req = q.get_nowait()
            except queue.Empty:
                return batch
            if req is not _STOP:
                req.popped_at = time.time()
                batch.append(req)

    def _process(self, batch: list, wid: int = 0) -> None:
        if not batch:
            return
        self.metrics.record_batch(len(batch))
        # worker-sampled gauges: what's still queued behind this batch,
        # and lane occupancy (0 unless a coalesced dispatch fires below)
        self.metrics.set_queue_depth(self.queue_depth())
        self.metrics.set_lanes_occupied(0)
        # bind every request to ONE tenant snapshot for the whole batch
        # (reload-under-fire safety), then group single-chunk requests by
        # bucket key: same (steps, conditional, layout-sig) => same
        # compiled program => one vmapped dispatch for the lot
        groups: dict = {}
        singles: list = []
        for req in batch:
            rt = self.fleet.get(req.tenant)
            if rt is None:
                self._fail(req, 410, f"tenant {req.tenant!r} was evicted")
                continue
            snap = rt.engine.snapshot()
            B = snap.cfg.batch_size
            first_step, skip = divmod(req.offset, B)
            total_steps = -(-(skip + req.n) // B)
            plan = rt.engine._chunk_plan(first_step, total_steps)
            member = _Member(req, rt, snap, first_step, skip)
            if len(plan) == 1 and self.max_lanes > 1:
                key = (plan[0][1], req.condition is not None, snap.sig)
                groups.setdefault(key, []).append(member)
            else:
                singles.append(member)
        for (steps, conditional, _sig), members in groups.items():
            if len(members) == 1:
                singles.append(members[0])
                continue
            for i in range(0, len(members), self.max_lanes):
                self._dispatch_lanes(steps, conditional,
                                     members[i:i + self.max_lanes],
                                     self._scratch_pools[wid])
        for member in singles:
            self._run_single(member)
        self._drain_rate.note(len(batch))
        self.metrics.set_fleet_state(len(self.fleet.names()),
                                     self.fleet.cache.stats())
        self._emit_stages()

    @staticmethod
    def _stamp_wait(req: _FleetRequest, t_start: float) -> None:
        """queue_wait ends at the pop, batch_form when this request's
        own processing starts (the wait behind earlier batch members
        lands in batch_form — the stages sum to the server latency)."""
        popped = req.popped_at or t_start
        req.stages["queue_wait"] = max(0.0, popped - req.enqueued_at)
        req.stages["batch_form"] = max(0.0, t_start - popped)

    def _emit_stages(self, force: bool = False) -> None:
        """Rate-limited per-tenant ``serve_stages`` journal summaries."""
        now = time.monotonic()
        if not force and now - self._last_stage_emit < 5.0:
            return
        snaps = self.metrics.stage_snapshots()
        if snaps:
            self._last_stage_emit = now
            for tenant, stages in snaps.items():
                _emit_event("serve_stages", tenant=tenant, stages=stages)

    def _run_single(self, m: _Member) -> None:
        req = m.req
        self._stamp_wait(req, time.time())
        try:
            req.result = m.rt.engine.sample_csv_bytes(
                req.n, seed=req.seed, offset=req.offset,
                condition=req.condition, header=req.header, snap=m.snap,
                stages=req.stages,
            )
            req.status = 200
            self.metrics.record_request(req.tenant,
                                        time.time() - req.enqueued_at, req.n)
            self.metrics.record_stages(req.tenant, req.stages)
            self._finish(req)
        except Exception as exc:  # noqa: BLE001 — becomes the 500 body
            self._fail(req, 500, repr(exc))

    # --------------------------------------------------------- lane engine

    def _lane_program(self, snap: EngineSnapshot, steps: int,
                      conditional: bool, lanes: int):
        key = ("lanes", steps, conditional, lanes, snap.sig)

        def build():
            import jax

            from fed_tgan_tpu.runtime.precision import resolve_precision

            run = build_bucket_program(snap.spec, snap.cfg, snap.layout,
                                       steps, conditional, tag=snap.tag)

            def lane_run(params_g, state_g, cond, key, start, pos, tables,
                         out):
                return jax.vmap(run)(params_g, state_g, cond, key, start,
                                     pos, tables, out)

            prec = resolve_precision(
                getattr(snap.cfg, "precision", "f32")).name
            lane_run.__name__ = fleet_bucket_name(steps, conditional, prec,
                                                  lanes, snap.tag)
            lane_run.__qualname__ = lane_run.__name__
            return jax.jit(lane_run, donate_argnums=7)

        B = snap.cfg.batch_size
        est = lanes * steps * B * (snap.spec.dim + len(snap.layout)) * 4
        return self.fleet.cache.get_or_build(key, build, est_bytes=est)

    def _dispatch_lanes(self, steps: int, conditional: bool,
                        members: list,
                        scratch: Optional[_ScratchPool] = None) -> None:
        """One vmapped device dispatch answering every member: per-tenant
        params/state/cond/tables stacked on a lane axis, lane count padded
        to a power of two (bounded program set) by repeating lane 0, whose
        extra output is simply dropped."""
        import jax
        import jax.numpy as jnp

        snap0 = members[0].snap
        lanes = min(_pow2(len(members)), self.max_lanes)
        padded = list(members) + [members[0]] * (lanes - len(members))
        if scratch is None:
            scratch = self._scratch_pools[0]
        t_start = time.time()
        for m in members:
            self._stamp_wait(m.req, t_start)
        t_dispatch = time.perf_counter()
        try:
            prog = self._lane_program(snap0, steps, conditional, lanes)
            B = snap0.cfg.batch_size
            synths = [m.snap.model.synth for m in padded]
            params = _stack_pytrees([s.params_g for s in synths])
            state = _stack_pytrees([s.state_g for s in synths])
            cond = _stack_pytrees([s.cond for s in synths])
            keys = jnp.stack([
                jax.random.key(m.req.seed + s.key_offset)
                for m, s in zip(padded, synths)])
            starts = np.asarray([m.first_step for m in padded], np.int32)
            poss = np.asarray(
                [m.req.condition if m.req.condition is not None else 0
                 for m in padded], np.int32)
            tables = _stack_pytrees([m.snap.tables for m in padded])
            buf = scratch.take((lanes, steps * B, len(snap0.layout)))
            with hot_region(f"serve.fleet[{steps}"
                            f"{'c' if conditional else ''}x{lanes}]"):
                res = prog(params, state, cond, keys, starts, poss, tables,
                           buf)
            host = np.asarray(res)
            scratch.give(res)
        except Exception as exc:  # noqa: BLE001 — fail the whole lane group
            for m in members:
                self._fail(m.req, 500, repr(exc))
            return
        # the whole coalesced device round (stack -> program -> host
        # copy) is each member's "dispatch": they all waited on it
        dispatch_s = time.perf_counter() - t_dispatch
        for m in members:
            m.req.stages["dispatch"] = dispatch_s
        self.metrics.record_lane_dispatch(len(members))
        self.metrics.set_lanes_occupied(len(members))
        from fed_tgan_tpu.data.csvio import csv_bytes
        from fed_tgan_tpu.data.decode import decode_matrix

        for i, m in enumerate(members):
            req = m.req
            try:
                t_decode = time.perf_counter()
                mat = host[i, m.skip:m.skip + req.n]
                frame = decode_matrix(mat, m.snap.model.meta,
                                      m.snap.model.encoders)
                t_ser = time.perf_counter()
                out = csv_bytes(frame)
                if not req.header:
                    out = out.split(b"\n", 1)[1]
                req.stages["decode"] = t_ser - t_decode
                req.stages["serialize"] = time.perf_counter() - t_ser
                req.result, req.status = out, 200
                self.metrics.record_request(
                    req.tenant, time.time() - req.enqueued_at, req.n)
                self.metrics.record_stages(req.tenant, req.stages)
                self._finish(req)
            except Exception as exc:  # noqa: BLE001
                self._fail(req, 500, repr(exc))

    # -------------------------------------------------------------- reload

    def _maybe_reload(self) -> None:
        if self.reload_interval_s <= 0:
            return
        now = time.monotonic()
        if now - self._last_reload_check < self.reload_interval_s:
            return
        self._last_reload_check = now
        for name, rt in self.fleet.items():
            try:
                if rt.gate is not None:
                    decision = rt.gate.consider()
                    if decision is None:
                        continue
                    self.metrics.quality.record_scores(
                        name, decision.get("avg_jsd"),
                        decision.get("avg_wd"))
                    self.metrics.quality.record_decision(
                        name, bool(decision.get("promoted")))
                    if not decision.get("promoted"):
                        continue  # old model keeps serving untouched
                if rt.gate is not None or rt.registry.maybe_reload():
                    kept = rt.engine.adopt(rt.registry.get())
                    if self.row_pool is not None:
                        # pooled segments belong to the OLD model; a hit
                        # must never serve rows the new model wouldn't
                        self.row_pool.invalidate(name)
                    self.metrics.record_reload(name)
                    _emit_event("serve_reload", tenant=name,
                                model_id=rt.registry.get().model_id,
                                programs_kept=bool(kept))
                    self._log(
                        f"fleet: tenant {name!r} now serving model "
                        f"{rt.registry.get().model_id} "
                        f"({'programs kept' if kept else 'programs rebuilt'})"
                    )
            except Exception as exc:  # noqa: BLE001 — reload never kills serving
                self._log(f"fleet: reload check failed for {name!r} "
                          f"({exc!r})")

    # -------------------------------------------------------------- status

    def fleet_status(self) -> dict:
        tenants = []
        for name, rt in self.fleet.items():
            model = rt.registry.get()
            with self._adm_lock:
                inflight = self._inflight.get(name, 0)
            entry = {
                "name": name,
                "root": rt.root,
                "model_id": model.model_id,
                "model_name": model.artifact.name,
                "inflight": inflight,
                **self.metrics.tenant_snapshot(name),
            }
            if rt.gate is not None:
                entry["promotion"] = rt.gate.status()
            tenants.append(entry)
        return {
            "status": "draining" if self._draining.is_set() else "ok",
            "tenants": tenants,
            "cache": self.fleet.cache.stats(),
            "queue_depth": self.queue_depth(),
            "tenant_cap": self.tenant_cap(),
            "workers": self.workers,
            "coalesce_window_s": self.coalesce_window_s,
            "row_pool": (self.row_pool.stats()
                         if self.row_pool is not None else None),
        }

    # ------------------------------------------------------------- routing

    @staticmethod
    def _tenant_for(path: str) -> Optional[str]:
        """``/t/<tenant>/sample`` -> tenant name, else None."""
        parts = path.split("/")
        if len(parts) == 4 and parts[1] == "t" and parts[3] == "sample":
            return urllib.parse.unquote(parts[2])
        return None

    def route(self, method: str, path: str, params: dict,
              on_done: Optional[Callable] = None
              ) -> Union[Response, Pending]:
        """The single route table both front doors adapt (the stdlib
        handler and the asyncio server render the SAME responses, so the
        two HTTP layers cannot drift).  ``params`` is the merged query/
        JSON-body dict; ``on_done`` is attached to a sampling request
        BEFORE it is enqueued, so an event-loop waiter never races the
        worker's completion."""
        if method == "GET":
            if path == "/healthz":
                self.metrics.set_fleet_state(len(self.fleet.names()),
                                             self.fleet.cache.stats())
                self.metrics.set_pool_state(
                    self.row_pool.stats()
                    if self.row_pool is not None else None)
                return _json_response(200, {
                    "status": "draining" if self._draining.is_set()
                    else "ok",
                    "tenants": self.fleet.names(),
                    **self.metrics.snapshot(self.queue_depth()),
                })
            if path == "/metrics":
                self.metrics.set_fleet_state(len(self.fleet.names()),
                                             self.fleet.cache.stats())
                self.metrics.set_pool_state(
                    self.row_pool.stats()
                    if self.row_pool is not None else None)
                text = self.metrics.render_prometheus(self.queue_depth())
                return Response(200, text.encode(),
                                "text/plain; version=0.0.4")
            if path == "/fleet":
                return _json_response(200, self.fleet_status())
        elif method == "POST" and path == "/fleet":
            return self._route_admin(params)
        tenant = self._tenant_for(path)
        if tenant is None and path == "/sample":
            rt = self.fleet.sole()
            if rt is None:
                return _json_response(400, {
                    "error": "/sample needs exactly one hot tenant; "
                             "use /t/<tenant>/sample",
                    "tenants": self.fleet.names()})
            tenant = rt.name
        if tenant is None:
            return _json_response(404, {"error": f"no route {path}"})
        return self._route_sample(tenant, params, on_done)

    def _route_admin(self, params: dict) -> Response:
        action = params.get("action")
        name = params.get("tenant")
        if action == "load":
            if not name or not params.get("root"):
                return _json_response(400,
                                      {"error": "load needs {tenant, root}"})
            try:
                rt = self.fleet.load(str(name), str(params["root"]))
            except ArtifactError as exc:
                return _json_response(400, {"error": str(exc)})
            return _json_response(200, {
                "loaded": name, "model_id": rt.registry.get().model_id})
        if action == "evict":
            if not name:
                return _json_response(400, {"error": "evict needs {tenant}"})
            if self.fleet.evict(str(name)):
                if self.row_pool is not None:
                    self.row_pool.invalidate(str(name))
                return _json_response(200, {"evicted": name})
            return _json_response(404, {"error": f"no tenant {name!r}",
                                        "tenants": self.fleet.names()})
        return _json_response(400, {
            "error": f"unknown action {action!r} (want load or evict)"})

    def _route_sample(self, tenant: str, params: dict,
                      on_done: Optional[Callable]
                      ) -> Union[Response, Pending]:
        rt = self.fleet.get(tenant)
        if rt is None:
            return _json_response(404, {"error": f"no tenant {tenant!r}",
                                        "tenants": self.fleet.names()})
        try:
            n = int(params.get("rows", params.get("n", 0)))
            seed = int(params.get("seed", 0))
            offset = int(params.get("offset", 0))
            header = str(params.get("header", "1")) not in ("0", "false")
            if n <= 0:
                raise ValueError(f"rows={n}: need a positive row count")
            if offset < 0:
                raise ValueError(f"offset={offset}: must be >= 0")
        except (TypeError, ValueError) as exc:
            return _json_response(400, {"error": str(exc)})
        condition = None
        column = params.get("column")
        if column:
            try:
                condition = rt.engine.resolve_condition(
                    column, params.get("value"))
            except ConditionError as exc:
                return _json_response(400, {"error": str(exc)})
        if self._draining.is_set():
            return _json_response(
                503, {"error": "draining"},
                headers={"Retry-After": "1"})
        # quota FIRST: a pool hit still spends the tenant's token, so a
        # quota-limited tenant is pinned at its configured rate no matter
        # how cacheable its traffic is
        t_admit = time.time()
        if not rt.bucket.allow():
            self._shed(tenant, "quota")
            retry = max(rt.bucket.retry_after_s(), 0.05)
            return _json_response(
                429, {"error": f"tenant {tenant!r} over quota"},
                headers={"Retry-After": f"{retry:.2f}"})
        if self.row_pool is not None:
            segments = self.row_pool.get(tenant, seed, offset, n,
                                         condition, header)
            if segments is not None:
                self.metrics.record_pool_hit(
                    tenant, time.time() - t_admit, n)
                return Response(200, segments, "text/csv")
        req = _FleetRequest(tenant=tenant, n=n, seed=seed, offset=offset,
                            condition=condition, header=header)
        req.on_done = on_done
        shed = self.submit_admitted(req)
        if shed is not None:
            return _json_response(
                503,
                {"error": "draining" if self._draining.is_set()
                 else "at capacity"},
                headers={
                    "Retry-After": f"{self.capacity_retry_after():.2f}"},
            )
        return Pending(req)

    @staticmethod
    def response_for(req: _FleetRequest) -> Response:
        """Render a finished (or timed-out) sampling request."""
        if not req.done.is_set():
            return _json_response(504,
                                  {"error": "request timed out in queue"})
        if req.status == 200 and req.result is not None:
            return Response(200, req.result, "text/csv")
        return _json_response(req.status, {"error": req.error or "failed"})


# ----------------------------------------------------------------- HTTP


def _make_fleet_handler(service: FleetService):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # stdlib's unbuffered wfile writes headers and body as separate
        # TCP segments; without NODELAY, Nagle + delayed ACK turns every
        # response into a flat ~40 ms stall (the whole pre-PR-15 serving
        # "capacity gap" was this artifact, not compute)
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send_response(self, r: Response) -> None:
            self.send_response(r.status)
            self.send_header("Content-Type", r.ctype)
            self.send_header("Content-Length", str(r.content_length()))
            for k, v in (r.headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            # one send: the threaded adapter joins segment bodies (each
            # wfile.write is a raw syscall here; streaming segments is
            # the asyncio front door's job)
            self.wfile.write(r.body_bytes())

        def _dispatch(self, method: str, params: dict) -> None:
            parsed = urllib.parse.urlsplit(self.path)
            routed = service.route(method, parsed.path, params)
            if isinstance(routed, Pending):
                routed.req.done.wait(timeout=service.request_timeout_s)
                routed = service.response_for(routed.req)
            self._send_response(routed)

        def do_GET(self):
            parsed = urllib.parse.urlsplit(self.path)
            params = {k: v[-1] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
            self._dispatch("GET", params)

        def do_POST(self):
            try:
                length = int(self.headers.get("Content-Length", "0"))
                params = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(params, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, json.JSONDecodeError) as exc:
                self._send_response(_json_response(
                    400, {"error": f"bad JSON body: {exc}"}))
                return
            self._dispatch("POST", params)

    return Handler


# ------------------------------------------------------------------- CLI


def fleet_main(argv=None) -> int:
    """``fed-tgan-tpu fleet name=artifact-dir [name=dir ...] [flags]``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="fed_tgan_tpu fleet",
        description="serve MANY model artifacts over one HTTP server with "
                    "cross-tenant program sharing and per-tenant quotas")
    ap.add_argument("tenants", nargs="+", metavar="NAME=DIR",
                    help="tenant name and its artifact root (same "
                         "resolution as --sample-from)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7799,
                    help="0 = ephemeral (printed at startup)")
    ap.add_argument("--max-batch", type=int, default=16,
                    help="max requests coalesced per worker cycle")
    ap.add_argument("--queue-size", type=int, default=128,
                    help="bounded request queue; full = shed with 503")
    ap.add_argument("--max-lanes", type=int, default=8,
                    help="max tenants coalesced into one vmapped dispatch "
                         "(1 disables cross-tenant coalescing)")
    ap.add_argument("--queue-share", type=float, default=0.5,
                    help="fraction of the queue one tenant may hold "
                         "in-flight before 503 (fair shedding)")
    ap.add_argument("--quota-rps", type=float, default=0.0,
                    help="per-tenant admission quota in requests/second "
                         "(0 = unlimited); over-quota requests get 429")
    ap.add_argument("--quota-burst", type=float, default=None,
                    help="per-tenant token-bucket burst (default: "
                         "max(quota-rps, 1))")
    ap.add_argument("--cache-entries", type=int, default=64,
                    help="compiled-program LRU entry budget")
    ap.add_argument("--cache-mb", type=float, default=256.0,
                    help="compiled-program LRU byte budget (estimated)")
    ap.add_argument("--workers", type=int, default=1,
                    help="batch workers draining a sharded queue (the "
                         "shared program cache still compiles each bucket "
                         "once across all of them)")
    ap.add_argument("--coalesce-window", type=float, default=0.0,
                    help="seconds a worker holds a forming batch for more "
                         "traffic (occupancy-driven admission; 0 = "
                         "dispatch immediately)")
    ap.add_argument("--http", choices=("asyncio", "threaded"),
                    default="asyncio",
                    help="front door: asyncio event loop with zero-copy "
                         "segment streaming, or the legacy threaded "
                         "stdlib server")
    ap.add_argument("--row-pool-chunks", type=int, default=8,
                    help="pre-generated row-pool chunks kept per hot "
                         "(tenant, seed, condition) stream "
                         "(0 disables the pool)")
    ap.add_argument("--row-pool-chunk-rows", type=int, default=2048,
                    help="rows per pre-generated pool chunk")
    ap.add_argument("--request-timeout", type=float, default=120.0,
                    help="seconds a request may wait before 504")
    ap.add_argument("--reload-interval", type=float, default=5.0,
                    help="seconds between per-tenant hot-reload polls "
                         "(0 = never)")
    ap.add_argument("--promote", choices=("canary", "immediate"),
                    default="immediate",
                    help="new-generation policy: immediate = hot-swap any "
                         "loadable checkpoint (default); canary = shadow-"
                         "score each tenant's candidate against its "
                         "reference statistics and promote only inside "
                         "the quality budgets in obs/budgets.json")
    ap.add_argument("--allow-meta-mismatch", action="store_true",
                    help="serve even when a meta JSON postdates its "
                         "synthesizer (see --sample-from)")
    ap.add_argument("--sanitize", action="store_true",
                    help="runtime sanitizers: transfer guards on the lane "
                         "dispatch + a one-compile-per-program budget over "
                         "the shared LRU (exit 4 on violation)")
    ap.add_argument("--lockwatch", action="store_true",
                    help="deadlock sanitizer: watch every lock the fleet "
                         "allocates, build the runtime lock-order graph, "
                         "and print hold/contention stats at drain (exit "
                         "4 if an order cycle or re-entry was recorded)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    pairs = []
    for spec in args.tenants:
        name, sep, root = spec.partition("=")
        if not sep or not name or not root:
            ap.error(f"tenant spec {spec!r}: want NAME=DIR")
        pairs.append((name, root))

    from fed_tgan_tpu.cli import _enable_compile_cache

    _enable_compile_cache()
    if args.sanitize:
        from fed_tgan_tpu.analysis.sanitizers import enable_sanitizers

        enable_sanitizers()
    if args.lockwatch:
        # installed before the registry/service are built so every lock
        # they allocate is watched
        from fed_tgan_tpu.analysis import lockwatch

        lockwatch.clear()
        lockwatch.install(on_deadlock="record")
    log = (lambda *a, **k: None) if args.quiet else print
    fleet = FleetRegistry(
        program_cache=ProgramCache(max_entries=args.cache_entries,
                                   max_bytes=int(args.cache_mb * 1024
                                                 * 1024)),
        quota_rps=args.quota_rps, quota_burst=args.quota_burst,
        allow_meta_mismatch=args.allow_meta_mismatch,
        promote=args.promote, log=log,
    )
    for name, root in pairs:
        try:
            fleet.load(name, root)
        except ArtifactError as exc:
            print(f"fleet: tenant {name!r}: {exc}")
            return 2
    row_pool = None
    service = FleetService(
        fleet, host=args.host, port=args.port, max_batch=args.max_batch,
        queue_size=args.queue_size, max_lanes=args.max_lanes,
        queue_share=args.queue_share,
        request_timeout_s=args.request_timeout,
        reload_interval_s=args.reload_interval, workers=args.workers,
        coalesce_window_s=args.coalesce_window, http_mode=args.http,
        log=log,
    )
    if args.row_pool_chunks > 0:
        from fed_tgan_tpu.serve.pool import RowPool

        row_pool = RowPool(fleet, chunk_rows=args.row_pool_chunk_rows,
                           max_chunks_per_key=args.row_pool_chunks)
        service.row_pool = row_pool
    service.start()
    print(f"serving {len(pairs)} tenant(s) on {service.url}  "
          f"(endpoints: /t/<tenant>/sample /fleet /healthz /metrics; "
          "Ctrl-C drains and exits)", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("fleet: draining...", flush=True)
        service.shutdown(drain=True)
    if args.sanitize:
        from fed_tgan_tpu.analysis import sanitizers

        print(sanitizers.compile_report())
        problems = sanitizers.check_fleet_budget(fleet.cache)
        for problem in problems:
            print(f"SANITIZE: {problem}")
        if problems:
            return 4
    if args.lockwatch:
        from fed_tgan_tpu.analysis import lockwatch

        lockwatch.uninstall()
        for lname, st in sorted(lockwatch.summary().items()):
            print(f"lockwatch: {lname}: {st['acquisitions']} acq "
                  f"({st['contentions']} contended), hold p99 "
                  f"{st['hold_p99_ms']:.3f} ms")
        problems = (lockwatch.reports("cycle")
                    + lockwatch.reports("reentry"))
        for rep in problems:
            print(f"LOCKWATCH: {rep.detail}")
        if problems:
            return 4
    return 0
