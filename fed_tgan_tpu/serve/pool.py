"""Pre-generated row pools: cache-hit requests skip the dispatch path.

The fleet's steady-state traffic is dominated by clients walking the
deterministic ``(seed, offset)`` row stream in small contiguous requests
(the paired CLI client, the bench's closed-loop clients).  Each such
request costs a full engine round — device dispatch, decode, CSV
serialize — even though the rows it wants are a pure function of
``(model, seed, absolute row index, condition)`` and its neighbours were
just computed for the previous request.  The pool exploits exactly that
determinism: a background filler bulk-samples CHUNKS of the stream
(``chunk_rows`` at a time, amortizing the fixed dispatch cost across
thousands of rows) and stores the per-row CSV byte segments
(:meth:`~.engine.SamplingEngine.sample_csv_segments`).  A request whose
row span is covered stitches its response from cached segments — bit-
identical to a cold dispatch by the engine's determinism contract — in
microseconds, without ever touching the queue or the device.

Keys are ``(tenant, seed, condition)``; a key becomes *hot* after
``hot_after`` requests have asked for it, which keeps one-off probes from
triggering 2048-row fills.  Per key the pool holds a bounded sliding
window of chunks (``max_chunks_per_key``): as a client advances its
offset, the filler extends the window ahead of the observed demand
(``lookahead_chunks``) and drops chunks the client has moved past.

Consistency: every chunk is tagged with the ``model_id`` of the engine
snapshot that produced it, inserts are rejected when the entry has moved
to a different model, and the serving worker invalidates a tenant's
entries whenever a hot reload adopts a new model — a pool hit never mixes
models, the same snapshot discipline the batch path enforces.

Admission interplay: the fleet charges a tenant's quota token BEFORE the
pool lookup, so a quota-limited tenant stays pinned at its configured
rate even when its traffic is 100% pool hits.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

__all__ = ["RowPool"]


class _PoolEntry:
    """One hot ``(tenant, seed, condition)`` stream: a sliding window of
    row-segment chunks plus the demand counters the filler reads."""

    __slots__ = ("model_id", "header", "chunks", "demand", "want_lo",
                 "want_hi", "unpoolable")

    def __init__(self):
        self.model_id: Optional[str] = None
        self.header: bytes = b""
        self.chunks: dict = {}      # chunk index -> [row_bytes] * chunk_rows
        self.demand = 0             # requests that asked for this key
        self.want_lo = 0            # lowest / highest chunk index recently
        self.want_hi = 0            # demanded (the filler's target window)
        self.unpoolable = False     # frame not row-sliceable: never pool


class RowPool:
    """Bounded pool of pre-serialized row chunks with a background filler.

    ``get`` is the request-path fast lookup (returns the response as a
    list of byte segments, or None on miss); ``fill_once`` runs one
    filler cycle synchronously (the deterministic seam tests and the
    doctor use); ``start``/``stop`` run ``fill_once`` on a daemon thread.
    All shared state is guarded by ``self._lock``; engine sampling always
    happens outside it.
    """

    def __init__(self, fleet, chunk_rows: int = 2048,
                 max_chunks_per_key: int = 8, max_keys: int = 32,
                 hot_after: int = 8, lookahead_chunks: int = 2,
                 fill_interval_s: float = 0.02,
                 max_fills_per_cycle: int = 4):
        self.fleet = fleet
        self.chunk_rows = max(1, int(chunk_rows))
        self.max_chunks_per_key = max(1, int(max_chunks_per_key))
        self.max_keys = max(1, int(max_keys))
        self.hot_after = max(0, int(hot_after))
        self.lookahead_chunks = max(0, int(lookahead_chunks))
        self.fill_interval_s = float(fill_interval_s)
        self.max_fills_per_cycle = max(1, int(max_fills_per_cycle))
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # key -> _PoolEntry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "RowPool":
        self._stop.clear()
        self._thread = threading.Thread(target=self._filler,
                                        name="row-pool-filler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _filler(self) -> None:
        while not self._stop.wait(self.fill_interval_s):
            try:
                self.fill_once()
            except Exception:  # noqa: BLE001 — filling must never die
                pass

    # --------------------------------------------------------- request path

    def get(self, tenant: str, seed: int, offset: int, n: int,
            condition: Optional[int], header: bool) -> Optional[list]:
        """Response byte segments for rows [offset, offset+n) of
        ``(tenant, seed, condition)``, or None when not fully cached.
        Records the demand either way — misses are what make a key hot."""
        key = (tenant, seed, condition)
        c0 = offset // self.chunk_rows
        c1 = (offset + n - 1) // self.chunk_rows
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = _PoolEntry()
                entry.want_lo, entry.want_hi = c0, c1
                self._entries[key] = entry
                while len(self._entries) > self.max_keys:
                    self._entries.popitem(last=False)
                    self.evictions += 1
            else:
                self._entries.move_to_end(key)
            entry.demand += 1
            # grow the demanded window while it fits the per-key budget —
            # stable for clients looping a bounded stream — and only once
            # the span exceeds capacity slide it to the latest request,
            # which is what a forward-walking client expects
            lo = min(entry.want_lo, c0)
            hi = max(entry.want_hi, c1)
            if hi - lo >= self.max_chunks_per_key:
                lo = c0
                hi = max(c1, entry.want_hi) if entry.want_hi >= c0 else c1
            entry.want_lo, entry.want_hi = lo, hi
            if entry.unpoolable:
                return None
            out = [entry.header] if header else []
            for c in range(c0, c1 + 1):
                rows = entry.chunks.get(c)
                if rows is None:
                    self.misses += 1
                    return None
                lo = max(0, offset - c * self.chunk_rows)
                hi = min(self.chunk_rows, offset + n - c * self.chunk_rows)
                out.extend(rows[lo:hi])
            self.hits += 1
            return out

    def invalidate(self, tenant: str) -> None:
        """Drop every entry of ``tenant`` — called when a hot reload
        adopts a new model, so a pool hit can never serve stale rows."""
        with self._lock:
            for key in [k for k in self._entries if k[0] == tenant]:
                del self._entries[key]

    # --------------------------------------------------------------- filler

    def _plan(self) -> list:
        """(key, chunk_index) fills wanted right now, hot keys first by
        demand, bounded to ``max_fills_per_cycle``.  Also slides each
        entry's window: chunks behind the demanded range are dropped."""
        plan: list = []
        with self._lock:
            entries = sorted(self._entries.items(),
                             key=lambda kv: -kv[1].demand)
            for key, entry in entries:
                if entry.unpoolable or entry.demand < self.hot_after:
                    continue
                lo = entry.want_lo
                hi = entry.want_hi + self.lookahead_chunks
                hi = min(hi, lo + self.max_chunks_per_key - 1)
                for c in [c for c in entry.chunks if c < lo or c > hi]:
                    del entry.chunks[c]
                    self.evictions += 1
                for c in range(lo, hi + 1):
                    if c not in entry.chunks:
                        plan.append((key, c))
                        if len(plan) >= self.max_fills_per_cycle:
                            return plan
        return plan

    def _drop_key(self, key: tuple) -> bool:
        """Forget ``key`` (its tenant left the fleet); returns False so
        ``_fill_chunk`` can tail-call it."""
        with self._lock:
            self._entries.pop(key, None)
        return False

    def _fill_chunk(self, key: tuple, chunk: int) -> bool:
        tenant, seed, condition = key
        rt = self.fleet.get(tenant)
        if rt is None:
            return self._drop_key(key)
        snap = rt.engine.snapshot()
        try:
            header, rows = rt.engine.sample_csv_segments(
                self.chunk_rows, seed=seed, offset=chunk * self.chunk_rows,
                condition=condition, snap=snap)
        except ValueError:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    entry.unpoolable = True
                    entry.chunks.clear()
            return False
        model_id = snap.model.model_id
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            if entry.model_id != model_id:
                if entry.model_id is not None:
                    # the tenant moved to a new model mid-fill: drop the
                    # old-model chunks rather than mixing generations
                    entry.chunks.clear()
                entry.model_id = model_id
            entry.header = header
            entry.chunks[chunk] = rows
            self.fills += 1
            while len(entry.chunks) > self.max_chunks_per_key:
                oldest = min(entry.chunks)
                del entry.chunks[oldest]
                self.evictions += 1
        return True

    def fill_once(self) -> int:
        """One filler cycle: plan under the lock, sample outside it,
        insert under the lock.  Returns the number of chunks filled."""
        filled = 0
        for key, chunk in self._plan():
            if self._fill_chunk(key, chunk):
                filled += 1
        return filled

    def fill_now(self, tenant: str, seed: int = 0, offset: int = 0,
                 n: int = 1, condition: Optional[int] = None) -> int:
        """Synchronously cover rows [offset, offset+n) for a key — the
        deterministic test/doctor seam (no background thread needed)."""
        key = (tenant, seed, condition)
        c0 = offset // self.chunk_rows
        c1 = (offset + n - 1) // self.chunk_rows
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = _PoolEntry()
                self._entries[key] = entry
            entry.demand = max(entry.demand, self.hot_after)
            entry.want_lo, entry.want_hi = c0, c1
        filled = 0
        for c in range(c0, c1 + 1):
            if self._fill_chunk(key, c):
                filled += 1
        return filled

    # --------------------------------------------------------------- status

    def stats(self) -> dict:
        with self._lock:
            chunks = sum(len(e.chunks) for e in self._entries.values())
            return {
                "keys": len(self._entries),
                "chunks": chunks,
                "rows": chunks * self.chunk_rows,
                "hits": self.hits,
                "misses": self.misses,
                "fills": self.fills,
                "evictions": self.evictions,
            }
