"""Long-lived stdlib-only sampling server + the paired CLI client.

Request path: HTTP handler threads validate and enqueue; N batch workers
(``workers``, default 1) each drain their own shard of a bounded queue,
coalescing up to ``max_batch`` queued requests per cycle (micro-batch
coalescing — under concurrent clients the queue builds while a batch
computes, so the next cycle serves several requests back-to-back without
re-entering the Python dispatch overhead per request), run them through
the compiled engine, and flip each request's event.  A bounded
``coalesce_window_s`` optionally holds a forming batch for more traffic
so lanes actually fill under closed-loop load.  The queue is bounded: a
full queue sheds load with 503 + a Retry-After computed from the
fleet-wide measured drain rate (scales with the worker count) instead of
building an unbounded latency tail.  Shutdown drains: new requests are
rejected, everything already queued is answered, then the workers exit.

Endpoints:

- ``GET/POST /sample``  rows/seed/offset/column/value/header params;
  returns ``text/csv`` bytes identical to the one-shot ``--sample-from``
  file for the same (rows, seed) — see the engine's determinism contract.
- ``GET /healthz``      JSON liveness + model id + counters.
- ``GET /metrics``      Prometheus text exposition.

Everything here is stdlib (http.server, queue, threading); jax only runs
inside the engine the worker calls.
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List

from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.serve.engine import ConditionError, SamplingEngine
from fed_tgan_tpu.serve.metrics import DrainRate, ServiceMetrics
from fed_tgan_tpu.serve.registry import ModelRegistry

_STOP = object()


@dataclass
class _Request:
    n: int
    seed: int
    offset: int
    condition: int | None
    header: bool
    enqueued_at: float = field(default_factory=time.time)
    done: threading.Event = field(default_factory=threading.Event)
    result: bytes | None = None
    error: str | None = None
    status: int = 500
    # request-scoped trace context: the worker stamps popped_at when it
    # pulls the request off the queue, and per-stage seconds accumulate
    # in stages (queue_wait/batch_form here, dispatch/decode/serialize
    # inside the engine) — host clocks only, never a device sync
    popped_at: float = 0.0
    stages: dict = field(default_factory=dict)


class SamplingService:
    """One registry-backed engine behind a bounded-queue HTTP server."""

    def __init__(self, registry: ModelRegistry, host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 8, queue_size: int = 64,
                 request_timeout_s: float = 120.0,
                 reload_interval_s: float = 5.0, workers: int = 1,
                 coalesce_window_s: float = 0.0, promote: str = "immediate",
                 canary_config=None, log=print):
        self.registry = registry
        self.engine = SamplingEngine(registry.get())
        self.metrics = ServiceMetrics()
        # promotion policy: "immediate" hot-swaps any loadable new
        # generation (historical behaviour); "canary" shadow-scores the
        # candidate against the tenant's reference statistics first and
        # only promotes inside the quality budgets
        self.promote_mode = str(promote)
        self.gate = None
        if self.promote_mode == "canary":
            from fed_tgan_tpu.serve.canary import CanaryGate

            self.gate = CanaryGate(registry, self.engine,
                                   tenant=registry.get().artifact.name,
                                   config=canary_config, log=log)
        self.max_batch = max(1, int(max_batch))
        self.request_timeout_s = request_timeout_s
        self.reload_interval_s = reload_interval_s
        self.workers = max(1, int(workers))
        self.coalesce_window_s = max(0.0, float(coalesce_window_s))
        self._log = log
        self._host, self._port = host, port
        # one queue shard per worker (round-robin admission, each worker
        # drains only its own) — same sharding as the fleet service
        total = max(1, int(queue_size))
        per = -(-total // self.workers)
        self._queue_size = per * self.workers
        self._queues: List[queue.Queue] = [
            queue.Queue(maxsize=per) for _ in range(self.workers)]
        self._rr = itertools.count()
        self._drain_rate = DrainRate()
        self._draining = threading.Event()
        self._last_reload_check = time.monotonic()
        # first stage summary goes out with the first batch
        self._last_stage_emit = float("-inf")
        self._httpd: ThreadingHTTPServer | None = None
        self._worker_threads: List[threading.Thread] = []
        self._serve_thread: threading.Thread | None = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "SamplingService":
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self._host, self._port), handler)
        self._httpd.daemon_threads = True
        self._worker_threads = [
            threading.Thread(target=self._worker, args=(i,),
                             name=f"serve-batch-worker-{i}", daemon=True)
            for i in range(self.workers)
        ]
        for t in self._worker_threads:
            t.start()
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
            name="serve-http", daemon=True)
        self._serve_thread.start()
        return self

    @property
    def port(self) -> int:
        assert self._httpd is not None, "start() first"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def shutdown(self, drain: bool = True) -> None:
        """Stop accepting, answer (or fail) everything queued, stop."""
        self._draining.set()
        if not drain:
            # fail queued requests instead of computing them
            for q in self._queues:
                while True:
                    try:
                        req = q.get_nowait()
                    except queue.Empty:
                        break
                    if req is not _STOP:
                        req.error, req.status = "server shutting down", 503
                        req.done.set()
        for q in self._queues:
            try:
                q.put_nowait(_STOP)
            except queue.Full:
                pass  # that worker is alive and draining; _draining exits it
        for t in self._worker_threads:
            t.join(timeout=max(self.request_timeout_s, 10))
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10)

    # -------------------------------------------------------- request path

    def submit(self, req: _Request) -> bool:
        """Enqueue; False = shed (queue full or draining).  Round-robin
        across shards; a full shard tries the rest before shedding."""
        if self._draining.is_set():
            return False
        start = next(self._rr) % self.workers
        for j in range(self.workers):
            try:
                self._queues[(start + j) % self.workers].put_nowait(req)
                return True
            except queue.Full:
                continue
        self.metrics.record_shed()
        return False

    def queue_depth(self) -> int:
        return sum(q.qsize() for q in self._queues)

    def capacity_retry_after(self) -> float:
        """503 Retry-After: queued work over the measured aggregate drain
        rate (scales with the worker count), clamped to a sane band;
        before any batch has completed, fall back to 1 s."""
        rate = self._drain_rate.rate()
        if rate <= 0.0:
            return 1.0
        return min(30.0, max(0.05, (self.queue_depth() + 1) / rate))

    # ------------------------------------------------------------- worker

    def _worker(self, wid: int = 0) -> None:
        q = self._queues[wid]
        while True:
            try:
                item = q.get(timeout=0.05)
            except queue.Empty:
                if self._draining.is_set():
                    return
                if wid == 0:  # one reload poller is enough
                    self._maybe_reload()
                continue
            if item is _STOP:
                self._process(self._drain_remaining(q))
                self._emit_stages(force=True)
                return
            item.popped_at = time.time()
            batch = [item]
            stop = False
            # occupancy-driven admission: hold the forming batch for at
            # most coalesce_window_s while the shard is quiet, so closed-
            # loop clients land in THIS batch instead of singletons
            deadline = (time.monotonic() + self.coalesce_window_s
                        if self.coalesce_window_s > 0 else 0.0)
            while len(batch) < self.max_batch:
                try:
                    nxt = q.get_nowait()
                except queue.Empty:
                    wait = deadline - time.monotonic()
                    if wait <= 0 or self._draining.is_set():
                        break
                    try:
                        nxt = q.get(timeout=wait)
                    except queue.Empty:
                        break
                if nxt is _STOP:
                    stop = True
                    break
                nxt.popped_at = time.time()
                batch.append(nxt)
            self._process(batch)
            if stop:
                self._process(self._drain_remaining(q))
                self._emit_stages(force=True)
                return
            if wid == 0:
                self._maybe_reload()

    def _drain_remaining(self, q: queue.Queue) -> list:
        batch = []
        while True:
            try:
                req = q.get_nowait()
            except queue.Empty:
                return batch
            if req is not _STOP:
                req.popped_at = time.time()
                batch.append(req)

    def _process(self, batch: list) -> None:
        if not batch:
            return
        self.metrics.record_batch(len(batch))
        # the advertised queue-depth gauge: sampled once per worker
        # cycle, right after the batch formed (what's still waiting)
        self.metrics.set_queue_depth(self.queue_depth())
        # one snapshot for the whole formed batch: a hot reload adopting a
        # new model mid-batch must never swap the model out from under
        # requests already grouped against the old one
        snap = self.engine.snapshot()
        for req in batch:
            # queue_wait ends at the pop; batch_form ends when THIS
            # request's own processing starts, so the wait behind
            # earlier batch members lands in batch_form and the five
            # stages sum to ~the full server-side latency
            t_start = time.time()
            popped = req.popped_at or t_start
            req.stages["queue_wait"] = max(0.0, popped - req.enqueued_at)
            req.stages["batch_form"] = max(0.0, t_start - popped)
            try:
                req.result = self.engine.sample_csv_bytes(
                    req.n, seed=req.seed, offset=req.offset,
                    condition=req.condition, header=req.header, snap=snap,
                    stages=req.stages,
                )
                req.status = 200
                self.metrics.record_request(
                    time.time() - req.enqueued_at, req.n)
                self.metrics.record_stages(req.stages)
            except Exception as exc:  # noqa: BLE001 — becomes the 500 body
                req.error, req.status = repr(exc), 500
                self.metrics.record_error()
            finally:
                req.done.set()
        self._drain_rate.note(len(batch))
        self._emit_stages()

    def _emit_stages(self, force: bool = False) -> None:
        """Rate-limited ``serve_stages`` journal summary (~1 per 5 s)."""
        now = time.monotonic()
        if not force and now - self._last_stage_emit < 5.0:
            return
        stages = self.metrics.stage_snapshot()
        if stages:
            self._last_stage_emit = now
            _emit_event("serve_stages", stages=stages)

    def _maybe_reload(self) -> None:
        if self.reload_interval_s <= 0:
            return
        now = time.monotonic()
        if now - self._last_reload_check < self.reload_interval_s:
            return
        self._last_reload_check = now
        if self.gate is not None:
            self._canary_reload()
            return
        try:
            if self.registry.maybe_reload():
                kept = self.engine.adopt(self.registry.get())
                self.metrics.record_reload()
                _emit_event("serve_reload",
                            model_id=self.registry.get().model_id,
                            programs_kept=bool(kept))
                self._log(
                    f"service: now serving model "
                    f"{self.registry.get().model_id} "
                    f"({'programs kept' if kept else 'programs rebuilt'})"
                )
        except Exception as exc:  # noqa: BLE001 — reload must never kill serving
            self._log(f"service: reload check failed ({exc!r})")

    def _canary_reload(self) -> None:
        """Canary promotion path: shadow-score before any swap.  The
        serving model is only replaced after the gate promotes, so a
        rejected candidate never contributes a byte to any response."""
        try:
            decision = self.gate.consider()
        except Exception as exc:  # noqa: BLE001 — gate must never kill serving
            self._log(f"service: canary check failed ({exc!r})")
            return
        if decision is None:
            return
        tenant = self.registry.get().artifact.name
        self.metrics.quality.record_scores(
            tenant, decision.get("avg_jsd"), decision.get("avg_wd"))
        self.metrics.quality.record_decision(
            tenant, bool(decision.get("promoted")))
        if decision.get("promoted"):
            kept = self.engine.adopt(self.registry.get())
            self.metrics.record_reload()
            _emit_event("serve_reload",
                        model_id=self.registry.get().model_id,
                        programs_kept=bool(kept))
            self._log(
                f"service: canary promoted model "
                f"{self.registry.get().model_id} "
                f"({'programs kept' if kept else 'programs rebuilt'})"
            )


def _make_handler(service: SamplingService):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # stdlib's unbuffered wfile writes headers and body as separate
        # TCP segments; without NODELAY, Nagle + delayed ACK stalls every
        # small response ~40 ms on loopback
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send(self, status: int, body: bytes, ctype: str,
                  extra: dict | None = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, status: int, obj: dict,
                       extra: dict | None = None) -> None:
            self._send(status, json.dumps(obj).encode(), "application/json",
                       extra)

        def do_GET(self):
            parsed = urllib.parse.urlsplit(self.path)
            if parsed.path == "/healthz":
                snap = service.metrics.snapshot(service.queue_depth())
                model = service.registry.get()
                self._send_json(200, {
                    "status": "draining" if service._draining.is_set()
                    else "ok",
                    "model_id": model.model_id,
                    "model_name": model.artifact.name,
                    **snap,
                    "stages": service.metrics.stage_snapshot(),
                    "promotion": (service.gate.status() if service.gate
                                  else {"mode": service.promote_mode}),
                })
            elif parsed.path == "/metrics":
                text = service.metrics.render_prometheus(
                    service.queue_depth())
                self._send(200, text.encode(), "text/plain; version=0.0.4")
            elif parsed.path == "/sample":
                params = {k: v[-1] for k, v in
                          urllib.parse.parse_qs(parsed.query).items()}
                self._handle_sample(params)
            else:
                self._send_json(404, {"error": f"no route {parsed.path}"})

        def do_POST(self):
            parsed = urllib.parse.urlsplit(self.path)
            if parsed.path != "/sample":
                self._send_json(404, {"error": f"no route {parsed.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                params = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(params, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, json.JSONDecodeError) as exc:
                self._send_json(400, {"error": f"bad JSON body: {exc}"})
                return
            self._handle_sample(params)

        def _handle_sample(self, params: dict) -> None:
            try:
                n = int(params.get("rows", params.get("n", 0)))
                seed = int(params.get("seed", 0))
                offset = int(params.get("offset", 0))
                header = str(params.get("header", "1")) not in ("0", "false")
                if n <= 0:
                    raise ValueError(f"rows={n}: need a positive row count")
                if offset < 0:
                    raise ValueError(f"offset={offset}: must be >= 0")
            except (TypeError, ValueError) as exc:
                self._send_json(400, {"error": str(exc)})
                return
            condition = None
            column = params.get("column")
            if column:
                try:
                    condition = service.engine.resolve_condition(
                        column, params.get("value"))
                except ConditionError as exc:
                    self._send_json(400, {"error": str(exc)})
                    return
            req = _Request(n=n, seed=seed, offset=offset,
                           condition=condition, header=header)
            if not service.submit(req):
                self._send_json(
                    503,
                    {"error": "draining" if service._draining.is_set()
                     else "queue full"},
                    extra={"Retry-After":
                           f"{service.capacity_retry_after():.2f}"},
                )
                return
            if not req.done.wait(timeout=service.request_timeout_s):
                self._send_json(504, {"error": "request timed out in queue"})
                return
            if req.status == 200 and req.result is not None:
                self._send(200, req.result, "text/csv")
            else:
                self._send_json(req.status, {"error": req.error or "failed"})

    return Handler


# ------------------------------------------------------------------- CLI


def serve_main(argv=None) -> int:
    """``fed-tgan-tpu serve <artifact-dir> [flags]``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="fed_tgan_tpu serve",
        description="serve synthetic rows from a --save-model artifact "
                    "over HTTP (long-lived, compile-once)")
    ap.add_argument("artifact", help="run out-dir / models dir / "
                    "synthesizer dir (same resolution as --sample-from)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7799,
                    help="0 = ephemeral (printed at startup)")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="max requests coalesced per worker cycle")
    ap.add_argument("--queue-size", type=int, default=64,
                    help="bounded request queue; full = shed with 503")
    ap.add_argument("--workers", type=int, default=1,
                    help="batch workers draining a sharded queue")
    ap.add_argument("--coalesce-window", type=float, default=0.0,
                    help="seconds a worker holds a forming batch for more "
                         "traffic (0 = dispatch immediately)")
    ap.add_argument("--request-timeout", type=float, default=120.0,
                    help="seconds a request may wait before 504")
    ap.add_argument("--reload-interval", type=float, default=5.0,
                    help="seconds between hot-reload polls (0 = never)")
    ap.add_argument("--promote", choices=("canary", "immediate"),
                    default="immediate",
                    help="new-generation policy: immediate = hot-swap any "
                         "loadable checkpoint (default); canary = shadow-"
                         "score the candidate against the reference "
                         "statistics and promote only inside the quality "
                         "budgets in obs/budgets.json")
    ap.add_argument("--allow-meta-mismatch", action="store_true",
                    help="serve even when the meta JSON postdates the "
                         "synthesizer (see --sample-from)")
    ap.add_argument("--sanitize", action="store_true",
                    help="runtime sanitizers: transfer guards on the "
                         "steady-state sampling dispatch + a one-compile-"
                         "per-bucket budget (exit 4 on violation)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    from fed_tgan_tpu.cli import _enable_compile_cache
    from fed_tgan_tpu.serve.registry import ArtifactError

    # warm restarts skip the per-bucket XLA compiles entirely
    _enable_compile_cache()
    if args.sanitize:
        from fed_tgan_tpu.analysis.sanitizers import enable_sanitizers

        enable_sanitizers()
    log = (lambda *a, **k: None) if args.quiet else print
    try:
        registry = ModelRegistry(args.artifact,
                                 allow_meta_mismatch=args.allow_meta_mismatch,
                                 log=log)
        service = SamplingService(
            registry, host=args.host, port=args.port,
            max_batch=args.max_batch, queue_size=args.queue_size,
            request_timeout_s=args.request_timeout,
            reload_interval_s=args.reload_interval, workers=args.workers,
            coalesce_window_s=args.coalesce_window, promote=args.promote,
            log=log,
        )
    except ArtifactError as exc:
        print(f"serve: {exc}")
        return 2
    service.start()
    model = registry.get()
    print(f"serving model {model.model_id} ({model.artifact.name}) "
          f"on {service.url}  (endpoints: /sample /healthz /metrics; "
          "Ctrl-C drains and exits)", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("serve: draining...", flush=True)
        service.shutdown(drain=True)
    if args.sanitize:
        from fed_tgan_tpu.analysis import sanitizers

        print(sanitizers.compile_report())
        problems = sanitizers.check_serving_budget(service.engine)
        for problem in problems:
            print(f"SANITIZE: {problem}")
        if problems:
            return 4
    return 0


def client_main(argv=None) -> int:
    """``fed-tgan-tpu sample-client --url ... --rows N [--chunks K]``.

    Chunked fetches are offset-contiguous, so the concatenated output is
    bit-identical to one N-row request (the engine's determinism
    contract) — K is purely a transfer-sizing knob."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="fed_tgan_tpu sample-client",
        description="fetch synthetic rows from a running serve instance")
    ap.add_argument("--url", default="http://127.0.0.1:7799",
                    help="server base URL")
    ap.add_argument("--rows", type=int, required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--offset", type=int, default=0,
                    help="starting row of the deterministic stream")
    ap.add_argument("--chunks", type=int, default=1,
                    help="split the fetch into K contiguous requests")
    ap.add_argument("--column", default=None,
                    help="conditional sampling: discrete column to fix")
    ap.add_argument("--value", default=None,
                    help="conditional sampling: the option to fix it to")
    ap.add_argument("--out", default=None,
                    help="output CSV path (default: stdout)")
    ap.add_argument("--timeout", type=float, default=300.0)
    args = ap.parse_args(argv)
    if args.rows <= 0:
        ap.error(f"--rows {args.rows}: must be positive")
    if not 1 <= args.chunks <= args.rows:
        ap.error(f"--chunks {args.chunks}: must be in [1, rows]")
    if (args.column is None) != (args.value is None):
        ap.error("--column and --value go together")

    base, done = args.rows // args.chunks, 0
    parts = []
    for i in range(args.chunks):
        n = base + (1 if i < args.rows % args.chunks else 0)
        if n == 0:
            continue
        q = {"rows": n, "seed": args.seed, "offset": args.offset + done,
             "header": int(i == 0)}
        if args.column is not None:
            q.update(column=args.column, value=args.value)
        url = f"{args.url}/sample?{urllib.parse.urlencode(q)}"
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as resp:
                parts.append(resp.read())
        except urllib.error.HTTPError as exc:
            print(f"sample-client: HTTP {exc.code}: "
                  f"{exc.read().decode(errors='replace')}", file=sys.stderr)
            return 1
        except OSError as exc:
            print(f"sample-client: {exc} (is `fed-tgan-tpu serve` running "
                  f"at {args.url}?)", file=sys.stderr)
            return 1
        done += n
    blob = b"".join(parts)
    if args.out:
        with open(args.out, "wb") as f:
            f.write(blob)
        print(f"wrote {args.rows} rows to {args.out}", file=sys.stderr)
    else:
        sys.stdout.buffer.write(blob)
    return 0
