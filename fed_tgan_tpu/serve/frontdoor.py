"""Asyncio HTTP front door: the fleet's production request path.

One event loop on one thread replaces the stdlib's thread-per-connection
server.  The motivation is measured, not aesthetic: the threaded server
tops out near a couple hundred requests/second on this box (per-request
thread handoff, unbuffered ``wfile`` writes interacting with Nagle +
delayed ACK), while a single asyncio loop serves thousands — and the
serving fleet's host work per request is microseconds once the row pool
answers it.

The door is deliberately minimal HTTP/1.1: request line + headers,
``Content-Length`` bodies, keep-alive by default.  It does NOT implement
chunked uploads or pipelining fan-out — the serving clients (CLI,
bench, SDKs speaking plain HTTP) don't use them, and every unsupported
shape gets a clean 400/close rather than an undefined one.

All routing lives in :meth:`~.fleet.FleetService.route` — this module
only parses bytes and renders :class:`~.fleet.Response` objects, so the
asyncio and threaded front doors cannot disagree about behavior.  Two
response paths matter:

* **Zero-copy segment streaming** — a ``Response`` whose body is a list
  of byte segments (a row-pool hit, pre-serialized CSV lines) is written
  with ``writelines`` straight into the transport: no intermediate join,
  no per-request copy of the payload.
* **Queue bridging** — a routed :class:`~.fleet.Pending` parks an
  ``asyncio`` future; the batch worker's completion callback flips it
  with ``call_soon_threadsafe``.  No thread ever blocks per request.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import urllib.parse
from typing import Optional

from fed_tgan_tpu.serve.fleet import Pending, Response, _json_response

#: request-line / header-block size guard (one line, all headers)
_MAX_HEADER_BYTES = 32 * 1024
_MAX_BODY_BYTES = 4 * 1024 * 1024

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found", 410: "Gone",
    429: "Too Many Requests", 500: "Internal Server Error",
    503: "Service Unavailable", 504: "Gateway Timeout",
}


class AsyncFrontDoor:
    """Event-loop HTTP server adapting ``service.route``.

    Runs the loop on a dedicated thread so the blocking
    :class:`~.fleet.FleetService` lifecycle (start/shutdown from
    synchronous code, batch workers on their own threads) stays
    unchanged.  ``start()`` blocks until the socket is bound, so
    ``port`` is always readable afterwards.
    """

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 120.0):
        self.service = service
        self.host = host
        self.request_timeout_s = request_timeout_s
        self._requested_port = port
        self._port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._startup_error: Optional[BaseException] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "AsyncFrontDoor":
        self._thread = threading.Thread(target=self._run,
                                        name="fleet-frontdoor", daemon=True)
        self._thread.start()
        self._ready.wait(timeout=30)
        if self._startup_error is not None:
            raise self._startup_error
        if self._port is None:
            raise RuntimeError("front door failed to bind within 30 s")
        return self

    @property
    def port(self) -> int:
        assert self._port is not None, "start() first"
        return self._port

    def stop(self) -> None:
        loop = self._loop
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(self._shutdown)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
        for task in asyncio.all_tasks(self._loop):
            task.cancel()

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(self._serve())
        except asyncio.CancelledError:
            pass
        except BaseException as exc:  # noqa: BLE001 — surface via start()
            self._startup_error = exc
            self._ready.set()
        finally:
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            except (RuntimeError, asyncio.CancelledError):
                pass
            loop.close()

    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self._requested_port)
        sock = self._server.sockets[0]
        self._port = sock.getsockname()[1]
        self._ready.set()
        try:
            await self._server.serve_forever()
        except asyncio.CancelledError:
            pass
        finally:
            self._server.close()
            try:
                await self._server.wait_closed()
            except asyncio.CancelledError:
                pass

    # ----------------------------------------------------------- connection

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            # without NODELAY every small response eats a Nagle/delayed-ACK
            # round trip (~40 ms) — the exact artifact this door removes
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                keep_alive = await self._handle_one(reader, writer)
                if not keep_alive:
                    break
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError,
                    asyncio.CancelledError):
                pass

    async def _handle_one(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> bool:
        """Serve one request; returns False when the connection must
        close (EOF, parse error, or an explicit Connection: close)."""
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError as exc:
            if exc.partial:
                await self._write(writer, _json_response(
                    400, {"error": "truncated request"}), close=True)
            return False
        except asyncio.LimitOverrunError:
            await self._write(writer, _json_response(
                400, {"error": "header block too large"}), close=True)
            return False
        if len(head) > _MAX_HEADER_BYTES:
            await self._write(writer, _json_response(
                400, {"error": "header block too large"}), close=True)
            return False
        try:
            request_line, headers = self._parse_head(head)
            method, target, _version = request_line
        except ValueError as exc:
            await self._write(writer, _json_response(
                400, {"error": str(exc)}), close=True)
            return False
        want_close = headers.get("connection", "").lower() == "close"
        body = b""
        length = headers.get("content-length")
        if length is not None:
            try:
                n = int(length)
                if n < 0 or n > _MAX_BODY_BYTES:
                    raise ValueError
            except ValueError:
                await self._write(writer, _json_response(
                    400, {"error": f"bad Content-Length {length!r}"}),
                    close=True)
                return False
            body = await reader.readexactly(n)

        parsed = urllib.parse.urlsplit(target)
        params = {k: v[-1] for k, v in
                  urllib.parse.parse_qs(parsed.query).items()}
        if method == "POST" and body:
            try:
                extra = json.loads(body)
                if not isinstance(extra, dict):
                    raise ValueError("body must be a JSON object")
                params.update(extra)
            except (ValueError, json.JSONDecodeError) as exc:
                await self._write(writer, _json_response(
                    400, {"error": f"bad JSON body: {exc}"}),
                    close=want_close)
                return not want_close
        if method not in ("GET", "POST"):
            await self._write(writer, _json_response(
                404, {"error": f"unsupported method {method}"}),
                close=want_close)
            return not want_close

        resp = await self._route(method, parsed.path, params)
        await self._write(writer, resp, close=want_close)
        return not want_close

    @staticmethod
    def _parse_head(head: bytes):
        lines = head.decode("latin-1").split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3:
            raise ValueError(f"malformed request line {lines[0]!r}")
        headers = {}
        for line in lines[1:]:
            if not line:
                continue
            name, sep, value = line.partition(":")
            if not sep:
                raise ValueError(f"malformed header {line!r}")
            headers[name.strip().lower()] = value.strip()
        return (parts[0], parts[1], parts[2]), headers

    async def _route(self, method: str, path: str,
                     params: dict) -> Response:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_done(req) -> None:
            # worker thread -> event loop; the future may already be
            # cancelled by the timeout below, so guard the set
            def flip() -> None:
                if not fut.done():
                    fut.set_result(req)
            loop.call_soon_threadsafe(flip)

        routed = self.service.route(method, path, params, on_done=on_done)
        if isinstance(routed, Response):
            return routed
        assert isinstance(routed, Pending)
        try:
            await asyncio.wait_for(fut, timeout=self.request_timeout_s)
        except asyncio.TimeoutError:
            pass
        return self.service.response_for(routed.req)

    async def _write(self, writer: asyncio.StreamWriter, resp: Response,
                     close: bool = False) -> None:
        reason = _REASONS.get(resp.status, "Unknown")
        head = [f"HTTP/1.1 {resp.status} {reason}",
                f"Content-Type: {resp.ctype}",
                f"Content-Length: {resp.content_length()}",
                f"Connection: {'close' if close else 'keep-alive'}"]
        for k, v in (resp.headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1"))
        if isinstance(resp.body, bytes):
            writer.write(resp.body)
        else:
            # the zero-copy path: pre-serialized segments (row-pool CSV
            # lines) go straight to the transport, no intermediate join
            writer.writelines(resp.body)
        await writer.drain()
