"""Quality control plane: canaried model promotion for the serving layer.

The paper evaluates synthetic-data fidelity offline (Avg_JSD on
categoricals + Avg_WD on continuous columns, arXiv:2108.07927 §5); the
fleet hot-reloads snapshots under fire with no check that the new
generator is any good.  This module turns the offline analysis into a
live promotion gate: when the registry sees a loadable new generation,
``--promote canary`` does NOT swap — a :class:`CanaryGate` samples shadow
rows from the candidate through the existing engine path, scores them
against the tenant's reference statistics, and only promotes when the
tenant's quality budgets (``obs/budgets.json``, ``quality/*`` rules)
pass.

Scoring:

- **Avg_JSD** — per categorical column, Jensen–Shannon distance (base 2,
  same as ``eval.similarity.column_similarity``) between the reference
  frequency table and the shadow sample's, over the REFERENCE category
  vocabulary (candidate-only categories are ignored, exactly like the
  offline scorer).
- **Avg_WD** — per continuous column, min-max-scaled 1-Wasserstein via
  the ``federation/sketch.py`` mixture-CDF program: reference and shadow
  samples become two "clients" of tiny-σ Gaussian mixtures, the pool
  weight ω = [1, 0] pins the pooled CDF to the reference, and row 1 of
  one :func:`~fed_tgan_tpu.federation.sketch._wd_impl` dispatch is
  W1(candidate, reference) for every column at once — scoring is one
  device program.
- optional **ML-efficacy probe** — train a tiny classifier on the shadow
  sample, evaluate accuracy on held-out real rows stored in the stats
  artifact (the paper's "train on synthetic, test on real" protocol).

Gating is DELTA-based: the candidate's scores are compared against the
incumbent's scores over the same reference/seed (cached per model id),
so the budgets bound *regressions*, not the absolute fidelity of a
checkpoint that may be one epoch old.  A rejected candidate's
fingerprint is quarantined — the same bytes are never re-scored, only a
genuinely new generation is — and the rejection journals a
``promotion_rejected`` forensics event carrying per-column deltas, the
tripped budget rules, and both model ids.

Reference statistics are a small JSON artifact written next to the
checkpoint at ``--save-model`` time (``reference_stats_<name>.json``);
for legacy artifacts the gate derives stats on demand by sampling the
incumbent (``source: "derived_incumbent"``) — the gate then bounds drift
relative to what is currently serving.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from fed_tgan_tpu.obs.journal import emit as _emit_event

REFERENCE_STATS_SCHEMA = 1

#: default shadow-sample size: large enough that score noise sits well
#: inside the 0.15 delta budgets, small enough to reuse the serving
#: engine's compiled buckets in one or two dispatches
DEFAULT_SHADOW_ROWS = 512

#: per-column value subsample kept in the stats artifact (order
#: statistics, so the subsample is a deterministic quantile sketch)
DEFAULT_MAX_VALUES = 256

#: σ of the empirical-value Gaussians, in min-max-scaled units — small
#: enough that the mixture CDF is the empirical CDF to well under any
#: budget, large enough to stay numerically clean on the shared grid
_EMPIRICAL_STD = 1e-3


# ------------------------------------------------------- reference stats


def reference_stats_path(models_dir: str, name: str) -> str:
    """The stats artifact lives next to the meta JSON / encoder pickle.

    The ``reference_stats_`` prefix guarantees the registry's artifact
    walk never mistakes it for a run meta: a meta JSON only counts with
    a paired ``label_encoders_<stem>.pickle``, which this never has."""
    return os.path.join(models_dir, f"reference_stats_{name}.json")


def _subsample(values: np.ndarray, max_values: int) -> np.ndarray:
    """Deterministic quantile sketch: evenly-spaced order statistics."""
    values = np.sort(np.asarray(values, dtype=np.float64))
    values = values[np.isfinite(values)]
    if len(values) <= max_values:
        return values
    idx = np.linspace(0, len(values) - 1, max_values).round().astype(int)
    return values[idx]


def compute_reference_stats(frame, categorical_columns,
                            max_values: int = DEFAULT_MAX_VALUES,
                            probe_rows: int = 0, name: str = "",
                            source: str = "training_data") -> dict:
    """Distill ``frame`` into the JSON-serializable scoring reference.

    ``probe_rows`` > 0 additionally stores that many (head) rows verbatim
    for the optional ML-efficacy probe."""
    cats = [c for c in categorical_columns if c in frame.columns]
    stats: dict = {
        "schema": REFERENCE_STATS_SCHEMA,
        "name": str(name),
        "rows": int(len(frame)),
        "source": str(source),
        "categorical": {},
        "continuous": {},
    }
    for col in frame.columns:
        if col in cats:
            freqs = frame[col].astype(str).value_counts(normalize=True)
            stats["categorical"][str(col)] = {
                "categories": [str(c) for c in freqs.index],
                "freqs": [float(v) for v in freqs.values],
            }
        else:
            vals = np.asarray(frame[col], dtype=np.float64)
            vals = vals[np.isfinite(vals)]
            lo = float(vals.min()) if len(vals) else 0.0
            hi = float(vals.max()) if len(vals) else 1.0
            stats["continuous"][str(col)] = {
                "min": lo,
                "max": hi,
                "values": [float(v) for v in _subsample(vals, max_values)],
            }
    if probe_rows > 0:
        head = frame.head(int(probe_rows))
        stats["probe"] = {
            "columns": [str(c) for c in head.columns],
            "rows": [[str(v) if c in cats else float(v)
                      for c, v in zip(head.columns, row)]
                     for row in head.itertuples(index=False)],
        }
    return stats


def write_reference_stats(stats: dict, path: str) -> str:
    """Atomic write (tmp + rename) so a reader never sees a torn file."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(stats, f)
    os.replace(tmp, path)
    return path


def load_reference_stats(path: str) -> dict:
    with open(path) as f:
        stats = json.load(f)
    if not isinstance(stats, dict) or "categorical" not in stats \
            or "continuous" not in stats:
        raise ValueError(f"{path}: not a reference-stats artifact")
    return stats


# ---------------------------------------------------------------- scoring


def _wd_columns(stats: dict, frame, grid_points: int,
                max_values: int = DEFAULT_MAX_VALUES) -> dict:
    """Per-column min-max-scaled W1(candidate, reference), every column in
    ONE sketch dispatch: a (2, C, K) stack of tiny-σ Gaussian mixtures
    with pool weight ω = [1, 0], so the pooled CDF IS the reference and
    row 1 of the result is each column's candidate-vs-reference W1."""
    cont = stats["continuous"]
    if not cont:
        return {}
    from fed_tgan_tpu.federation.sketch import _wd_fn, column_grids

    cols, pairs = [], []
    for col, info in cont.items():
        lo, hi = float(info["min"]), float(info["max"])
        span = (hi - lo) if hi > lo else 1.0
        ref = (np.asarray(info["values"], dtype=np.float64) - lo) / span
        if col in frame.columns:
            cand = np.asarray(frame[col], dtype=np.float64)
            cand = (cand[np.isfinite(cand)] - lo) / span
            cand = _subsample(cand, max_values)
        else:
            cand = np.asarray([], dtype=np.float64)
        cols.append(col)
        pairs.append((ref, cand))
    k = max(max(len(r), len(c), 1) for r, c in pairs)
    shape = (2, len(cols), k)
    means = np.zeros(shape)
    stds = np.ones(shape)       # zero-weight padding keeps the CDF finite
    weights = np.zeros(shape)
    for j, (ref, cand) in enumerate(pairs):
        for row, vals in ((0, ref), (1, cand)):
            if not len(vals):
                continue
            means[row, j, :len(vals)] = vals
            stds[row, j, :len(vals)] = _EMPIRICAL_STD
            weights[row, j, :len(vals)] = 1.0 / len(vals)
    import jax
    import jax.numpy as jnp

    omega = np.array([1.0, 0.0])
    grid = column_grids(means, stds, weights, grid_points)
    wd = np.asarray(jax.device_get(_wd_fn()(
        jnp.asarray(means, jnp.float32), jnp.asarray(stds, jnp.float32),
        jnp.asarray(weights, jnp.float32), jnp.asarray(omega, jnp.float32),
        jnp.asarray(grid, jnp.float32),
    )), dtype=np.float64)
    out = {}
    for j, (col, (_, cand)) in enumerate(zip(cols, pairs)):
        # an empty candidate column is maximally wrong, not silently fine
        out[col] = float(wd[1, j]) if len(cand) else 1.0
    return out


def score_frame(stats: dict, frame,
                grid_points: Optional[int] = None) -> dict:
    """Score ``frame`` against ``stats``; same units as
    ``eval.similarity.statistical_similarity`` (JSD base 2, WD on
    min-max-scaled values — the reference min/max, stored in the stats).

    Returns ``{"avg_jsd", "avg_wd", "per_column": {col: {kind, value}}}``.
    """
    from scipy.spatial.distance import jensenshannon

    from fed_tgan_tpu.federation.sketch import GRID_POINTS

    per_column: dict = {}
    jsd_vals = []
    for col, info in stats["categorical"].items():
        p = np.asarray(info["freqs"], dtype=np.float64)
        if col in frame.columns and len(frame):
            freqs = frame[col].astype(str).value_counts(normalize=True)
            q = np.asarray([float(freqs.get(c, 0.0))
                            for c in info["categories"]])
        else:
            q = np.zeros_like(p)
        val = float(jensenshannon(p, q, 2.0))
        if not np.isfinite(val):
            val = 0.0  # identical degenerate distributions
        per_column[col] = {"kind": "jsd", "value": val}
        jsd_vals.append(val)
    wd_by_col = _wd_columns(stats, frame,
                            grid_points or GRID_POINTS)
    for col, val in wd_by_col.items():
        per_column[col] = {"kind": "wd", "value": val}
    wd_vals = list(wd_by_col.values())
    return {
        "avg_jsd": float(np.mean(jsd_vals)) if jsd_vals else 0.0,
        "avg_wd": float(np.mean(wd_vals)) if wd_vals else 0.0,
        "per_column": per_column,
    }


def ml_efficacy_probe(stats: dict, frame) -> Optional[float]:
    """Train-on-synthetic / test-on-real accuracy for the first
    categorical column, against the probe rows stored in ``stats``.
    None when the probe is not applicable (no probe rows, no categorical
    target, sklearn unavailable, degenerate training labels)."""
    probe = stats.get("probe")
    if not probe or not stats["categorical"]:
        return None
    target = next(iter(stats["categorical"]))
    try:
        import pandas as pd
        from sklearn.linear_model import LogisticRegression

        real = pd.DataFrame(probe["rows"], columns=probe["columns"])

        def features(df):
            blocks = []
            for col, info in stats["categorical"].items():
                if col == target:
                    continue
                s = df[col].astype(str)
                blocks.append(np.stack(
                    [(s == c).to_numpy(float)
                     for c in info["categories"]], axis=1))
            for col, info in stats["continuous"].items():
                lo, hi = float(info["min"]), float(info["max"])
                span = (hi - lo) if hi > lo else 1.0
                v = (np.asarray(df[col], dtype=np.float64) - lo) / span
                blocks.append(np.nan_to_num(v)[:, None])
            return np.concatenate(blocks, axis=1)

        y_train = frame[target].astype(str).to_numpy()
        if len(np.unique(y_train)) < 2:
            return None
        clf = LogisticRegression(max_iter=200)
        clf.fit(features(frame), y_train)
        y_real = real[target].astype(str).to_numpy()
        return float(np.mean(clf.predict(features(real)) == y_real))
    except Exception:
        return None


# ------------------------------------------------------------------- gate


@dataclass
class CanaryConfig:
    """Knobs of one tenant's promotion gate."""

    shadow_rows: int = DEFAULT_SHADOW_ROWS
    shadow_seed: int = 0
    grid_points: int = 0            # 0 = the sketch default (512)
    max_values: int = DEFAULT_MAX_VALUES
    ml_probe: bool = False          # score quality/ml_acc_delta too
    budgets_path: Optional[str] = None   # None = obs/budgets.json


@dataclass
class _StatsCache:
    key: tuple = ()
    stats: Optional[dict] = field(default=None)


class CanaryGate:
    """Per-tenant promotion state machine over one registry + engine.

    ``consider()`` is the canary-mode replacement for the reload poll's
    ``maybe_reload()``: it polls for a candidate generation, scores it
    in shadow, and either promotes it into the registry (the caller then
    adopts, exactly like an immediate reload) or quarantines its
    fingerprint and leaves the serving model untouched.  Never raises —
    a failing gate must not take serving down."""

    def __init__(self, registry, engine, tenant: str = "",
                 config: Optional[CanaryConfig] = None, log=print):
        self.registry = registry
        self.engine = engine
        self.tenant = tenant or registry.get().artifact.name
        self.config = config or CanaryConfig()
        self._log = log
        # consider() runs on the reload thread; status() is read by HTTP
        # handler threads — counters and the quarantine map are shared
        self._lock = threading.Lock()
        self._quarantine: dict = {}   # fingerprint -> rejection decision
        self._baselines: dict = {}    # incumbent model_id -> scores
        self._stats_cache = _StatsCache()
        self.last_decision: Optional[dict] = None
        self.promotions = 0
        self.rejections = 0
        self.scored_total = 0

    # --------------------------------------------------------- reference

    def _reference_stats(self, incumbent) -> dict:
        """The artifact's stats when present (cache keyed by stat), else
        stats derived from the incumbent's own shadow sample (legacy
        artifacts: the gate then bounds drift vs what is serving now)."""
        art = incumbent.artifact
        path = reference_stats_path(art.models_dir, art.name)
        try:
            st = os.stat(path)
            key = ("file", path, st.st_mtime_ns, st.st_size)
        except OSError:
            key = ("derived", incumbent.model_id)
        if self._stats_cache.key == key and self._stats_cache.stats:
            return self._stats_cache.stats
        if key[0] == "file":
            try:
                stats = load_reference_stats(path)
            except (OSError, ValueError) as exc:
                self._log(f"canary[{self.tenant}]: unreadable reference "
                          f"stats {path} ({exc}); deriving from incumbent")
                key = ("derived", incumbent.model_id)
                stats = None
        else:
            stats = None
        if stats is None:
            frame = self.engine.sample_frame(
                self.config.shadow_rows, seed=self.config.shadow_seed,
                snap=self.engine.snapshot())
            stats = compute_reference_stats(
                frame, list(incumbent.meta.categorical_columns),
                max_values=self.config.max_values, name=art.name,
                source="derived_incumbent")
        self._stats_cache = _StatsCache(key=key, stats=stats)
        return stats

    def _score(self, stats: dict, snap) -> dict:
        frame = self.engine.sample_frame(
            self.config.shadow_rows, seed=self.config.shadow_seed,
            snap=snap)
        with self._lock:
            self.scored_total += 1
        scores = score_frame(stats, frame,
                             grid_points=self.config.grid_points or None)
        if self.config.ml_probe and stats.get("probe"):
            scores["ml_acc"] = ml_efficacy_probe(stats, frame)
        return scores

    def _baseline(self, incumbent, stats: dict) -> dict:
        cached = self._baselines.get(incumbent.model_id)
        if cached is None:
            cached = self._score(stats, self.engine.snapshot())
            # one incumbent at a time: dropping the rest bounds the cache
            self._baselines = {incumbent.model_id: cached}
        return cached

    # ------------------------------------------------------------ budgets

    def _quality_rules(self) -> list:
        from fed_tgan_tpu.obs import slo

        path = self.config.budgets_path or slo.default_budgets_path()
        try:
            rules = slo.load_budgets(path)
        except slo.SLOError as exc:
            self._log(f"canary[{self.tenant}]: budgets unreadable ({exc}); "
                      "promoting unguarded")
            return []
        out = []
        for rule in rules:
            if not str(rule.get("metric", "")).startswith("quality/"):
                continue
            sel = (rule.get("select") or {}).get("tenant")
            if sel and sel not in ("*", self.tenant):
                continue
            out.append(rule)
        return out

    @staticmethod
    def _tripped(figures: dict, rules: list) -> list:
        tripped = []
        for rule in rules:
            value = figures.get(rule["metric"])
            if value is None:
                continue
            name = rule.get("name", rule["metric"])
            if "max" in rule and value > float(rule["max"]):
                tripped.append(name)
            elif "min" in rule and value < float(rule["min"]):
                tripped.append(name)
        return tripped

    # ----------------------------------------------------------- decision

    def consider(self) -> Optional[dict]:
        """One promotion poll.  Returns None when there is nothing new to
        decide (no candidate, or a quarantined/unloadable one), else the
        decision dict (``decision["promoted"]`` tells the caller whether
        to adopt the registry's new model)."""
        cand = self.registry.poll_candidate()
        if cand is None:
            return None
        if cand.fingerprint in self._quarantine:
            # the same rejected bytes re-published (or re-statted): skip
            # without re-scoring — the no-retry-storm contract
            self.registry.dismiss(cand)
            return None
        t0 = time.time()
        incumbent = self.registry.get()
        try:
            model = self.registry.load_candidate(cand)
        except Exception as exc:  # noqa: BLE001 — torn candidate
            self._log(f"canary[{self.tenant}]: candidate "
                      f"{cand.fingerprint} failed to load ({exc!r})")
            _emit_event("serve_reload_failed", tenant=self.tenant,
                        model_id=incumbent.model_id, error=repr(exc))
            self.registry.dismiss(cand)
            return None
        try:
            stats = self._reference_stats(incumbent)
            base = self._baseline(incumbent, stats)
            scores = self._score(stats, self.engine.shadow_snapshot(model))
        except Exception as exc:  # noqa: BLE001 — a candidate that cannot
            # be shadow-sampled is rejected, never promoted on faith
            return self._reject(cand, incumbent, None, None,
                                ["shadow_error"], t0, error=repr(exc))
        figures = {
            "quality/avg_jsd": scores["avg_jsd"],
            "quality/avg_wd": scores["avg_wd"],
            "quality/jsd_delta": scores["avg_jsd"] - base["avg_jsd"],
            "quality/wd_delta": scores["avg_wd"] - base["avg_wd"],
        }
        if scores.get("ml_acc") is not None \
                and base.get("ml_acc") is not None:
            figures["quality/ml_acc_delta"] = base["ml_acc"] - scores["ml_acc"]
        tripped = self._tripped(figures, self._quality_rules())
        if tripped:
            return self._reject(cand, incumbent, scores, base, tripped, t0,
                                figures=figures)
        self.registry.promote(model, cand)
        # the candidate is the incumbent now; its scores are the next
        # baseline for free (same stats, same shadow seed)
        self._baselines = {model.model_id: scores}
        with self._lock:
            self.promotions += 1
        decision = self._decision(True, cand, incumbent, scores, base,
                                  [], t0, figures=figures)
        _emit_event("promotion_promoted", **decision)
        self._log(f"canary[{self.tenant}]: promoted {cand.fingerprint} "
                  f"(jsd_delta={figures['quality/jsd_delta']:+.4f} "
                  f"wd_delta={figures['quality/wd_delta']:+.4f})")
        self.last_decision = decision
        return decision

    def _decision(self, promoted: bool, cand, incumbent, scores, base,
                  tripped: list, t0: float, figures: Optional[dict] = None,
                  error: Optional[str] = None) -> dict:
        per_column = {}
        if scores is not None and base is not None:
            for col, cur in scores["per_column"].items():
                b = base["per_column"].get(col, {}).get("value", 0.0)
                per_column[col] = {
                    "kind": cur["kind"],
                    "candidate": round(cur["value"], 6),
                    "baseline": round(b, 6),
                    "delta": round(cur["value"] - b, 6),
                }
        decision = {
            "promoted": promoted,
            "tenant": self.tenant,
            "candidate": cand.fingerprint,
            "model_id": incumbent.model_id,
            "tripped": list(tripped),
            "per_column": per_column,
            "seconds": round(time.time() - t0, 3),
        }
        if scores is not None:
            decision["avg_jsd"] = round(scores["avg_jsd"], 6)
            decision["avg_wd"] = round(scores["avg_wd"], 6)
        for key, val in (figures or {}).items():
            decision[key.split("/", 1)[1]] = round(val, 6)
        if error is not None:
            decision["error"] = error
        return decision

    def _reject(self, cand, incumbent, scores, base, tripped: list,
                t0: float, figures: Optional[dict] = None,
                error: Optional[str] = None) -> dict:
        decision = self._decision(False, cand, incumbent, scores, base,
                                  tripped, t0, figures=figures, error=error)
        with self._lock:
            self._quarantine[cand.fingerprint] = decision
            self.rejections += 1
        self.registry.dismiss(cand)
        _emit_event("promotion_rejected", **decision)
        self._log(f"canary[{self.tenant}]: REJECTED candidate "
                  f"{cand.fingerprint} (tripped: {', '.join(tripped)}); "
                  f"keeping {incumbent.model_id}")
        self.last_decision = decision
        return decision

    # ------------------------------------------------------------- status

    def status(self) -> dict:
        """Candidate/promotion state for /healthz and /fleet."""
        with self._lock:
            return {
                "mode": "canary",
                "promotions": self.promotions,
                "rejections": self.rejections,
                "quarantined": sorted(self._quarantine),
                "last_decision": self.last_decision,
            }
