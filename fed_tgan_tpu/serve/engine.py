"""Compiled batched sampling engine behind the serving layer.

One jitted program per (batch-bucket, conditional?) fuses the generator
forward pass, the conditional-vector draw, gumbel activation, and the
device-side inverse transform — a request costs one device dispatch plus
one (n, n_columns) host transfer.

Determinism contract: rows form a virtual stream addressed by
``(seed, row_offset)``.  Step ``s`` of stream ``seed`` is generated with
``fold_in(key(seed + key_offset), s)`` — a pure function of the absolute
step index, never of the request that happened to cover it — so N rows
fetched in K chunks are bit-identical to one N-row draw, and bucket
padding (requests are rounded up to power-of-two step counts so the
compiled-program set stays tiny) can never perturb earlier rows.

Conditional sampling (CTGAN's generation-time knob: fix one discrete
column to a chosen option) swaps the empirical conditional draw for a
constant one-hot; the condition position is a traced scalar, so every
(column, value) pair shares one compiled program per bucket.

Program identity (the fleet-sharing refactor): a bucket program's trace
depends only on the encoded LAYOUT — output_info, the decode layout
shape, batch/embedding/generator dims, precision — never on a model's
constants.  Decode tables (GMM mode means/stds, code tables) ride in as
runtime arguments (``ops.decode.make_layout_decode``), so hot reloads
that keep the layout keep every compiled program, and tenants with equal
layouts can share ONE compiled program per bucket through the fleet's
LRU cache.  The result lands in a DONATED output scratch
(``lax.dynamic_update_slice`` + ``donate_argnums``), so steady-state
sampling writes into rotated buffers instead of allocating fresh output
per dispatch — the donation alias is a contract requirement
(``donation_required``), not an accident.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple, Optional

import numpy as np

from fed_tgan_tpu.analysis.sanitizers import hot_region
from fed_tgan_tpu.serve.naming import fleet_bucket_name, layout_tag
from fed_tgan_tpu.serve.registry import LoadedModel


class ConditionError(ValueError):
    """Unknown column / value for a conditional sampling request."""


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def build_bucket_program(spec, cfg, layout, n_steps: int, conditional: bool,
                         tag: Optional[str] = None):
    """The un-jitted ``n_steps``-step bucket program: fused generator
    forward + conditional draw + gumbel activation + device decode over
    runtime ``tables`` (``layout`` None skips decode and returns the
    activated encoded matrix — the raw-output form).  Named via
    :func:`~fed_tgan_tpu.serve.naming.fleet_bucket_name` so the sanitizer
    compile budget and the IR contracts key off the same identity
    (``tag=None`` keeps the pre-fleet single-model names).

    Signature of the returned function:
    ``run(params_g, state_g, cond, key, start, pos, tables, out)`` where
    ``tables`` matches ``ops.decode.decode_tables`` for ``layout`` and
    ``out`` is an output-shaped float32 scratch the caller donates
    (``donate_argnums=7``) — the program writes the result into it via
    ``dynamic_update_slice``, which is what makes the donation alias
    lower (an unused donated arg is DCE'd out of the program).
    """
    import jax
    import jax.numpy as jnp

    from fed_tgan_tpu.models.ctgan import generator_apply
    from fed_tgan_tpu.ops.decode import make_layout_decode
    from fed_tgan_tpu.ops.segments import apply_activate
    from fed_tgan_tpu.runtime.precision import resolve_precision

    B, emb = cfg.batch_size, cfg.embedding_dim
    # getattr: cfg may be a pre-precision TrainConfig restored from an old
    # saved model artifact — those trained (and serve) in f32
    pol = resolve_precision(getattr(cfg, "precision", "f32"))
    decode = make_layout_decode(layout) if layout is not None else None

    def run(params_g, state_g, cond, key, start, pos, tables, out):
        # one step == make_sample_step's draw exactly (kz/kc/ka split
        # order), so the unconditional stream is bit-identical to
        # SavedSynthesizer.sample_encoded's schedule
        def single(k):
            kz, kc, ka = jax.random.split(k, 3)
            z = jax.random.normal(kz, (B, emb))
            if spec.n_discrete > 0:
                if conditional:
                    c = jnp.broadcast_to(
                        (jnp.arange(spec.n_opt) == pos)
                        .astype(z.dtype)[None, :],
                        (B, spec.n_opt),
                    )
                else:
                    c = cond.sample_empirical(kc, B)
                z = jnp.concatenate([z, c], axis=1)
            raw, _ = generator_apply(
                pol.cast(params_g), state_g, pol.cast(z), train=False)
            return apply_activate(raw, spec, ka)

        def body(carry, i):
            return carry, single(jax.random.fold_in(key, start + i))

        _, enc = jax.lax.scan(body, None, jnp.arange(n_steps))
        # decode (quantile inverse transform) is an f32 island under bf16;
        # the cast is a traced no-op in f32 mode
        flat = enc.reshape(n_steps * B, -1).astype(jnp.float32)
        result = decode(flat, tables) if decode is not None else flat
        # write into the donated scratch: the full-buffer update makes the
        # scratch a USED operand, so the donation lowers as an output
        # alias instead of being dead-code-eliminated
        return jax.lax.dynamic_update_slice(out, result, (0, 0))

    # distinct compiled-program name per bucket, so the sanitizer compile
    # counter can assert "<= one compile per bucket" and the contracts
    # can key the fingerprint
    run.__name__ = fleet_bucket_name(n_steps, conditional, pol.name, 1, tag)
    run.__qualname__ = run.__name__
    return run


class EngineSnapshot(NamedTuple):
    """One consistent view of the engine's serving state, captured under
    the engine lock — everything a multi-chunk sample (or a fleet batch
    already formed for this model) needs, immune to a concurrent hot
    reload swapping fields out from under it mid-request."""

    model: LoadedModel
    spec: object
    cfg: object
    layout: tuple
    tables: tuple
    sig: tuple        # full trace identity (layout key)
    tag: Optional[str]


class SamplingEngine:
    """Offset-addressable deterministic sampling over one loaded model.

    ``program_cache`` (optional) is a fleet-shared LRU with a
    ``get_or_build(key, builder, est_bytes)`` contract; when given,
    bucket programs are keyed by the full layout signature and NAMED with
    its tag, so same-layout tenants resolve to one compiled program and
    different-layout ones cannot collide.  Without it the engine keeps
    its private dict (the single-model PR 3 shape, same legacy names).
    """

    def __init__(self, model: LoadedModel, max_chunk_steps: int = 128,
                 program_cache=None):
        self.max_chunk_steps = max_chunk_steps
        self._cache = program_cache
        self._programs: dict = {}
        # dead output buffers by shape, rotated back in as donated scratch
        # once their host copy has completed (at most 2 live per shape)
        self._scratch: dict = {}
        # HTTP handler threads read (resolve_condition, self.model) while
        # the batch worker swaps models / fills the program cache — the
        # lock makes adoption atomic w.r.t. readers (jaxlint J05)
        self._lock = threading.RLock()
        self._adopt_fields(model)

    def _adopt_fields(self, model: LoadedModel) -> None:
        import jax

        from fed_tgan_tpu.ops.decode import decode_layout, decode_tables

        self.model = model
        synth = model.synth
        self.spec, self.cfg = synth.spec, synth.cfg
        columns = synth.transformer.columns
        self._layout = decode_layout(columns)
        # one h2d put at adopt time, not one per dispatch
        self._tables = jax.device_put(decode_tables(columns))
        self._sig = self.layout_key(model)
        self._tag = layout_tag(self._sig) if self._cache is not None else None

    @staticmethod
    def layout_key(model: LoadedModel) -> tuple:
        """Everything a bucket program's TRACE depends on — and nothing a
        model's constants feed.  Equal keys => identical lowered programs
        (decode tables are runtime arguments), which is the fleet's
        cross-tenant sharing criterion and the reload keep-programs one."""
        from fed_tgan_tpu.ops.decode import decode_layout

        synth = model.synth
        cfg = synth.cfg
        return (
            tuple(synth.transformer.output_info),
            decode_layout(synth.transformer.columns),
            int(cfg.batch_size), int(cfg.embedding_dim),
            tuple(cfg.gen_dims),
            getattr(cfg, "precision", "f32"),
        )

    def adopt(self, model: LoadedModel) -> bool:
        """Swap in a hot-reloaded model.  When the layout signature is
        unchanged (the common keep-training case) every compiled program
        is kept — new params and new decode tables are just new arguments
        — and adoption is free; otherwise the private program dict is
        dropped (a shared fleet cache is left alone: other tenants may
        still serve from those entries, and stale ones age out via LRU).
        Returns whether the programs were kept."""
        with self._lock:
            same_shape = self.layout_key(model) == self._sig
            if not same_shape:
                self._programs = {}
                self._scratch = {}
            self._adopt_fields(model)
            return same_shape

    # ------------------------------------------------------------ programs

    def snapshot(self) -> EngineSnapshot:
        """Capture one consistent serving state under the lock.  A sample
        (or a fleet batch) formed against this snapshot keeps using the
        SAME model/tables/programs even if a hot reload adopts a new
        model mid-flight — the reload-under-fire safety contract."""
        with self._lock:
            return EngineSnapshot(self.model, self.spec, self.cfg,
                                  self._layout, self._tables, self._sig,
                                  self._tag)

    def shadow_snapshot(self, model: LoadedModel) -> EngineSnapshot:
        """A snapshot over a CANDIDATE model, without adopting it — the
        canary gate samples shadow rows through the exact serving path
        while every serving field stays untouched.  A candidate with the
        serving layout (the common keep-training case) reuses the serving
        sig/tag and therefore every compiled bucket program — zero extra
        compiles; a different layout gets its own tag so shadow programs
        never collide with serving ones under the sanitizer's
        one-compile-per-name budget."""
        import jax

        from fed_tgan_tpu.ops.decode import decode_layout, decode_tables

        sig = self.layout_key(model)
        columns = model.synth.transformer.columns
        layout = decode_layout(columns)
        tables = jax.device_put(decode_tables(columns))
        with self._lock:
            tag = self._tag if sig == self._sig else layout_tag(sig)
        return EngineSnapshot(model, model.synth.spec, model.synth.cfg,
                              layout, tables, sig, tag)

    def _program(self, snap: EngineSnapshot, n_steps: int,
                 conditional: bool):
        key = (n_steps, conditional, snap.sig)

        def build():
            import jax

            run = build_bucket_program(snap.spec, snap.cfg, snap.layout,
                                       n_steps, conditional, tag=snap.tag)
            return jax.jit(run, donate_argnums=7)

        if self._cache is not None:
            B = snap.cfg.batch_size
            n_cols = len(snap.layout)
            # rough live-footprint estimate: encoded intermediate + output
            est = n_steps * B * (snap.spec.dim + n_cols) * 4
            return self._cache.get_or_build(key, build, est_bytes=est)
        with self._lock:
            if key not in self._programs:
                self._programs[key] = build()
            return self._programs[key]

    def _chunk_plan(self, first_step: int, total_steps: int):
        """(start_step, n_steps) chunks covering ``total_steps`` from
        ``first_step``: full ``max_chunk_steps`` blocks, then a power-of-two
        bucketed tail — compiled step counts are only 1, 2, 4, ...,
        max_chunk_steps regardless of request sizes."""
        plan, start = [], first_step
        end = first_step + total_steps
        while start < end:
            remaining = end - start
            steps = (self.max_chunk_steps if remaining >= self.max_chunk_steps
                     else min(_pow2(remaining), self.max_chunk_steps))
            plan.append((start, steps))
            start += steps
        return plan

    # ------------------------------------------------------- scratch pool

    def _scratch_take(self, shape: tuple):
        """A donated-output scratch for ``shape``: a dead buffer from the
        pool when one exists (its host copy completed), else a fresh
        zeros.  Donation invalidates whatever we hand out, so a buffer is
        either in the pool or owned by exactly one dispatch."""
        import jax.numpy as jnp

        with self._lock:
            bufs = self._scratch.get(shape)
            if bufs:
                return bufs.pop()
        return jnp.zeros(shape, jnp.float32)

    def _scratch_give(self, buf) -> None:
        shape = tuple(buf.shape)
        with self._lock:
            bufs = self._scratch.setdefault(shape, [])
            if len(bufs) < 2:  # double-buffered dispatch: 2 covers it
                bufs.append(buf)

    # ------------------------------------------------------------ sampling

    def resolve_condition(self, column: str, value) -> int:
        """(column name, raw category value) -> conditional-vector position.

        Called from HTTP handler threads; holds the engine lock so the
        (meta, columns, encoders) triple is read from ONE model, never a
        half-adopted mix."""
        with self._lock:
            return self._resolve_condition_locked(column, value)

    def _resolve_condition_locked(self, column: str, value) -> int:
        from fed_tgan_tpu.features.transformer import DiscreteColumn

        meta = self.model.meta
        columns = self.model.synth.transformer.columns
        # the i-th transformer column IS the i-th meta column — the exact
        # correspondence decode_matrix decodes by (transformer names are
        # positional in the standalone path, so resolve via the meta)
        names = list(meta.column_names)
        if len(names) != len(columns):
            raise ConditionError(
                "conditional sampling unsupported for this table: encoded "
                f"layout has {len(columns)} columns but the meta {len(names)} "
                "(date part-columns?)"
            )
        if column not in names:
            raise ConditionError(
                f"unknown column {column!r} (have {names})"
            )
        idx = names.index(column)
        tcol = columns[idx]
        if not isinstance(tcol, DiscreteColumn):
            raise ConditionError(
                f"column {column!r} is continuous; conditional sampling "
                "fixes a DISCRETE column to one of its options"
            )
        cats = list(meta.categorical_columns)
        if column not in cats:
            raise ConditionError(f"column {column!r} has no encoder")
        enc = self.model.encoders[cats.index(column)]
        try:
            code = int(enc.transform([value])[0])
        except ValueError:
            try:  # HTTP query params arrive as strings; retry coerced
                code = int(enc.transform([str(value)])[0])
            except ValueError as exc:
                raise ConditionError(str(exc)) from None
        slots = np.flatnonzero(np.asarray(tcol.codes) == code)
        if not len(slots):
            raise ConditionError(
                f"value {value!r} of column {column!r} never occurred in "
                "training data (no generator slot)"
            )
        # every softmax segment is one transformer column, in column order,
        # so the column index IS the conditional-column index
        return int(self.spec.cond_offsets[idx]) + int(slots[0])

    def sample_decoded(self, n: int, seed: int = 0, offset: int = 0,
                       condition: Optional[int] = None,
                       snap: Optional[EngineSnapshot] = None,
                       stages: Optional[dict] = None) -> np.ndarray:
        """Rows [offset, offset + n) of stream ``seed`` as the decoded
        numeric (n, n_columns) matrix (device decode, float32).

        ``condition``: a position from :meth:`resolve_condition`, or None
        for the empirical conditional draw (the reference's sampling).
        ``snap``: an :class:`EngineSnapshot` to sample against (defaults
        to a fresh one) — the whole multi-chunk draw reads ONE model.
        ``stages``: optional stage-attribution accumulator ({stage:
        seconds}, see :data:`~.metrics.STAGES`) — host ``perf_counter``
        deltas only, never a device sync, so it composes with the
        sanitizers' transfer guard."""
        import jax

        if n <= 0:
            raise ValueError(f"n={n}: need at least one row")
        if offset < 0:
            raise ValueError(f"offset={offset}: must be >= 0")
        if snap is None:
            snap = self.snapshot()
        B = snap.cfg.batch_size
        synth = snap.model.synth
        first_step, skip = divmod(offset, B)
        total_steps = -(-(skip + n) // B)
        key = jax.random.key(seed + synth.key_offset)
        conditional = condition is not None
        pos = np.int32(condition if conditional else 0)

        out, pending = [], []

        def harvest(buf) -> np.ndarray:
            host = np.asarray(buf)   # host copy done: buffer is dead now
            self._scratch_give(buf)  # rotate it back in as donated scratch
            return host

        t_dispatch = time.perf_counter()
        for start, steps in self._chunk_plan(first_step, total_steps):
            # double-buffered like SampleProgramCache.sample: chunk i+1
            # computes while chunk i transfers, at most 2 buffers live
            prog = self._program(snap, steps, conditional)
            scratch = self._scratch_take((steps * B, len(snap.layout)))
            with hot_region(f"serve.engine[{steps}"
                            f"{'c' if conditional else ''}]"):
                chunk = prog(
                    synth.params_g, synth.state_g, synth.cond, key, start,
                    pos, snap.tables, scratch
                )
            chunk.copy_to_host_async()
            pending.append(chunk)
            if len(pending) == 2:
                out.append(harvest(pending.pop(0)))
        out.extend(harvest(p) for p in pending)
        result = np.concatenate(out, axis=0)[skip:skip + n]
        if stages is not None:
            # the whole chunk loop is "dispatch": device compute plus
            # the host copies that complete it (the harvest is the
            # chunk's natural sync point)
            stages["dispatch"] = (stages.get("dispatch", 0.0)
                                  + time.perf_counter() - t_dispatch)
        return result

    def sample_frame(self, n: int, seed: int = 0, offset: int = 0,
                     condition: Optional[int] = None,
                     snap: Optional[EngineSnapshot] = None,
                     stages: Optional[dict] = None):
        """Decoded raw-format DataFrame (categories as strings, dates
        rejoined) — exactly what the one-shot CSV path writes."""
        from fed_tgan_tpu.data.decode import decode_matrix

        if snap is None:
            snap = self.snapshot()
        mat = self.sample_decoded(n, seed=seed, offset=offset,
                                  condition=condition, snap=snap,
                                  stages=stages)
        t_decode = time.perf_counter()
        frame = decode_matrix(mat, snap.model.meta, snap.model.encoders)
        if stages is not None:
            stages["decode"] = (stages.get("decode", 0.0)
                                + time.perf_counter() - t_decode)
        return frame

    def sample_csv_bytes(self, n: int, seed: int = 0, offset: int = 0,
                         condition: Optional[int] = None,
                         header: bool = True,
                         snap: Optional[EngineSnapshot] = None,
                         stages: Optional[dict] = None) -> bytes:
        """CSV bytes with the same formatting as ``data.csvio.write_csv``
        (the one-shot file), so served output is byte-comparable to it."""
        from fed_tgan_tpu.data.csvio import csv_bytes

        frame = self.sample_frame(n, seed=seed, offset=offset,
                                  condition=condition, snap=snap,
                                  stages=stages)
        t_ser = time.perf_counter()
        out = csv_bytes(frame)
        if not header:
            out = out.split(b"\n", 1)[1]
        if stages is not None:
            stages["serialize"] = (stages.get("serialize", 0.0)
                                   + time.perf_counter() - t_ser)
        return out

    def sample_csv_segments(self, n: int, seed: int = 0, offset: int = 0,
                            condition: Optional[int] = None,
                            snap: Optional[EngineSnapshot] = None,
                            stages: Optional[dict] = None):
        """``(header_line, [row_line, ...])`` for rows [offset, offset+n).

        Per-row byte segments of the exact :meth:`sample_csv_bytes` output
        (same frame, same writer): ``header + b"".join(rows)`` equals the
        ``header=True`` response and ``b"".join(rows)`` the ``header=False``
        one.  Row bytes are a pure function of the row's absolute stream
        position (the determinism contract), so the serving row pool can
        stitch any contiguous slice of cached segments into a response that
        is bit-identical to a cold dispatch.  Raises :class:`ValueError`
        when the frame is not row-sliceable (see ``csvio.csv_segments``)."""
        from fed_tgan_tpu.data.csvio import csv_segments

        frame = self.sample_frame(n, seed=seed, offset=offset,
                                  condition=condition, snap=snap,
                                  stages=stages)
        t_ser = time.perf_counter()
        header_line, rows = csv_segments(frame)
        if stages is not None:
            stages["serialize"] = (stages.get("serialize", 0.0)
                                   + time.perf_counter() - t_ser)
        return header_line, rows
