"""Compiled batched sampling engine behind the serving layer.

One jitted program per (batch-bucket, conditional?) fuses the generator
forward pass, the conditional-vector draw, gumbel activation, and the
device-side inverse transform (``ops.decode.make_device_decode``) — a
request costs one device dispatch plus one (n, n_columns) host transfer.

Determinism contract: rows form a virtual stream addressed by
``(seed, row_offset)``.  Step ``s`` of stream ``seed`` is generated with
``fold_in(key(seed + key_offset), s)`` — a pure function of the absolute
step index, never of the request that happened to cover it — so N rows
fetched in K chunks are bit-identical to one N-row draw, and bucket
padding (requests are rounded up to power-of-two step counts so the
compiled-program set stays tiny) can never perturb earlier rows.

Conditional sampling (CTGAN's generation-time knob: fix one discrete
column to a chosen option) swaps the empirical conditional draw for a
constant one-hot; the condition position is a traced scalar, so every
(column, value) pair shares one compiled program per bucket.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from fed_tgan_tpu.analysis.sanitizers import hot_region
from fed_tgan_tpu.serve.naming import serve_bucket_name
from fed_tgan_tpu.serve.registry import LoadedModel


class ConditionError(ValueError):
    """Unknown column / value for a conditional sampling request."""


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def build_bucket_program(spec, cfg, decode_fn, n_steps: int,
                         conditional: bool):
    """The un-jitted ``n_steps``-step bucket program: fused generator
    forward + conditional draw + gumbel activation (+ device decode when
    ``decode_fn`` is given; None returns the activated encoded matrix --
    the contracts harness lowers that form without a trained
    transformer).  Named via :func:`serve_bucket_name` so the sanitizer
    compile budget and the IR contracts key off the same identity.

    Signature of the returned function:
    ``run(params_g, state_g, cond, key, start, pos)``.
    """
    import jax
    import jax.numpy as jnp

    from fed_tgan_tpu.models.ctgan import generator_apply
    from fed_tgan_tpu.ops.segments import apply_activate
    from fed_tgan_tpu.runtime.precision import resolve_precision

    B, emb = cfg.batch_size, cfg.embedding_dim
    # getattr: cfg may be a pre-precision TrainConfig restored from an old
    # saved model artifact — those trained (and serve) in f32
    pol = resolve_precision(getattr(cfg, "precision", "f32"))

    def run(params_g, state_g, cond, key, start, pos):
        # one step == make_sample_step's draw exactly (kz/kc/ka split
        # order), so the unconditional stream is bit-identical to
        # SavedSynthesizer.sample_encoded's schedule
        def single(k):
            kz, kc, ka = jax.random.split(k, 3)
            z = jax.random.normal(kz, (B, emb))
            if spec.n_discrete > 0:
                if conditional:
                    c = jnp.broadcast_to(
                        (jnp.arange(spec.n_opt) == pos)
                        .astype(z.dtype)[None, :],
                        (B, spec.n_opt),
                    )
                else:
                    c = cond.sample_empirical(kc, B)
                z = jnp.concatenate([z, c], axis=1)
            raw, _ = generator_apply(
                pol.cast(params_g), state_g, pol.cast(z), train=False)
            return apply_activate(raw, spec, ka)

        def body(carry, i):
            return carry, single(jax.random.fold_in(key, start + i))

        _, out = jax.lax.scan(body, None, jnp.arange(n_steps))
        # decode (quantile inverse transform) is an f32 island under bf16;
        # the cast is a traced no-op in f32 mode
        flat = out.reshape(n_steps * B, -1).astype(jnp.float32)
        return decode_fn(flat) if decode_fn is not None else flat

    # distinct compiled-program name per bucket, so the sanitizer compile
    # counter can assert "<= one compile per bucket" and the contracts
    # can key the fingerprint
    run.__name__ = serve_bucket_name(n_steps, conditional, pol.name)
    run.__qualname__ = run.__name__
    return run


class SamplingEngine:
    """Offset-addressable deterministic sampling over one loaded model."""

    def __init__(self, model: LoadedModel, max_chunk_steps: int = 128):
        self.max_chunk_steps = max_chunk_steps
        self._programs: dict = {}
        # HTTP handler threads read (resolve_condition, self.model) while
        # the batch worker swaps models / fills the program cache — the
        # lock makes adoption atomic w.r.t. readers (jaxlint J05)
        self._lock = threading.RLock()
        self._adopt_fields(model)

    def _adopt_fields(self, model: LoadedModel) -> None:
        from fed_tgan_tpu.ops.decode import make_device_decode

        self.model = model
        synth = model.synth
        self.spec, self.cfg = synth.spec, synth.cfg
        self._decode_fn = make_device_decode(synth.transformer.columns)

    def adopt(self, model: LoadedModel) -> bool:
        """Swap in a hot-reloaded model.  When the encoded layout and
        sampling config are unchanged (the common keep-training case) the
        compiled programs are kept — new params are just new arguments —
        and adoption is free; otherwise the program cache is rebuilt.
        Returns whether the programs were kept."""
        with self._lock:
            same_shape = (
                model.synth.transformer.output_info
                == self.model.synth.transformer.output_info
                and model.synth.cfg == self.cfg
                and self._decode_plan_signature(model)
                == self._decode_plan_signature(self.model)
            )
            if not same_shape:
                self._programs = {}
            self._adopt_fields(model)
            return same_shape

    @staticmethod
    def _decode_plan_signature(model: LoadedModel) -> tuple:
        """The decode constants a compiled program bakes in: GMM mode
        means/stds per continuous column, code tables per discrete one."""
        from fed_tgan_tpu.features.transformer import ContinuousColumn

        sig = []
        for col in model.synth.transformer.columns:
            if isinstance(col, ContinuousColumn):
                active = np.flatnonzero(col.gmm.active)
                sig.append(("cont", col.gmm.means[active].tobytes(),
                            col.gmm.stds[active].tobytes()))
            else:
                sig.append(("disc", np.asarray(col.codes).tobytes()))
        return tuple(sig)

    # ------------------------------------------------------------ programs

    def _program(self, n_steps: int, conditional: bool):
        key = (n_steps, conditional)
        with self._lock:
            return self._program_fill(key, n_steps, conditional)

    def _program_fill(self, key, n_steps: int, conditional: bool):
        # only ever called with self._lock held (see _program/adopt)
        if key not in self._programs:
            import jax

            run = build_bucket_program(
                self.spec, self.cfg, self._decode_fn, n_steps, conditional
            )
            with self._lock:  # re-entrant: callers already hold it
                self._programs[key] = jax.jit(run)
        return self._programs[key]

    def _chunk_plan(self, first_step: int, total_steps: int):
        """(start_step, n_steps) chunks covering ``total_steps`` from
        ``first_step``: full ``max_chunk_steps`` blocks, then a power-of-two
        bucketed tail — compiled step counts are only 1, 2, 4, ...,
        max_chunk_steps regardless of request sizes."""
        plan, start = [], first_step
        end = first_step + total_steps
        while start < end:
            remaining = end - start
            steps = (self.max_chunk_steps if remaining >= self.max_chunk_steps
                     else min(_pow2(remaining), self.max_chunk_steps))
            plan.append((start, steps))
            start += steps
        return plan

    # ------------------------------------------------------------ sampling

    def resolve_condition(self, column: str, value) -> int:
        """(column name, raw category value) -> conditional-vector position.

        Called from HTTP handler threads; holds the engine lock so the
        (meta, columns, encoders) triple is read from ONE model, never a
        half-adopted mix."""
        with self._lock:
            return self._resolve_condition_locked(column, value)

    def _resolve_condition_locked(self, column: str, value) -> int:
        from fed_tgan_tpu.features.transformer import DiscreteColumn

        meta = self.model.meta
        columns = self.model.synth.transformer.columns
        # the i-th transformer column IS the i-th meta column — the exact
        # correspondence decode_matrix decodes by (transformer names are
        # positional in the standalone path, so resolve via the meta)
        names = list(meta.column_names)
        if len(names) != len(columns):
            raise ConditionError(
                "conditional sampling unsupported for this table: encoded "
                f"layout has {len(columns)} columns but the meta {len(names)} "
                "(date part-columns?)"
            )
        if column not in names:
            raise ConditionError(
                f"unknown column {column!r} (have {names})"
            )
        idx = names.index(column)
        tcol = columns[idx]
        if not isinstance(tcol, DiscreteColumn):
            raise ConditionError(
                f"column {column!r} is continuous; conditional sampling "
                "fixes a DISCRETE column to one of its options"
            )
        cats = list(meta.categorical_columns)
        if column not in cats:
            raise ConditionError(f"column {column!r} has no encoder")
        enc = self.model.encoders[cats.index(column)]
        try:
            code = int(enc.transform([value])[0])
        except ValueError:
            try:  # HTTP query params arrive as strings; retry coerced
                code = int(enc.transform([str(value)])[0])
            except ValueError as exc:
                raise ConditionError(str(exc)) from None
        slots = np.flatnonzero(np.asarray(tcol.codes) == code)
        if not len(slots):
            raise ConditionError(
                f"value {value!r} of column {column!r} never occurred in "
                "training data (no generator slot)"
            )
        # every softmax segment is one transformer column, in column order,
        # so the column index IS the conditional-column index
        return int(self.spec.cond_offsets[idx]) + int(slots[0])

    def sample_decoded(self, n: int, seed: int = 0, offset: int = 0,
                       condition: Optional[int] = None) -> np.ndarray:
        """Rows [offset, offset + n) of stream ``seed`` as the decoded
        numeric (n, n_columns) matrix (device decode, float32).

        ``condition``: a position from :meth:`resolve_condition`, or None
        for the empirical conditional draw (the reference's sampling)."""
        import jax

        if n <= 0:
            raise ValueError(f"n={n}: need at least one row")
        if offset < 0:
            raise ValueError(f"offset={offset}: must be >= 0")
        B = self.cfg.batch_size
        synth = self.model.synth
        first_step, skip = divmod(offset, B)
        total_steps = -(-(skip + n) // B)
        key = jax.random.key(seed + synth.key_offset)
        conditional = condition is not None
        pos = np.int32(condition if conditional else 0)

        out, pending = [], []
        for start, steps in self._chunk_plan(first_step, total_steps):
            # double-buffered like SampleProgramCache.sample: chunk i+1
            # computes while chunk i transfers, at most 2 buffers live
            prog = self._program(steps, conditional)
            with hot_region(f"serve.engine[{steps}"
                            f"{'c' if conditional else ''}]"):
                chunk = prog(
                    synth.params_g, synth.state_g, synth.cond, key, start,
                    pos
                )
            chunk.copy_to_host_async()
            pending.append(chunk)
            if len(pending) == 2:
                out.append(np.asarray(pending.pop(0)))
        out.extend(np.asarray(p) for p in pending)
        return np.concatenate(out, axis=0)[skip:skip + n]

    def sample_frame(self, n: int, seed: int = 0, offset: int = 0,
                     condition: Optional[int] = None):
        """Decoded raw-format DataFrame (categories as strings, dates
        rejoined) — exactly what the one-shot CSV path writes."""
        from fed_tgan_tpu.data.decode import decode_matrix

        mat = self.sample_decoded(n, seed=seed, offset=offset,
                                  condition=condition)
        return decode_matrix(mat, self.model.meta, self.model.encoders)

    def sample_csv_bytes(self, n: int, seed: int = 0, offset: int = 0,
                         condition: Optional[int] = None,
                         header: bool = True) -> bytes:
        """CSV bytes with the same formatting as ``data.csvio.write_csv``
        (the one-shot file), so served output is byte-comparable to it."""
        from fed_tgan_tpu.data.csvio import csv_bytes

        frame = self.sample_frame(n, seed=seed, offset=offset,
                                  condition=condition)
        out = csv_bytes(frame)
        if not header:
            out = out.split(b"\n", 1)[1]
        return out
