"""Stable names for the serve engine's compiled bucket programs.

Three consumers key off these names and must never drift apart:

* the engine itself (``run.__name__`` of each jitted bucket program, so
  XLA compile logs carry the bucket identity);
* the runtime sanitizer's serving compile budget
  (``analysis.sanitizers.check_serving_budget`` counts programs by
  prefix);
* the IR program contracts (``analysis.contracts`` keys each lowered
  bucket fingerprint by this name, so a rename would otherwise read as
  "entrypoint vanished + new uncontracted entrypoint").

Pure stdlib -- importable from the lint/contract prong without JAX.
"""

from __future__ import annotations

SERVE_BUCKET_PREFIX = "serve_bucket_"


def serve_bucket_name(n_steps: int, conditional: bool) -> str:
    """Program name for the (power-of-two step bucket, conditional?) pair."""
    return f"{SERVE_BUCKET_PREFIX}{int(n_steps)}{'_cond' if conditional else ''}"
