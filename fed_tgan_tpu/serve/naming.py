"""Stable names for the serve engine's compiled bucket programs.

Three consumers key off these names and must never drift apart:

* the engine itself (``run.__name__`` of each jitted bucket program, so
  XLA compile logs carry the bucket identity);
* the runtime sanitizer's serving compile budget
  (``analysis.sanitizers.check_serving_budget`` counts programs by
  prefix);
* the IR program contracts (``analysis.contracts`` keys each lowered
  bucket fingerprint by this name, so a rename would otherwise read as
  "entrypoint vanished + new uncontracted entrypoint").

Pure stdlib -- importable from the lint/contract prong without JAX.
"""

from __future__ import annotations

SERVE_BUCKET_PREFIX = "serve_bucket_"


def serve_bucket_name(n_steps: int, conditional: bool,
                      precision: str = "f32") -> str:
    """Program name for the (power-of-two step bucket, conditional?) pair.

    ``precision`` suffixes non-f32 buckets (``_bf16``): a model trained
    under mixed precision serves through DIFFERENT programs than an f32
    one, and the contracts/compile-budget must see them as such.  f32
    names are unchanged from pre-precision builds."""
    suffix = "" if precision == "f32" else f"_{precision}"
    return (f"{SERVE_BUCKET_PREFIX}{int(n_steps)}"
            f"{'_cond' if conditional else ''}{suffix}")
