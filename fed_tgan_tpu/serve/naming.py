"""Stable names for the serve engine's compiled bucket programs.

Three consumers key off these names and must never drift apart:

* the engine itself (``run.__name__`` of each jitted bucket program, so
  XLA compile logs carry the bucket identity);
* the runtime sanitizer's serving compile budget
  (``analysis.sanitizers.check_serving_budget`` counts programs by
  prefix);
* the IR program contracts (``analysis.contracts`` keys each lowered
  bucket fingerprint by this name, so a rename would otherwise read as
  "entrypoint vanished + new uncontracted entrypoint").

Pure stdlib -- importable from the lint/contract prong without JAX.
"""

from __future__ import annotations

SERVE_BUCKET_PREFIX = "serve_bucket_"


def serve_bucket_name(n_steps: int, conditional: bool,
                      precision: str = "f32") -> str:
    """Program name for the (power-of-two step bucket, conditional?) pair.

    ``precision`` suffixes non-f32 buckets (``_bf16``): a model trained
    under mixed precision serves through DIFFERENT programs than an f32
    one, and the contracts/compile-budget must see them as such.  f32
    names are unchanged from pre-precision builds."""
    suffix = "" if precision == "f32" else f"_{precision}"
    return (f"{SERVE_BUCKET_PREFIX}{int(n_steps)}"
            f"{'_cond' if conditional else ''}{suffix}")


def layout_tag(layout_key) -> str:
    """8-hex content tag of a fleet layout key (any repr-stable value).

    The fleet's shared program cache keys compiled programs by the full
    trace identity — encoded layout, decode layout, batch/embedding/
    generator dims, precision — so tenants with the SAME tag share one
    compiled program per bucket while different-schema tenants get
    distinct program names (and the compile budget can still assert
    "<= one compile per name")."""
    import hashlib

    return hashlib.sha1(repr(layout_key).encode()).hexdigest()[:8]


def fleet_bucket_name(n_steps: int, conditional: bool,
                      precision: str = "f32", lanes: int = 1,
                      tag: str | None = None) -> str:
    """Program name for a fleet bucket: the single-model bucket name plus
    a ``_xL`` lane-width suffix for vmapped cross-tenant dispatches and a
    ``_L<tag>`` layout tag.  ``lanes=1, tag=None`` reduces exactly to
    :func:`serve_bucket_name` (the contracts' stable keys)."""
    name = serve_bucket_name(n_steps, conditional, precision)
    if lanes > 1:
        name += f"_x{int(lanes)}"
    if tag is not None:
        name += f"_L{tag}"
    return name
