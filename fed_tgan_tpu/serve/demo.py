"""Self-contained demo artifact builder for the serving layer.

Trains a deliberately tiny standalone synthesizer on a synthesized
mixed-type table and persists the full ``--save-model`` artifact layout
(``models/synthesizer`` + meta JSON + encoder pickle) — the doctor's
serving check, ``bench.py --workload serving``, and the hermetic service
tests all need a real loadable artifact without shipping data files or
paying a real training run.  Seconds on CPU: one epoch, batch 50,
embedding 16.
"""

from __future__ import annotations

import os
import pickle


def demo_frame(rows: int = 200, seed: int = 0):
    """Mixed-type table: continuous, non-negative, two categoricals."""
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "amount": np.exp(rng.normal(2.0, 1.0, rows)).round(2),
        "score": np.concatenate([
            rng.normal(-4.0, 0.5, rows // 2),
            rng.normal(3.0, 1.0, rows - rows // 2),
        ]),
        "color": rng.choice(["red", "green", "blue"], rows, p=[0.6, 0.3, 0.1]),
        "flag": rng.choice(["yes", "no"], rows, p=[0.8, 0.2]),
    })


def build_demo_artifact(out_dir: str, rows: int = 200, seed: int = 0,
                        epochs: int = 1, batch_size: int = 50,
                        embedding_dim: int = 16, name: str = "demo",
                        precision: str = "f32") -> str:
    """Train + persist the demo artifact under ``out_dir``; returns
    ``out_dir`` (resolvable by ``registry.resolve_artifact``).

    Mirrors the CLI standalone ``--save-model`` block: meta/encoders
    first, the synthesizer last, so the registry's meta-freshness check
    sees the healthy ordering.  ``precision`` rides into the persisted
    TrainConfig, so a served engine builds its bucket programs at the
    model's training precision (bf16 buckets compile separately and are
    contract-checked as ``serve_bucket_*_bf16``)."""
    from fed_tgan_tpu.data.encoders import encoder_artifact
    from fed_tgan_tpu.data.ingest import TablePreprocessor
    from fed_tgan_tpu.federation.init import harmonize_categories
    from fed_tgan_tpu.runtime.checkpoint import save_synthesizer
    from fed_tgan_tpu.train.standalone import StandaloneSynthesizer
    from fed_tgan_tpu.train.steps import TrainConfig

    pre = TablePreprocessor(
        frame=demo_frame(rows, seed), name=name,
        categorical_columns=["color", "flag"],
        non_negative_columns=["amount"],
    )
    meta, encoders, _ = harmonize_categories([pre.local_meta()])
    matrix, cat_idx, ord_idx = pre.encode(encoders)

    cfg = TrainConfig(batch_size=batch_size, embedding_dim=embedding_dim,
                      gen_dims=(32, 32), dis_dims=(32, 32),
                      precision=precision)
    synth = StandaloneSynthesizer(config=cfg, seed=seed)
    synth.fit(matrix, cat_idx, ord_idx, epochs=epochs)

    models_dir = os.path.join(out_dir, "models")
    os.makedirs(models_dir, exist_ok=True)
    table_meta = pre.global_table_meta(meta)
    table_meta.dump_json(os.path.join(models_dir, f"{name}.json"))
    with open(os.path.join(models_dir, f"label_encoders_{name}.pickle"),
              "wb") as f:
        pickle.dump(
            encoder_artifact(table_meta.categorical_columns, encoders), f)
    save_synthesizer(synth, os.path.join(models_dir, "synthesizer"))
    # reference statistics for the canary promotion gate: scored against
    # shadow samples from candidate checkpoints at serve time
    from fed_tgan_tpu.serve.canary import (compute_reference_stats,
                                           reference_stats_path,
                                           write_reference_stats)

    stats = compute_reference_stats(
        pre.frame, table_meta.categorical_columns, name=name,
        probe_rows=min(64, rows))
    write_reference_stats(stats, reference_stats_path(models_dir, name))
    return out_dir


def republish_demo_candidate(artifact_dir: str,
                             key_offset_bump: int = 1000) -> str:
    """Republish the artifact's synthesizer as a NEW generation with the
    same learned parameters but a bumped sampling-key offset: a fresh
    checkpoint fingerprint whose output distribution is identical in
    law.  The canary gate should always promote it — tests, the bench
    canary workload, and the doctor all use this as the 'clean
    candidate' against the degraded one."""
    from fed_tgan_tpu.runtime.checkpoint import (load_synthesizer,
                                                 save_synthesizer)

    path = os.path.join(artifact_dir, "models", "synthesizer")
    synth = load_synthesizer(path)
    synth.key_offset += int(key_offset_bump)
    save_synthesizer(synth, path)
    return path
