"""Mode-specific normalization (the CTGAN input encoding).

Behavioral equivalent of the reference ``BGM_CTGAN_Transformer``
(reference Server/dtds/features/transformers.py:310-464):

- continuous column -> scalar ``(x - mu_k)/(4 sigma_k)`` for a posterior-
  sampled active mode k (clipped to +-0.99, 'tanh' segment) plus a one-hot
  over active modes ('softmax' segment);
- categorical/ordinal column -> one-hot over its categories, slot order =
  frequency order (the ``i2s`` order).

All per-row Python loops of the reference are replaced by vectorized numpy
(the mode pick uses the inverse-CDF trick instead of per-row
``np.random.choice``, distributionally identical).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.schema import TableMeta
from fed_tgan_tpu.features.bgm import (
    N_CLUSTERS,
    WEIGHT_EPS,
    ColumnGMM,
    fit_column_gmms,
)

CLIP = 0.99
SCALE = 4.0  # the reference's (x - mu) / (4 sigma)


@dataclass
class ContinuousColumn:
    name: str
    gmm: ColumnGMM


@dataclass
class DiscreteColumn:
    name: str
    codes: np.ndarray  # slot -> integer code, in frequency order

    @property
    def size(self) -> int:
        return len(self.codes)


class ModeNormalizer:
    """fit/refit/transform/inverse_transform for one table."""

    def __init__(
        self,
        n_components: int = N_CLUSTERS,
        eps: float = WEIGHT_EPS,
        backend: str = "sklearn",
        seed: Optional[int] = None,
    ):
        self.n_components = n_components
        self.eps = eps
        self.backend = backend
        self.seed = seed
        self.columns: list[ContinuousColumn | DiscreteColumn] = []
        self.output_info: list[tuple[int, str]] = []
        self.output_dim: int = 0

    # ---------------------------------------------------------------- fit

    def fit(
        self,
        data: np.ndarray,
        categorical_idx: Sequence[int] = (),
        ordinal_idx: Sequence[int] = (),
        column_names: Optional[Sequence[str]] = None,
        column_gmms: Optional[dict] = None,
    ) -> "ModeNormalizer":
        """Fit per-column models on a (rows, cols) numeric matrix.

        Discrete slot order is local frequency order, like the reference's
        ``get_metadata`` (transformers.py:22-29).  ``column_gmms`` injects
        already-fitted continuous models (column index -> ColumnGMM) — the
        cohort-batched onboarding path fits whole client batches in one
        device program (``bgm_jax.fit_shards_jax``) and installs the results
        here, so per-client ``fit`` does only the cheap discrete bookkeeping.
        """
        data = np.asarray(data, dtype=np.float64)
        discrete = set(categorical_idx) | set(ordinal_idx)
        # GMM fits dominate init wall-clock; fit all continuous columns in a
        # process pool (bit-identical to the serial loop — same estimator,
        # same seed per column)
        cont_idx = [j for j in range(data.shape[1]) if j not in discrete]
        if column_gmms is not None:
            missing = [j for j in cont_idx if j not in column_gmms]
            if missing:
                raise ValueError(
                    f"column_gmms missing continuous columns {missing}"
                )
            gmms = {j: column_gmms[j] for j in cont_idx}
        else:
            gmms = dict(zip(cont_idx, fit_column_gmms(
                [data[:, j] for j in cont_idx],
                self.n_components, self.eps, self.backend, self.seed,
            )))
        self.columns = []
        for j in range(data.shape[1]):
            name = column_names[j] if column_names is not None else str(j)
            if j in discrete:
                col = data[:, j]
                values, counts = np.unique(col.astype(np.int64), return_counts=True)
                order = np.argsort(-counts, kind="stable")
                self.columns.append(DiscreteColumn(name, values[order]))
            else:
                self.columns.append(ContinuousColumn(name, gmms[j]))
        self._finalize()
        return self

    def refit_with_global(
        self,
        global_meta: TableMeta,
        encoders: Sequence[CategoryEncoder],
        gmms: Sequence[Optional[ColumnGMM]],
    ) -> "ModeNormalizer":
        """Install the server-aggregated global models.

        Equivalent of the reference's ``refit`` + ``get_metadata_refit``
        (transformers.py:359-376, :41-71): categorical slot order becomes the
        *global* frequency order (the harmonized ``i2s`` mapped through the
        global label encoder), continuous modes come from the pooled global
        GMMs, so every client agrees on output_dim and one-hot layout.
        """
        self.columns = []
        enc_cursor = 0
        for j, cmeta in enumerate(global_meta.columns):
            if cmeta.is_continuous:
                gmm = gmms[j]
                assert gmm is not None, f"missing global GMM for column {cmeta.name}"
                self.columns.append(ContinuousColumn(cmeta.name, gmm))
            else:
                raw = [str(v) for v in cmeta.i2s]
                codes = encoders[enc_cursor].transform(raw)
                enc_cursor += 1
                self.columns.append(DiscreteColumn(cmeta.name, codes))
        self._finalize()
        return self

    def _finalize(self) -> None:
        self.output_info = []
        self.output_dim = 0
        for col in self.columns:
            if isinstance(col, ContinuousColumn):
                n_active = col.gmm.n_active
                self.output_info += [(1, "tanh"), (n_active, "softmax")]
                self.output_dim += 1 + n_active
            else:
                self.output_info += [(col.size, "softmax")]
                self.output_dim += col.size

    # ---------------------------------------------------------- transform

    def transform(
        self, data: np.ndarray, rng: Optional[np.random.Generator] = None
    ) -> np.ndarray:
        data = np.asarray(data, dtype=np.float64)
        rng = rng or np.random.default_rng()
        n = len(data)
        parts: list[np.ndarray] = []
        for j, col in enumerate(self.columns):
            x = data[:, j]
            if isinstance(col, ContinuousColumn):
                gmm = col.gmm
                z = (x[:, None] - gmm.means[None, :]) / (SCALE * gmm.stds[None, :])
                z = z[:, gmm.active]
                probs = gmm.predict_proba(x)[:, gmm.active]
                pp = probs + 1e-6
                pp = pp / pp.sum(axis=1, keepdims=True)
                # inverse-CDF sample of the mode, one uniform per row
                r = rng.random((n, 1))
                sel = (np.cumsum(pp, axis=1) > r).argmax(axis=1)
                feat = np.clip(z[np.arange(n), sel], -CLIP, CLIP)
                onehot = np.zeros((n, gmm.n_active), dtype=np.float64)
                onehot[np.arange(n), sel] = 1.0
                parts += [feat[:, None], onehot]
            else:
                codes = x.astype(np.int64)
                if codes.size and (codes.min() < 0 or codes.max() > col.codes.max()):
                    raise ValueError(
                        f"column {col.name!r}: category code out of fitted range"
                    )
                slot_of_code = _slot_lookup(col.codes)
                slots = slot_of_code[codes]
                if (slots < 0).any():
                    raise ValueError(
                        f"column {col.name!r}: unseen category codes "
                        f"{sorted(set(codes[slots < 0].tolist()))[:10]}"
                    )
                onehot = np.zeros((n, col.size), dtype=np.float64)
                onehot[np.arange(n), slots] = 1.0
                parts.append(onehot)
        return np.concatenate(parts, axis=1).astype(np.float32)

    def inverse_transform(self, data: np.ndarray) -> np.ndarray:
        """Decode an encoded/generated matrix back to numeric column values.

        Continuous: ``u * 4 sigma_k + mu_k`` for the argmax active mode k
        (reference transformers.py:430-456).  Discrete: argmax slot -> code.
        """
        data = np.asarray(data, dtype=np.float64)
        n = len(data)
        out = np.zeros((n, len(self.columns)), dtype=np.float64)
        st = 0
        for j, col in enumerate(self.columns):
            if isinstance(col, ContinuousColumn):
                gmm = col.gmm
                u = np.clip(data[:, st], -1.0, 1.0)
                v = data[:, st + 1 : st + 1 + gmm.n_active]
                st += 1 + gmm.n_active
                active_idx = np.flatnonzero(gmm.active)
                k = active_idx[np.argmax(v, axis=1)]
                out[:, j] = u * SCALE * gmm.stds[k] + gmm.means[k]
            else:
                v = data[:, st : st + col.size]
                st += col.size
                out[:, j] = col.codes[np.argmax(v, axis=1)]
        return out

    # ------------------------------------------------------------- export

    @property
    def column_gmms(self) -> list[Optional[ColumnGMM]]:
        """Per-column GMMs (None for discrete) — what the federation init
        exchanges, like the reference's ``get_information`` (transformers.py:378)."""
        return [
            col.gmm if isinstance(col, ContinuousColumn) else None
            for col in self.columns
        ]

    def continuous_positions(self) -> list[int]:
        return [
            j for j, col in enumerate(self.columns) if isinstance(col, ContinuousColumn)
        ]


def _slot_lookup(codes: np.ndarray) -> np.ndarray:
    lookup = np.full(int(codes.max()) + 1, -1, dtype=np.int64)
    lookup[codes] = np.arange(len(codes))
    return lookup
