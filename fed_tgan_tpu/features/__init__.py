from fed_tgan_tpu.features.bgm import ColumnGMM, fit_column_gmm, fit_column_gmms
from fed_tgan_tpu.features.transformer import ModeNormalizer
from fed_tgan_tpu.features.zoo import (
    BGMTransformer,
    BinningTransformer,
    GMMTransformer,
    GridTransformer,
    MinMaxTransformer,
)

__all__ = [
    "BGMTransformer",
    "BinningTransformer",
    "ColumnGMM",
    "GMMTransformer",
    "GridTransformer",
    "MinMaxTransformer",
    "ModeNormalizer",
    "fit_column_gmm",
    "fit_column_gmms",
]
