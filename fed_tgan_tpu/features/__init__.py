from fed_tgan_tpu.features.bgm import ColumnGMM, fit_column_gmm
from fed_tgan_tpu.features.transformer import ModeNormalizer

__all__ = ["ColumnGMM", "ModeNormalizer", "fit_column_gmm"]
