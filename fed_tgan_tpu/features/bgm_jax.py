"""TPU-native variational Bayesian GMM fitting (one program, all columns).

The reference fits one sklearn ``BayesianGaussianMixture`` per continuous
column, serially on the host (reference Server/dtds/features/
transformers.py:331-340 and the federated refit
Server/dtds/distributed.py:743-746) — the dominant cost of federated
initialization (~30 s for Intrusion's 22 columns x (2 clients + global)).

This module reimplements the same model — truncated Dirichlet-process
mixture of 1-D Gaussians, variational inference with sklearn's update
equations and default priors — as a masked, ``vmap``-over-columns JAX
program: every column of every participant fits in ONE jitted call.
Ragged column lengths are handled by zero-masking padded rows, which is
exactly equivalent to fitting each column alone.

Differences from sklearn (documented, intentional):
- fixed ``max_iter`` sweeps instead of lower-bound early stopping (sklearn
  routinely hits max_iter on real columns anyway — the ConvergenceWarnings
  the reference emits);
- k-means init uses deterministic quantile seeding + Lloyd sweeps instead of
  sklearn's seeded k-means++, so mode assignments can differ on ties;
- float32 on device (TPU has no f64).  Mode means/stds typically agree with
  sklearn to ~1e-3 relative; mode COUNTS (weights > eps), which set model
  output dims, agree on well-separated data.  The sklearn backend remains
  the default for bit-parity with the reference.
"""

from __future__ import annotations

from functools import partial

import numpy as np

from fed_tgan_tpu.obs.trace import span as _span

N_KMEANS_ITERS = 20


def _fit_batch(x, mask, *, n_components, max_iter, reg_covar, wc_prior):
    """Variational DP-GMM for a batch of 1-D columns.

    x, mask: (N,) data and 0/1 validity (vmapped to (C, N) outside).
    Returns (means, stds, weights) each (K,).
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.scipy.special import digamma, logsumexp

    K = n_components
    n_valid = jnp.maximum(mask.sum(), 1.0)
    mean0 = (x * mask).sum() / n_valid
    # sklearn's default covariance_prior is the (ddof=1) sample covariance
    var0 = ((x - mean0) ** 2 * mask).sum() / jnp.maximum(n_valid - 1.0, 1.0)
    var0 = jnp.maximum(var0, reg_covar)

    # ---- deterministic k-means init: quantile seeds + Lloyd sweeps.
    # Padded entries sort to +inf; quantile indices stay inside the valid
    # prefix, so seeds come from real data only.
    big = jnp.where(mask > 0, x, jnp.inf)
    srt = jnp.sort(big)
    qidx = jnp.clip(
        ((jnp.arange(K) + 0.5) / K * n_valid).astype(jnp.int32), 0, x.shape[0] - 1
    )
    centers = srt[qidx]
    centers = jnp.where(jnp.isfinite(centers), centers, mean0)

    def lloyd(centers, _):
        d = (x[:, None] - centers[None, :]) ** 2
        assign = jnp.argmin(d, axis=1)
        onehot = (assign[:, None] == jnp.arange(K)[None, :]) * mask[:, None]
        cnt = onehot.sum(0)
        new = (onehot * x[:, None]).sum(0) / jnp.maximum(cnt, 1e-12)
        return jnp.where(cnt > 0, new, centers), None

    centers, _ = lax.scan(lloyd, centers, None, length=N_KMEANS_ITERS)

    d = (x[:, None] - centers[None, :]) ** 2
    resp = (jnp.argmin(d, axis=1)[:, None] == jnp.arange(K)[None, :]).astype(
        x.dtype
    ) * mask[:, None]

    # ---- variational sweeps (sklearn's update equations, 1-D case)
    mpp = 1.0  # mean_precision_prior
    dof0 = 1.0  # degrees_of_freedom_prior (= n_features)
    tiny = 10.0 * jnp.finfo(x.dtype).eps

    def m_step(resp):
        nk = resp.sum(0) + tiny
        xk = (resp * x[:, None]).sum(0) / nk
        sk = (resp * (x[:, None] - xk[None, :]) ** 2).sum(0) / nk + reg_covar
        # stick-breaking Beta posteriors (dirichlet_process)
        a = 1.0 + nk
        rev = jnp.cumsum(nk[::-1])[::-1]  # rev[k] = sum_{j>=k} nj
        b = wc_prior + jnp.concatenate([rev[1:], jnp.zeros((1,), x.dtype)])
        mean_prec = mpp + nk
        means = (mpp * mean0 + nk * xk) / mean_prec
        dof = dof0 + nk
        cov = (
            var0 + nk * sk + (nk * mpp / mean_prec) * (xk - mean0) ** 2
        ) / dof
        return nk, a, b, mean_prec, means, dof, cov

    def e_step(a, b, mean_prec, means, dof, cov):
        prec = 1.0 / cov
        log_gauss = -0.5 * (
            jnp.log(2.0 * jnp.pi) - jnp.log(prec)[None, :]
            + (x[:, None] - means[None, :]) ** 2 * prec[None, :]
        ) - 0.5 * jnp.log(dof)[None, :]
        log_lambda = jnp.log(2.0) + digamma(0.5 * dof)
        log_prob = log_gauss + 0.5 * (log_lambda - 1.0 / mean_prec)[None, :]
        dsum = digamma(a + b)
        log_w = digamma(a) - dsum + jnp.concatenate(
            [jnp.zeros((1,), x.dtype), jnp.cumsum(digamma(b) - dsum)[:-1]]
        )
        wlp = log_prob + log_w[None, :]
        return jnp.exp(wlp - logsumexp(wlp, axis=1, keepdims=True)) * mask[:, None]

    def sweep(resp, _):
        _, a, b, mean_prec, means, dof, cov = m_step(resp)
        return e_step(a, b, mean_prec, means, dof, cov), None

    resp, _ = lax.scan(sweep, resp, None, length=max_iter)
    _, a, b, mean_prec, means, dof, cov = m_step(resp)

    # sklearn's expected mixture weights under the stick-breaking posterior
    frac = a / (a + b)
    sticks = jnp.concatenate(
        [jnp.ones((1,), x.dtype), jnp.cumprod(b / (a + b))[:-1]]
    )
    weights = frac * sticks
    weights = weights / weights.sum()
    return means, jnp.sqrt(cov), weights, mean_prec, dof, a, b


def fit_columns_jax(
    columns: "list[np.ndarray]",
    n_components: int = 10,
    eps: float = 0.005,
    max_iter: int = 100,
    reg_covar: float = 1e-6,
    wc_prior: float = 0.001,
):
    """Fit every column in one jitted, vmapped program; returns ColumnGMMs."""
    import jax
    import jax.numpy as jnp

    from fed_tgan_tpu.features.bgm import ColumnGMM

    cols = [np.asarray(c, dtype=np.float32).reshape(-1) for c in columns]
    if not cols:
        return []
    # degenerate shards (< n_components samples) need the component clamp;
    # route those through the host fitter rather than slicing a K=10 fit
    small = {i for i, c in enumerate(cols) if len(c) < n_components}
    if small:
        from fed_tgan_tpu.features.bgm import fit_column_gmm

        out = [None] * len(cols)
        for i in small:
            out[i] = fit_column_gmm(cols[i], n_components, eps)
        rest = [i for i in range(len(cols)) if i not in small]
        fitted = fit_columns_jax(
            [cols[i] for i in rest], n_components, eps, max_iter, reg_covar,
            wc_prior,
        )
        for i, g in zip(rest, fitted):
            out[i] = g
        return out
    n_max = max(len(c) for c in cols)
    xs = np.zeros((len(cols), n_max), dtype=np.float32)
    masks = np.zeros((len(cols), n_max), dtype=np.float32)
    for i, c in enumerate(cols):
        xs[i, : len(c)] = c
        masks[i, : len(c)] = 1.0

    fit = jax.jit(
        jax.vmap(
            partial(
                _fit_batch,
                n_components=n_components,
                max_iter=max_iter,
                reg_covar=reg_covar,
                wc_prior=wc_prior,
            )
        )
    )
    # one batched transfer for all seven result arrays (jaxlint J01),
    # then the float64 view is a host-side dtype conversion
    with _span("init.bgm_fit_jax", columns=len(cols), n_max=n_max):
        means, stds, weights, mean_prec, dof, stick_a, stick_b = (
            np.asarray(r, dtype=np.float64)
            for r in jax.device_get(fit(jnp.asarray(xs), jnp.asarray(masks)))
        )
    out = []
    for i in range(len(cols)):
        w = weights[i]
        out.append(
            ColumnGMM(
                means=means[i],
                stds=np.maximum(stds[i], 1e-9),
                weights=w,
                active=w > eps,
                # posterior extras: predict_proba then evaluates the exact
                # variational E-step instead of the Gaussian approximation
                mean_precision=mean_prec[i],
                dof=dof[i],
                stick_a=stick_a[i],
                stick_b=stick_b[i],
            )
        )
    return out
