"""TPU-native variational Bayesian GMM fitting (one program, all columns).

The reference fits one sklearn ``BayesianGaussianMixture`` per continuous
column, serially on the host (reference Server/dtds/features/
transformers.py:331-340 and the federated refit
Server/dtds/distributed.py:743-746) — the dominant cost of federated
initialization (~30 s for Intrusion's 22 columns x (2 clients + global)).

This module reimplements the same model — truncated Dirichlet-process
mixture of 1-D Gaussians, variational inference with sklearn's update
equations and default priors — as a masked, ``vmap``-over-columns JAX
program: every column of every participant fits in ONE jitted call.
Ragged column lengths are handled by zero-masking padded rows, which is
exactly equivalent to fitting each column alone.

Differences from sklearn (documented, intentional):
- fixed ``max_iter`` sweeps instead of lower-bound early stopping (sklearn
  routinely hits max_iter on real columns anyway — the ConvergenceWarnings
  the reference emits);
- k-means init uses deterministic quantile seeding + Lloyd sweeps instead of
  sklearn's seeded k-means++, so mode assignments can differ on ties;
- float32 on device (TPU has no f64).  Mode means/stds typically agree with
  sklearn to ~1e-3 relative; mode COUNTS (weights > eps), which set model
  output dims, agree on well-separated data.  The sklearn backend remains
  the default for bit-parity with the reference.
"""

from __future__ import annotations

import functools
from functools import partial

import numpy as np

from fed_tgan_tpu.obs.trace import span as _span

N_KMEANS_ITERS = 20

# shape-bucketing knobs for the batched fit: rows pad up to a power of two
# (results are padding-independent — masking — so clients of slightly
# different shard sizes share one compiled program), and one dispatch is
# capped so the padded (batch, rows) f32 block stays under ~128 MiB
_ROWS_FLOOR = 64
_BATCH_FLOOR = 8
_MAX_BATCH_ELEMENTS = 1 << 25


def _pow2_at_least(n: int, floor: int) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def _fit_batch(x, mask, *, n_components, max_iter, reg_covar, wc_prior):
    """Variational DP-GMM for a batch of 1-D columns.

    x, mask: (N,) data and 0/1 validity (vmapped to (C, N) outside).
    Returns (means, stds, weights) each (K,).
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.scipy.special import digamma, logsumexp

    K = n_components
    n_valid = jnp.maximum(mask.sum(), 1.0)
    mean0 = (x * mask).sum() / n_valid
    # sklearn's default covariance_prior is the (ddof=1) sample covariance
    var0 = ((x - mean0) ** 2 * mask).sum() / jnp.maximum(n_valid - 1.0, 1.0)
    var0 = jnp.maximum(var0, reg_covar)

    # ---- deterministic k-means init: quantile seeds + Lloyd sweeps.
    # Padded entries sort to +inf; quantile indices stay inside the valid
    # prefix, so seeds come from real data only.
    big = jnp.where(mask > 0, x, jnp.inf)
    srt = jnp.sort(big)
    qidx = jnp.clip(
        ((jnp.arange(K) + 0.5) / K * n_valid).astype(jnp.int32), 0, x.shape[0] - 1
    )
    centers = srt[qidx]
    centers = jnp.where(jnp.isfinite(centers), centers, mean0)

    def lloyd(centers, _):
        d = (x[:, None] - centers[None, :]) ** 2
        assign = jnp.argmin(d, axis=1)
        onehot = (assign[:, None] == jnp.arange(K)[None, :]) * mask[:, None]
        cnt = onehot.sum(0)
        new = (onehot * x[:, None]).sum(0) / jnp.maximum(cnt, 1e-12)
        return jnp.where(cnt > 0, new, centers), None

    centers, _ = lax.scan(lloyd, centers, None, length=N_KMEANS_ITERS)

    d = (x[:, None] - centers[None, :]) ** 2
    resp = (jnp.argmin(d, axis=1)[:, None] == jnp.arange(K)[None, :]).astype(
        x.dtype
    ) * mask[:, None]

    # ---- variational sweeps (sklearn's update equations, 1-D case)
    mpp = 1.0  # mean_precision_prior
    dof0 = 1.0  # degrees_of_freedom_prior (= n_features)
    tiny = 10.0 * jnp.finfo(x.dtype).eps

    def m_step(resp):
        nk = resp.sum(0) + tiny
        xk = (resp * x[:, None]).sum(0) / nk
        sk = (resp * (x[:, None] - xk[None, :]) ** 2).sum(0) / nk + reg_covar
        # stick-breaking Beta posteriors (dirichlet_process)
        a = 1.0 + nk
        rev = jnp.cumsum(nk[::-1])[::-1]  # rev[k] = sum_{j>=k} nj
        b = wc_prior + jnp.concatenate([rev[1:], jnp.zeros((1,), x.dtype)])
        mean_prec = mpp + nk
        means = (mpp * mean0 + nk * xk) / mean_prec
        dof = dof0 + nk
        cov = (
            var0 + nk * sk + (nk * mpp / mean_prec) * (xk - mean0) ** 2
        ) / dof
        return nk, a, b, mean_prec, means, dof, cov

    def e_step(a, b, mean_prec, means, dof, cov):
        prec = 1.0 / cov
        log_gauss = -0.5 * (
            jnp.log(2.0 * jnp.pi) - jnp.log(prec)[None, :]
            + (x[:, None] - means[None, :]) ** 2 * prec[None, :]
        ) - 0.5 * jnp.log(dof)[None, :]
        log_lambda = jnp.log(2.0) + digamma(0.5 * dof)
        log_prob = log_gauss + 0.5 * (log_lambda - 1.0 / mean_prec)[None, :]
        dsum = digamma(a + b)
        log_w = digamma(a) - dsum + jnp.concatenate(
            [jnp.zeros((1,), x.dtype), jnp.cumsum(digamma(b) - dsum)[:-1]]
        )
        wlp = log_prob + log_w[None, :]
        return jnp.exp(wlp - logsumexp(wlp, axis=1, keepdims=True)) * mask[:, None]

    def sweep(resp, _):
        _, a, b, mean_prec, means, dof, cov = m_step(resp)
        return e_step(a, b, mean_prec, means, dof, cov), None

    resp, _ = lax.scan(sweep, resp, None, length=max_iter)
    _, a, b, mean_prec, means, dof, cov = m_step(resp)

    # sklearn's expected mixture weights under the stick-breaking posterior
    frac = a / (a + b)
    sticks = jnp.concatenate(
        [jnp.ones((1,), x.dtype), jnp.cumprod(b / (a + b))[:-1]]
    )
    weights = frac * sticks
    weights = weights / weights.sum()
    return means, jnp.sqrt(cov), weights, mean_prec, dof, a, b


@functools.lru_cache(maxsize=None)
def _jitted_fit(n_components, max_iter, reg_covar, wc_prior):
    """Process-wide jitted fit, one per hyperparameter tuple.

    Building ``jax.jit(...)`` inside every call hands jax a fresh callable
    each time, so nothing ever hits the C++ program cache — every client's
    fit retraced AND recompiled (~1 s/client, the superlinear init wall).
    Cached here, jax keys compiled programs on input *shape*, and the pow2
    bucketing below keeps distinct shapes to a handful per run.
    """
    import jax

    return jax.jit(
        jax.vmap(
            partial(
                _fit_batch,
                n_components=n_components,
                max_iter=max_iter,
                reg_covar=reg_covar,
                wc_prior=wc_prior,
            )
        )
    )


def _fit_flat(cols, n_components, eps, max_iter, reg_covar, wc_prior):
    """Fit a flat list of f32 columns with shape-bucketed batched dispatches.

    Rows pad to the next power of two (masking makes results independent of
    padding), the batch axis pads to a power of two with fully-masked dummy
    columns (``_fit_batch`` clamps ``n_valid`` to 1, so they are numerically
    inert and simply dropped), and oversized buckets split into chunks so a
    million-column flat batch still fits device memory.
    """
    import jax
    import jax.numpy as jnp

    from fed_tgan_tpu.features.bgm import ColumnGMM

    out = [None] * len(cols)
    # degenerate shards (< n_components samples) need the component clamp;
    # route those through the host fitter rather than slicing a K=10 fit
    small = [i for i, c in enumerate(cols) if len(c) < n_components]
    if small:
        from fed_tgan_tpu.features.bgm import fit_column_gmm

        for i in small:
            out[i] = fit_column_gmm(cols[i], n_components, eps)

    buckets: dict[int, list[int]] = {}
    for i, c in enumerate(cols):
        if out[i] is None:
            buckets.setdefault(_pow2_at_least(len(c), _ROWS_FLOOR), []).append(i)

    fit = _jitted_fit(n_components, max_iter, reg_covar, wc_prior)
    for rows, idxs in sorted(buckets.items()):
        max_chunk = max(_BATCH_FLOOR, _MAX_BATCH_ELEMENTS // rows)
        for lo in range(0, len(idxs), max_chunk):
            chunk = idxs[lo : lo + max_chunk]
            padded_b = min(_pow2_at_least(len(chunk), _BATCH_FLOOR), max_chunk)
            xs = np.zeros((padded_b, rows), dtype=np.float32)
            masks = np.zeros((padded_b, rows), dtype=np.float32)
            for row, i in enumerate(chunk):
                c = cols[i]
                xs[row, : len(c)] = c
                masks[row, : len(c)] = 1.0
            # one batched transfer for all seven result arrays (jaxlint
            # J01), then the float64 view is a host-side dtype conversion
            means, stds, weights, mean_prec, dof, stick_a, stick_b = (
                np.asarray(r, dtype=np.float64)
                for r in jax.device_get(fit(jnp.asarray(xs), jnp.asarray(masks)))
            )
            for row, i in enumerate(chunk):
                w = weights[row]
                out[i] = ColumnGMM(
                    means=means[row],
                    stds=np.maximum(stds[row], 1e-9),
                    weights=w,
                    active=w > eps,
                    # posterior extras: predict_proba then evaluates the
                    # exact variational E-step instead of the Gaussian
                    # approximation
                    mean_precision=mean_prec[row],
                    dof=dof[row],
                    stick_a=stick_a[row],
                    stick_b=stick_b[row],
                )
    return out


def fit_columns_jax(
    columns: "list[np.ndarray]",
    n_components: int = 10,
    eps: float = 0.005,
    max_iter: int = 100,
    reg_covar: float = 1e-6,
    wc_prior: float = 0.001,
):
    """Fit every column in one jitted, vmapped program; returns ColumnGMMs."""
    cols = [np.asarray(c, dtype=np.float32).reshape(-1) for c in columns]
    if not cols:
        return []
    with _span(
        "init.bgm_fit_jax", columns=len(cols), n_max=max(len(c) for c in cols)
    ):
        return _fit_flat(cols, n_components, eps, max_iter, reg_covar, wc_prior)


def fit_shards_jax(
    shard_columns: "list[list[np.ndarray]]",
    n_components: int = 10,
    eps: float = 0.005,
    max_iter: int = 100,
    reg_covar: float = 1e-6,
    wc_prior: float = 0.001,
):
    """Fit every continuous column of every client shard in a handful of
    batched device dispatches.

    ``shard_columns[i]`` is client i's list of 1-D columns; the ragged
    client x column structure flattens into one shape-bucketed batch (the
    leading axis of ``_fit_batch``'s vmap is *clients x columns*, not just
    columns), so a whole cohort onboards per dispatch instead of one jit
    round-trip per client.  Returns the same ragged structure of ColumnGMMs.
    """
    flat: list[np.ndarray] = []
    offsets = [0]
    for shard in shard_columns:
        flat.extend(np.asarray(c, dtype=np.float32).reshape(-1) for c in shard)
        offsets.append(len(flat))
    if not flat:
        return [[] for _ in shard_columns]
    with _span(
        "init.bgm_fit_shards",
        clients=len(shard_columns),
        columns=len(flat),
        n_max=max(len(c) for c in flat),
    ):
        fitted = _fit_flat(flat, n_components, eps, max_iter, reg_covar, wc_prior)
    return [fitted[offsets[i] : offsets[i + 1]] for i in range(len(shard_columns))]
