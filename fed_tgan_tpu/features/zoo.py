"""Alternative table encoders (the reference's transformer zoo).

The reference ships five encoder variants besides the federated
``BGM_CTGAN_Transformer`` (our ``features.transformer.ModeNormalizer``):
``DiscretizeTransformer`` (reference Server/dtds/features/transformers.py:82),
``GeneralTransformer`` (:136), ``GMMTransformer`` (:218), ``BGMTransformer``
(:467, used by the standalone ``CTGANSynthesizer.fit``, ctgan.py:337) and
``TableganTransformer`` (:589).  Here they are rebuilt as vectorized numpy
encoders sharing one metadata scheme — no per-row Python in ``transform`` /
``inverse_transform``, since their outputs feed device arrays.

All encoders expose ``fit(data) -> None``, ``transform(data) -> np.ndarray``,
``inverse_transform(encoded) -> np.ndarray`` and, where a GAN consumes the
encoding, ``output_info`` compatible with ``ops.segments.SegmentSpec``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from fed_tgan_tpu.data.constants import CATEGORICAL, CONTINUOUS, ORDINAL
from fed_tgan_tpu.features.bgm import ColumnGMM, fit_column_gmm


@dataclass
class ZooColumnMeta:
    """Per-column metadata (reference transformers.py:14-40 semantics):
    categorical/ordinal map values to ``i2s`` ordered by descending
    frequency; continuous record min/max."""

    name: object
    kind: str
    i2s: list = field(default_factory=list)
    min: float = 0.0
    max: float = 0.0

    @property
    def size(self) -> int:
        return len(self.i2s)


def infer_zoo_meta(
    data: np.ndarray,
    categorical_columns: Sequence[int] = (),
    ordinal_columns: Sequence[int] = (),
) -> list[ZooColumnMeta]:
    """Column metadata from a raw 2-D array; columns are identified by index."""
    import pandas as pd

    meta = []
    df = pd.DataFrame(np.asarray(data))
    for index in df:
        column = df[index]
        if index in categorical_columns or index in ordinal_columns:
            kind = CATEGORICAL if index in categorical_columns else ORDINAL
            i2s = column.value_counts().index.tolist()
            meta.append(ZooColumnMeta(name=index, kind=kind, i2s=i2s))
        else:
            meta.append(
                ZooColumnMeta(
                    name=index, kind=CONTINUOUS,
                    min=float(column.min()), max=float(column.max()),
                )
            )
    return meta


def _codes(col: np.ndarray, i2s: list) -> np.ndarray:
    """Vectorized value -> i2s index (replaces the reference's
    ``list(map(info['i2s'].index, col))`` per-row loop)."""
    lut = {v: i for i, v in enumerate(i2s)}
    return np.fromiter((lut[v] for v in col), dtype=np.int64, count=len(col))


def _onehot(codes: np.ndarray, size: int) -> np.ndarray:
    out = np.zeros((len(codes), size), dtype=np.float32)
    out[np.arange(len(codes)), codes] = 1.0
    return out


def _decode(onehot: np.ndarray, i2s: list) -> np.ndarray:
    return np.asarray(i2s, dtype=object)[np.argmax(onehot, axis=1)]


class BinningTransformer:
    """Uniform-width binning of continuous columns to integer codes
    (reference ``DiscretizeTransformer``, transformers.py:82-132 — there via
    sklearn ``KBinsDiscretizer(strategy='uniform')``; uniform edges are
    closed-form, so no sklearn here).  Inverse maps codes to bin centers."""

    def __init__(self, n_bins: int):
        self.n_bins = n_bins
        self.meta: Optional[list[ZooColumnMeta]] = None

    def fit(self, data, categorical_columns=(), ordinal_columns=()):
        self.meta = infer_zoo_meta(data, categorical_columns, ordinal_columns)
        self.continuous_idx = [i for i, m in enumerate(self.meta) if m.kind == CONTINUOUS]
        self.edges = {
            i: (self.meta[i].min, max(self.meta[i].max - self.meta[i].min, 1e-12))
            for i in self.continuous_idx
        }

    def transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        out = np.empty(data.shape, dtype=np.int64)
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                lo, span = self.edges[i]
                codes = np.floor((data[:, i].astype(np.float64) - lo) / span * self.n_bins)
                out[:, i] = np.clip(codes, 0, self.n_bins - 1)
            else:
                out[:, i] = _codes(data[:, i], m.i2s)
        return out

    def inverse_transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        out = np.empty((len(data), len(self.meta)), dtype=object)
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                lo, span = self.edges[i]
                codes = data[:, i].astype(np.float64)
                out[:, i] = lo + (codes + 0.5) / self.n_bins * span
            else:
                idx = data[:, i].astype(np.int64).clip(0, m.size - 1)
                out[:, i] = np.asarray(m.i2s, dtype=object)[idx]
        return out


class MinMaxTransformer:
    """Continuous/ordinal columns scaled to [0,1] (sigmoid) or [-1,1] (tanh);
    categorical columns one-hot (reference ``GeneralTransformer``,
    transformers.py:136-215)."""

    def __init__(self, act: str = "sigmoid"):
        assert act in ("sigmoid", "tanh")
        self.act = act
        self.meta: Optional[list[ZooColumnMeta]] = None

    def fit(self, data, categorical_columns=(), ordinal_columns=()):
        self.meta = infer_zoo_meta(data, categorical_columns, ordinal_columns)
        self.output_info = [
            (1, self.act) if m.kind in (CONTINUOUS, ORDINAL) else (m.size, "softmax")
            for m in self.meta
        ]
        self.output_dim = sum(s for s, _ in self.output_info)

    def transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        parts = []
        for i, m in enumerate(self.meta):
            col = data[:, i]
            if m.kind == CONTINUOUS:
                x = (col.astype(np.float64) - m.min) / max(m.max - m.min, 1e-12)
            elif m.kind == ORDINAL:
                x = _codes(col, m.i2s).astype(np.float64) / m.size
            else:
                parts.append(_onehot(_codes(col, m.i2s), m.size))
                continue
            if self.act == "tanh":
                x = x * 2.0 - 1.0
            parts.append(x.reshape(-1, 1).astype(np.float32))
        return np.concatenate(parts, axis=1)

    def inverse_transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        out = np.empty((len(data), len(self.meta)), dtype=object)
        st = 0
        for i, m in enumerate(self.meta):
            if m.kind in (CONTINUOUS, ORDINAL):
                x = data[:, st].astype(np.float64)
                st += 1
                if self.act == "tanh":
                    x = (x + 1.0) / 2.0
                x = np.clip(x, 0.0, 1.0)
                if m.kind == CONTINUOUS:
                    out[:, i] = x * (m.max - m.min) + m.min
                else:
                    idx = np.round(x * m.size).clip(0, m.size - 1).astype(np.int64)
                    out[:, i] = np.asarray(m.i2s, dtype=object)[idx]
            else:
                out[:, i] = _decode(data[:, st : st + m.size], m.i2s)
                st += m.size
        return out


class GMMTransformer:
    """Continuous columns modeled by a plain EM Gaussian mixture: scalar
    ``(x - mu_k)/(2 sigma_k)`` at the argmax-posterior mode plus the full
    posterior vector (reference ``GMMTransformer``, transformers.py:218-305).
    Categorical/ordinal columns one-hot."""

    def __init__(self, n_clusters: int = 5):
        self.n_clusters = n_clusters
        self.meta: Optional[list[ZooColumnMeta]] = None

    def fit(self, data, categorical_columns=(), ordinal_columns=(), seed: int = 0):
        from sklearn.mixture import GaussianMixture

        data = np.asarray(data)
        self.meta = infer_zoo_meta(data, categorical_columns, ordinal_columns)
        self.models: list[Optional[ColumnGMM]] = []
        self.output_info = []
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                gm = GaussianMixture(self.n_clusters, random_state=seed)
                gm.fit(data[:, i].astype(np.float64).reshape(-1, 1))
                self.models.append(ColumnGMM.from_sklearn(gm, eps=-1.0))  # all active
                self.output_info += [(1, "tanh"), (self.n_clusters, "softmax")]
            else:
                self.models.append(None)
                self.output_info += [(m.size, "softmax")]
        self.output_dim = sum(s for s, _ in self.output_info)

    def transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        parts = []
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                gm = self.models[i]
                x = data[:, i].astype(np.float64).reshape(-1, 1)
                feats = (x - gm.means[None, :]) / (2.0 * gm.stds[None, :])
                probs = gm.predict_proba(x.ravel())
                pick = np.argmax(probs, axis=1)
                scalar = feats[np.arange(len(x)), pick].clip(-0.99, 0.99)
                parts += [scalar.reshape(-1, 1).astype(np.float32), probs.astype(np.float32)]
            else:
                parts.append(_onehot(_codes(data[:, i], m.i2s), m.size))
        return np.concatenate(parts, axis=1)

    def inverse_transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        out = np.empty((len(data), len(self.meta)), dtype=object)
        st = 0
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                gm = self.models[i]
                u = np.clip(data[:, st], -1.0, 1.0)
                v = data[:, st + 1 : st + 1 + self.n_clusters]
                st += 1 + self.n_clusters
                pick = np.argmax(v, axis=1)
                out[:, i] = u * 2.0 * gm.stds[pick] + gm.means[pick]
            else:
                out[:, i] = _decode(data[:, st : st + m.size], m.i2s)
                st += m.size
        return out


class BGMTransformer:
    """Mode-specific normalization with a Bayesian GMM per continuous column
    and PROBABILITY-SAMPLED mode assignment (reference ``BGMTransformer``,
    transformers.py:467-588 — the encoder behind the standalone
    ``CTGANSynthesizer.fit``).  Differs from the federated ``ModeNormalizer``
    in keeping each column's LOCAL mixture (no global refit protocol).

    Mode sampling is vectorized: one uniform draw per row against the
    cumulative posterior, replacing the reference's per-row
    ``np.random.choice`` loop (transformers.py:530-534)."""

    def __init__(self, n_clusters: int = 10, eps: float = 0.005):
        self.n_clusters = n_clusters
        self.eps = eps
        self.meta: Optional[list[ZooColumnMeta]] = None

    def fit(self, data, categorical_columns=(), ordinal_columns=(), seed: int = 0):
        data = np.asarray(data)
        self.meta = infer_zoo_meta(data, categorical_columns, ordinal_columns)
        self.models: list[Optional[ColumnGMM]] = []
        self.output_info = []
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                gm = fit_column_gmm(
                    data[:, i].astype(np.float64),
                    n_components=self.n_clusters,
                    eps=self.eps,
                    seed=seed,
                )
                self.models.append(gm)
                self.output_info += [(1, "tanh"), (gm.n_active, "softmax")]
            else:
                self.models.append(None)
                self.output_info += [(m.size, "softmax")]
        self.output_dim = sum(s for s, _ in self.output_info)

    def transform(self, data: np.ndarray, seed: int = 0) -> np.ndarray:
        data = np.asarray(data)
        rng = np.random.default_rng(seed)
        parts = []
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                gm = self.models[i]
                active = gm.active
                x = data[:, i].astype(np.float64)
                feats = (x[:, None] - gm.means[None, active]) / (4.0 * gm.stds[None, active])
                probs = gm.predict_proba(x)[:, active]
                probs = probs + 1e-6
                probs /= probs.sum(axis=1, keepdims=True)
                cum = np.cumsum(probs, axis=1)
                # clip guards the 1-ulp case where cum[-1] < 1 and the draw
                # lands beyond it, which would index one past the last mode
                pick = (rng.random((len(x), 1)) > cum).sum(axis=1)
                pick = pick.clip(0, int(active.sum()) - 1)
                scalar = feats[np.arange(len(x)), pick].clip(-0.99, 0.99)
                parts += [
                    scalar.reshape(-1, 1).astype(np.float32),
                    _onehot(pick, int(active.sum())),
                ]
            else:
                parts.append(_onehot(_codes(data[:, i], m.i2s), m.size))
        return np.concatenate(parts, axis=1)

    def inverse_transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data)
        out = np.empty((len(data), len(self.meta)), dtype=object)
        st = 0
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                gm = self.models[i]
                active_idx = np.nonzero(gm.active)[0]
                n_active = len(active_idx)
                u = np.clip(data[:, st], -1.0, 1.0)
                v = data[:, st + 1 : st + 1 + n_active]
                st += 1 + n_active
                pick = active_idx[np.argmax(v, axis=1)]
                out[:, i] = u * 4.0 * gm.stds[pick] + gm.means[pick]
            else:
                out[:, i] = _decode(data[:, st : st + m.size], m.i2s)
                st += m.size
        return out


class GridTransformer:
    """Min-max scale every column to [-1,1] and pad/reshape rows into a
    (1, side, side) square image for conv models (reference
    ``TableganTransformer``, transformers.py:589-625).  Categorical columns
    are encoded as their integer code and rounded on inverse."""

    def __init__(self, side: int):
        self.side = side
        self.meta: Optional[list[ZooColumnMeta]] = None

    def fit(self, data, categorical_columns=(), ordinal_columns=()):
        self.meta = infer_zoo_meta(data, categorical_columns, ordinal_columns)
        lo, hi = [], []
        for m in self.meta:
            if m.kind == CONTINUOUS:
                lo.append(m.min - 1e-3)
                hi.append(m.max + 1e-3)
            else:
                lo.append(-1e-3)
                hi.append(m.size - 1 + 1e-3)
        self.lo = np.asarray(lo)
        self.hi = np.asarray(hi)

    def transform(self, data: np.ndarray) -> np.ndarray:
        cols = []
        data = np.asarray(data)
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                cols.append(data[:, i].astype(np.float64))
            else:
                cols.append(_codes(data[:, i], m.i2s).astype(np.float64))
        x = np.stack(cols, axis=1)
        x = (x - self.lo) / (self.hi - self.lo) * 2.0 - 1.0
        pad = self.side * self.side - x.shape[1]
        if pad > 0:
            x = np.concatenate([x, np.zeros((len(x), pad))], axis=1)
        return x.reshape(-1, 1, self.side, self.side).astype(np.float32)

    def inverse_transform(self, data: np.ndarray) -> np.ndarray:
        flat = np.asarray(data).reshape(len(data), -1)[:, : len(self.meta)]
        x = (flat.astype(np.float64) + 1.0) / 2.0 * (self.hi - self.lo) + self.lo
        out = np.empty((len(flat), len(self.meta)), dtype=object)
        for i, m in enumerate(self.meta):
            if m.kind == CONTINUOUS:
                out[:, i] = x[:, i]
            else:
                idx = np.round(x[:, i]).clip(0, m.size - 1).astype(np.int64)
                out[:, i] = np.asarray(m.i2s, dtype=object)[idx]
        return out
