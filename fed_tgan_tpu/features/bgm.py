"""Per-column Bayesian Gaussian mixtures for mode-specific normalization.

The reference fits one sklearn ``BayesianGaussianMixture(n_components=10,
weight_concentration_prior_type="dirichlet_process",
weight_concentration_prior=0.001)`` per continuous column (reference
Server/dtds/features/transformers.py:334-340) and ships the fitted sklearn
objects over RPC.  Here the mixture is a plain-array dataclass (cheap to
serialize, usable on device); fitting is sklearn-backed on host by default.

``ColumnGMM`` keeps the fitted sklearn estimator alive (when available) so
``predict_proba`` matches sklearn's variational posterior exactly during a
session; the array-only fallback uses standard Gaussian responsibilities.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

N_CLUSTERS = 10
WEIGHT_EPS = 0.005
WEIGHT_CONCENTRATION_PRIOR = 0.001


@dataclass
class ColumnGMM:
    """A 1-D Gaussian mixture as plain arrays.

    means/stds/weights have shape (n_components,); ``active`` is the boolean
    mask of components whose weight exceeds the activity threshold
    (reference transformers.py:342-347).
    """

    means: np.ndarray
    stds: np.ndarray
    weights: np.ndarray
    active: np.ndarray
    _sk: Optional[object] = field(default=None, repr=False, compare=False)
    # variational posterior extras (jax backend): with these present,
    # predict_proba evaluates the same expected-log-prob E-step sklearn uses
    # instead of the plain-Gaussian approximation
    mean_precision: Optional[np.ndarray] = None
    dof: Optional[np.ndarray] = None
    stick_a: Optional[np.ndarray] = None
    stick_b: Optional[np.ndarray] = None

    @property
    def n_components(self) -> int:
        return len(self.means)

    @property
    def n_active(self) -> int:
        return int(self.active.sum())

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        """Posterior responsibilities p(k | x); shape (len(x), n_components)."""
        x = np.asarray(x, dtype=np.float64).reshape(-1)
        if self._sk is not None:
            return self._sk.predict_proba(x.reshape(-1, 1))
        if self.mean_precision is not None:
            return self._variational_proba(x)
        log_w = np.log(np.maximum(self.weights, 1e-300))
        z = (x[:, None] - self.means[None, :]) / self.stds[None, :]
        log_p = log_w[None, :] - 0.5 * z**2 - np.log(self.stds)[None, :]
        log_p -= log_p.max(axis=1, keepdims=True)
        p = np.exp(log_p)
        return p / p.sum(axis=1, keepdims=True)

    def _variational_proba(self, x: np.ndarray) -> np.ndarray:
        """sklearn's BGM E-step (1-D) from the stored posterior parameters —
        the same formula bgm_jax's fit iterates, so jax-backend transforms
        assign modes exactly as the fit's final responsibilities would."""
        from scipy.special import digamma

        cov = self.stds**2
        prec = 1.0 / cov
        log_gauss = -0.5 * (
            np.log(2.0 * np.pi) - np.log(prec)[None, :]
            + (x[:, None] - self.means[None, :]) ** 2 * prec[None, :]
        ) - 0.5 * np.log(self.dof)[None, :]
        log_lambda = np.log(2.0) + digamma(0.5 * self.dof)
        log_prob = log_gauss + 0.5 * (log_lambda - 1.0 / self.mean_precision)[None, :]
        a, b = self.stick_a, self.stick_b
        dsum = digamma(a + b)
        log_w = digamma(a) - dsum + np.concatenate(
            [[0.0], np.cumsum(digamma(b) - dsum)[:-1]]
        )
        wlp = log_prob + log_w[None, :]
        wlp -= wlp.max(axis=1, keepdims=True)
        p = np.exp(wlp)
        return p / p.sum(axis=1, keepdims=True)

    def sample(self, n: int, rng: np.random.Generator | None = None) -> np.ndarray:
        """Draw n scalars from the mixture (used by the federated GMM refit,
        reference Server/dtds/distributed.py:731-735)."""
        rng = rng or np.random.default_rng()
        comp = rng.choice(self.n_components, size=n, p=self.weights / self.weights.sum())
        return rng.normal(self.means[comp], self.stds[comp])

    def to_dict(self) -> dict:
        d = {
            "means": self.means.tolist(),
            "stds": self.stds.tolist(),
            "weights": self.weights.tolist(),
            "active": self.active.tolist(),
        }
        for extra in ("mean_precision", "dof", "stick_a", "stick_b"):
            v = getattr(self, extra)
            if v is not None:
                d[extra] = np.asarray(v).tolist()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ColumnGMM":
        extras = {
            extra: np.asarray(d[extra], dtype=np.float64)
            for extra in ("mean_precision", "dof", "stick_a", "stick_b")
            if extra in d
        }
        return cls(
            means=np.asarray(d["means"], dtype=np.float64),
            stds=np.asarray(d["stds"], dtype=np.float64),
            weights=np.asarray(d["weights"], dtype=np.float64),
            active=np.asarray(d["active"], dtype=bool),
            **extras,
        )

    @classmethod
    def from_sklearn(cls, gm, eps: float = WEIGHT_EPS) -> "ColumnGMM":
        means = np.asarray(gm.means_).reshape(-1)
        stds = np.sqrt(np.asarray(gm.covariances_)).reshape(-1)
        weights = np.asarray(gm.weights_).reshape(-1)
        return cls(
            means=means,
            stds=stds,
            weights=weights,
            active=weights > eps,
            _sk=gm,
        )


def fit_column_gmm(
    x: np.ndarray,
    n_components: int = N_CLUSTERS,
    eps: float = WEIGHT_EPS,
    backend: str = "sklearn",
    seed: Optional[int] = None,
) -> ColumnGMM:
    """Fit a DP Bayesian GMM to one column (host-side, init-time only)."""
    x = np.asarray(x, dtype=np.float64).reshape(-1, 1)
    # degenerate shards: a mixture can't have more components than samples.
    # Only local (per-client) fits can be this small; the global refit pools
    # all clients, so output dims are unaffected.
    n_components = max(1, min(n_components, len(x)))
    if backend == "sklearn":
        import warnings

        from sklearn.exceptions import ConvergenceWarning
        from sklearn.mixture import BayesianGaussianMixture

        # experiment levers (PARITY.md 500-epoch sweep): the reference fits
        # at sklearn defaults (max_iter=100, tol=1e-3) where variational
        # inference routinely stops at max_iter — these env knobs test
        # whether better-converged mode structure moves delta-F1 on the
        # small surviving table; defaults reproduce the reference exactly
        try:
            max_iter = int(os.environ.get("FED_TGAN_TPU_BGM_MAX_ITER", 100))
            tol = float(os.environ.get("FED_TGAN_TPU_BGM_TOL", 1e-3))
        except ValueError:
            max_iter, tol = 100, 1e-3
        gm = BayesianGaussianMixture(
            n_components=n_components,
            weight_concentration_prior_type="dirichlet_process",
            weight_concentration_prior=WEIGHT_CONCENTRATION_PRIOR,
            n_init=1,
            max_iter=max_iter,
            tol=tol,
            random_state=seed,
        )
        with warnings.catch_warnings():
            # the reference fits at these exact settings, where variational
            # inference routinely hits max_iter on real columns; the partial
            # fit is the parity behavior, so the warning is expected noise
            warnings.simplefilter("ignore", ConvergenceWarning)
            gm.fit(x)
        return ColumnGMM.from_sklearn(gm, eps)
    if backend == "jax":
        from fed_tgan_tpu.features.bgm_jax import fit_columns_jax

        return fit_columns_jax([x.reshape(-1)], n_components, eps)[0]
    raise ValueError(f"unknown backend {backend!r}")


def resolved_init_workers() -> int:
    """Worker count for init-time GMM fitting (FED_TGAN_TPU_INIT_WORKERS;
    default 1 — see ``fit_column_gmms`` for why parallelism is opt-in)."""
    import os

    return int(os.environ.get("FED_TGAN_TPU_INIT_WORKERS") or 1)


def _fit_one(args):
    x, n_components, eps, backend, seed = args
    return fit_column_gmm(x, n_components, eps, backend, seed)


def fit_column_gmms(
    columns: "list[np.ndarray]",
    n_components: int = N_CLUSTERS,
    eps: float = WEIGHT_EPS,
    backend: str = "sklearn",
    seed: Optional[int] = None,
    max_workers: Optional[int] = None,
) -> "list[ColumnGMM]":
    """Fit one DP-BGM per column, in parallel across columns.

    The reference fits its 22 Intrusion columns serially
    (transformers.py:331-340); each fit here is identical (same estimator,
    same seed), so pooled results are bit-identical to the serial loop
    regardless of worker count.  Workers are OPT-IN via
    ``FED_TGAN_TPU_INIT_WORKERS=N``: single-process parallelism only pays on
    multi-core hosts, and environments whose site hooks eagerly initialize
    an accelerator runtime on interpreter start (one-chip tunnels) can't
    spawn compute workers safely.  In real federated deployments the
    per-client fits parallelize across hosts via the multihost init protocol
    (federation/distributed.py) instead.
    """
    if backend == "jax":
        # the whole batch is ONE vmapped device program — worker processes
        # would only add dispatch overhead
        from fed_tgan_tpu.features.bgm_jax import fit_columns_jax

        return fit_columns_jax(list(columns), n_components, eps)
    if max_workers is None:
        max_workers = resolved_init_workers()
    jobs = [(np.asarray(c, dtype=np.float64), n_components, eps, backend, seed)
            for c in columns]
    if max_workers <= 1 or len(jobs) <= 1:
        return [_fit_one(j) for j in jobs]

    import concurrent.futures as cf
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    with cf.ProcessPoolExecutor(
        max_workers=min(max_workers, len(jobs)), mp_context=ctx
    ) as pool:
        return list(pool.map(_fit_one, jobs))
