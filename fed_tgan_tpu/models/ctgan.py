"""CTGAN generator/discriminator as parameter pytrees.

Architectures match the reference (Server/dtds/synthesizers/ctgan.py:15-64):

- Generator: residual MLP — each block Linear(d->h) + BatchNorm + ReLU with
  the input concatenated back on (so widths grow), then Linear(d_total->D).
- Discriminator: "pac" trick (pac rows concatenated into one sample,
  reference pac=10) then [Linear + LeakyReLU(0.2) + Dropout(0.5)] blocks and
  a final Linear(->1).

Plain dict pytrees + pure apply functions (no flax): the federated weighted
average is then literally ``tree_map(psum(w * p))`` and parameter layouts are
transparent to shard or serialize.  Initialization follows torch's Linear
default (U(±1/sqrt(fan_in))) and BatchNorm1d defaults so training dynamics
match the reference closely.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

BN_EPS = 1e-5  # torch BatchNorm1d defaults
BN_MOMENTUM = 0.1
LEAKY_SLOPE = 0.2
DROPOUT_RATE = 0.5
PAC = 10

Params = Any  # pytree of jnp arrays
State = Any


def _linear_init(key: jax.Array, fan_in: int, fan_out: int) -> dict:
    bound = 1.0 / jnp.sqrt(fan_in)
    wk, bk = jax.random.split(key)
    return {
        "w": jax.random.uniform(wk, (fan_in, fan_out), minval=-bound, maxval=bound),
        "b": jax.random.uniform(bk, (fan_out,), minval=-bound, maxval=bound),
    }


def _linear(p: dict, x: jax.Array) -> jax.Array:
    return x @ p["w"] + p["b"]


# --------------------------------------------------------------- generator


def init_generator(
    key: jax.Array, input_dim: int, hidden: tuple[int, ...], data_dim: int
) -> tuple[Params, State]:
    """Residual-MLP generator parameters + batch-norm running state."""
    params: dict = {"blocks": [], "out": None}
    state: dict = {"blocks": []}
    dim = input_dim
    keys = jax.random.split(key, len(hidden) + 1)
    for k, h in zip(keys[:-1], hidden):
        params["blocks"].append(
            {
                "fc": _linear_init(k, dim, h),
                "bn_scale": jnp.ones((h,)),
                "bn_bias": jnp.zeros((h,)),
            }
        )
        state["blocks"].append(
            {"mean": jnp.zeros((h,)), "var": jnp.ones((h,))}
        )
        dim += h  # residual concat widens the stream
    params["out"] = _linear_init(keys[-1], dim, data_dim)
    return params, state


def generator_apply(
    params: Params, state: State, z: jax.Array, train: bool = True
) -> tuple[jax.Array, State]:
    """Forward pass; returns (raw output, updated BN state).

    train=True normalizes by batch statistics and advances the running
    averages (torch BatchNorm1d semantics, incl. unbiased variance in the
    running update); train=False uses the stored running statistics — the
    reference samples under ``generator.eval()``
    (Server/dtds/distributed.py:161)."""
    x = z
    new_blocks = []
    for block, bstate in zip(params["blocks"], state["blocks"]):
        h = _linear(block["fc"], x)
        # batch-norm statistics are an f32 island under bf16 compute: the
        # (h - mean) cancellation and the running-average update both die
        # in bf16's 8 mantissa bits.  The running state pytree is passed
        # in f32 (callers never cast it), so the aggregated BN state stays
        # a full-precision master copy; same-dtype casts are no-ops in
        # f32 mode, keeping that program byte-identical.
        h32 = h.astype(jnp.float32)
        if train:
            mean = h32.mean(axis=0)
            var = h32.var(axis=0)  # biased, used for normalization
            n = h.shape[0]
            unbiased = var * n / max(n - 1, 1)
            new_blocks.append(
                {
                    "mean": (1 - BN_MOMENTUM) * bstate["mean"] + BN_MOMENTUM * mean,
                    "var": (1 - BN_MOMENTUM) * bstate["var"] + BN_MOMENTUM * unbiased,
                }
            )
        else:
            mean, var = bstate["mean"], bstate["var"]
            new_blocks.append(bstate)
        h32 = (h32 - mean) / jnp.sqrt(var + BN_EPS)
        h32 = h32 * block["bn_scale"] + block["bn_bias"]
        h = jax.nn.relu(h32).astype(h.dtype)
        x = jnp.concatenate([h, x], axis=1)
    out = _linear(params["out"], x)
    return out, {"blocks": new_blocks}


# ----------------------------------------------------------- discriminator


def init_discriminator(
    key: jax.Array, input_dim: int, hidden: tuple[int, ...], pac: int = PAC
) -> Params:
    params: dict = {"layers": []}
    dim = input_dim * pac
    keys = jax.random.split(key, len(hidden) + 1)
    for k, h in zip(keys[:-1], hidden):
        params["layers"].append(_linear_init(k, dim, h))
        dim = h
    params["out"] = _linear_init(keys[-1], dim, 1)
    return params


def discriminator_apply(
    params: Params,
    x: jax.Array,
    key: jax.Array | None,
    pac: int = PAC,
    train: bool = True,
) -> jax.Array:
    """Forward pass; x is (batch, input_dim), batch divisible by pac.

    Dropout(0.5) after every hidden LeakyReLU when train=True; each call
    needs a fresh ``key`` (torch draws a new mask per forward)."""
    assert x.shape[0] % pac == 0, (x.shape, pac)
    h = x.reshape(x.shape[0] // pac, -1)
    for i, layer in enumerate(params["layers"]):
        h = jax.nn.leaky_relu(_linear(layer, h), LEAKY_SLOPE)
        if train:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - DROPOUT_RATE, h.shape)
            h = jnp.where(keep, h / (1.0 - DROPOUT_RATE), 0.0)
    return _linear(params["out"], h)
