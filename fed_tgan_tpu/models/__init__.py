from fed_tgan_tpu.models.ctgan import (
    discriminator_apply,
    generator_apply,
    init_discriminator,
    init_generator,
)
from fed_tgan_tpu.models.losses import gradient_penalty, slerp

__all__ = [
    "discriminator_apply",
    "generator_apply",
    "gradient_penalty",
    "init_discriminator",
    "init_generator",
    "slerp",
]
