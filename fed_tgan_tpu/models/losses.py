"""WGAN-GP losses with the reference's slerp interpolation quirk.

The reference interpolates real/fake pairs for the gradient penalty with
*spherical* interpolation rather than the usual linear mix
(reference Server/dtds/synthesizers/ctgan.py:231-258) — preserved here, it
changes where the Lipschitz constraint is enforced.  The second-order
gradient (grad of the penalty through grad-of-D) is plain ``jax.grad``
composition; XLA handles the double backward without torch's
create_graph/retain_graph choreography.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

GP_LAMBDA = 10.0


def slerp(val: jax.Array, low: jax.Array, high: jax.Array) -> jax.Array:
    """Spherical interpolation between rows of low and high; val is (batch, 1)."""
    low_norm = low / jnp.linalg.norm(low, axis=1, keepdims=True)
    high_norm = high / jnp.linalg.norm(high, axis=1, keepdims=True)
    cos = (low_norm * high_norm).sum(axis=1, keepdims=True)
    omega = jnp.arccos(jnp.clip(cos, -1.0, 1.0))
    so = jnp.sin(omega)
    # guard the parallel case (sin->0): fall back to linear interpolation
    safe_so = jnp.where(jnp.abs(so) < 1e-7, 1.0, so)
    sl = (jnp.sin((1.0 - val) * omega) / safe_so) * low + (
        jnp.sin(val * omega) / safe_so
    ) * high
    lin = (1.0 - val) * low + val * high
    return jnp.where(jnp.abs(so) < 1e-7, lin, sl)


def gradient_penalty(
    d_fn: Callable[[jax.Array], jax.Array],
    real: jax.Array,
    fake: jax.Array,
    key: jax.Array,
    pac: int = 10,
    lambda_: float = GP_LAMBDA,
) -> jax.Array:
    """((||dD/dx at slerp(real,fake)||_2 per pac-group - 1)^2).mean() * lambda.

    ``d_fn`` must already close over discriminator params and its dropout key
    (reference ctgan.py:240-258).  Differentiable w.r.t. whatever d_fn closes
    over — the double backward "gulf" the reference needs retain_graph for is
    just nested autodiff here.
    """
    alpha = jax.random.uniform(key, (real.shape[0], 1))
    # f32 islands under bf16 compute: slerp's arccos/sin chain and the
    # grad-norm reduction both lose the (norm - 1) signal entirely in
    # bf16's 8 mantissa bits, so they are pinned to f32; the D forward
    # itself runs at the inputs' compute dtype (interp is cast back).
    # Every cast is a same-dtype no-op in f32 mode.
    interp = slerp(
        alpha, real.astype(jnp.float32), fake.astype(jnp.float32)
    ).astype(real.dtype)
    grads = jax.grad(lambda x: d_fn(x).sum())(interp)
    norms = jnp.linalg.norm(
        grads.astype(jnp.float32).reshape(-1, pac * real.shape[1]), axis=1
    )
    return ((norms - 1.0) ** 2).mean() * lambda_
