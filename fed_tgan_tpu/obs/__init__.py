"""Unified telemetry: metrics registry, span tracing, run journal.

Three legs, one package, zero heavy imports:

- :mod:`.registry` -- a process-wide, thread-safe metrics registry
  (counters / gauges / histograms) with Prometheus text exposition.
  ``serve.metrics.ServiceMetrics`` is re-implemented on top of it; the
  trainer, transport, checkpointing, and watchdog publish into the
  process-wide default registry.
- :mod:`.trace` -- ``span(name, **attrs)`` host-side span tracing with a
  Chrome-trace / Perfetto JSON exporter, so host phases (ingest,
  local-steps, aggregate, snapshot, monitor, checkpoint) can be overlaid
  on the XLA device timeline from ``runtime/profiling.py``.
- :mod:`.journal` -- a durable per-run JSONL event stream (round
  summaries, per-client contributions, watchdog alarms and rollbacks,
  quarantine / eviction, transport reconnects and heartbeat lapses,
  compile events, backend probes, checkpoints) with a stable schema,
  summarized by ``python -m fed_tgan_tpu.obs report <journal>...``.
- :mod:`.exporter` -- the live plane: an opt-in in-trainer HTTP
  exporter (``--obs-port``) serving ``/metrics``, ``/healthz`` and the
  journal as tailable NDJSON, watched live by
  ``python -m fed_tgan_tpu.obs watch``.

Everything here is pure stdlib and MUST stay importable before
jax / numpy warm up -- ``doctor.py --check observability`` enforces it.
Instrumentation is free by construction: ``span`` and ``emit`` touch
only host clocks and Python objects (never device arrays), so hot
regions stay clean under ``jax.transfer_guard_device_to_host``.
"""

from __future__ import annotations

from fed_tgan_tpu.obs.exporter import (
    HealthState,
    TelemetryExporter,
    get_health,
)
from fed_tgan_tpu.obs.journal import (
    RunJournal,
    emit,
    get_journal,
    read_journal,
    set_journal,
)
from fed_tgan_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from fed_tgan_tpu.obs.trace import (
    Tracer,
    current_tracer,
    span,
    start_tracing,
    stop_tracing,
)

__all__ = [
    "Counter",
    "Gauge",
    "HealthState",
    "Histogram",
    "MetricsRegistry",
    "RunJournal",
    "TelemetryExporter",
    "Tracer",
    "current_tracer",
    "emit",
    "get_health",
    "get_journal",
    "get_registry",
    "read_journal",
    "set_journal",
    "span",
    "start_tracing",
    "stop_tracing",
]
