"""Live federation view: ``python -m fed_tgan_tpu.obs watch``.

Tails one or more run journals (files) or polls a training process's
telemetry exporter (``http://host:port``) and renders a rolling status
line -- rounds/s, losses, similarity, quarantine/rollback events -- plus
an in-run SLO alarm: every ``--slo-every`` newly observed rounds the
budget rules are re-evaluated over the events seen so far
(:func:`fed_tgan_tpu.obs.slo.check_figures`), and a regression both
prints an ALERT line and lands a ``slo_breach`` event in the journal,
turning the post-hoc gate into something that fires while the run can
still be stopped.

Multiple journals merge into one federation view keyed by round (the
per-rank streams of a multihost run); a URL source reads the exporter's
``/journal?offset=N`` incremental endpoint.  Pure stdlib -- never
imports jax.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request
from typing import List, Optional

from fed_tgan_tpu.obs.slo import (
    check_figures,
    default_budgets_path,
    journal_figures,
    load_budgets,
)

__all__ = ["watch_main"]

_NOTABLE = ("quarantine", "client_dropped", "watchdog_alarm",
            "watchdog_rollback", "slo_breach", "checkpoint_restore")


def _warn(msg: str) -> None:
    print(f"obs watch: warning: {msg}", file=sys.stderr)


class _FileSource:
    """Incremental reader over one journal file; crash-tolerant.

    Only complete (newline-terminated) lines are parsed; a torn tail is
    carried until the writer finishes it -- or warned about once the
    stream ends with it still torn.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._offset = 0
        self._buf = ""

    def poll(self) -> List[dict]:
        events: List[dict] = []
        try:
            with open(self.path, "r") as fh:
                fh.seek(self._offset)
                chunk = fh.read()
                self._offset = fh.tell()
        except OSError as exc:
            _warn(f"cannot read {self.path}: {exc}")
            return events
        self._buf += chunk
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                _warn(f"{self.path}: skipping truncated journal line "
                      f"({len(line)} bytes)")
                continue
            if isinstance(ev, dict):
                events.append(ev)
        return events

    def finish(self) -> None:
        """End of watching: a still-buffered torn tail gets its warning
        (a crashed writer never terminates the line; follow mode would
        otherwise swallow it silently)."""
        if self._buf.strip():
            _warn(f"{self.path}: skipping truncated journal line "
                  f"({len(self._buf.strip())} bytes)")
            self._buf = ""


class _UrlSource:
    """Incremental reader over an exporter's ``/journal?offset=N``."""

    def __init__(self, url: str, timeout: float = 5.0) -> None:
        self.url = url.rstrip("/")
        self.timeout = timeout
        self._offset = 0
        self._buf = ""

    def poll(self) -> List[dict]:
        events: List[dict] = []
        req = f"{self.url}/journal?offset={self._offset}"
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = resp.read().decode("utf-8", errors="replace")
                nxt = resp.headers.get("X-Journal-Offset")
                self._offset = (int(nxt) if nxt is not None
                                else self._offset + len(body))
        except (OSError, ValueError) as exc:
            _warn(f"cannot poll {req}: {exc}")
            return events
        self._buf += body
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                _warn(f"{req}: skipping truncated journal line")
                continue
            if isinstance(ev, dict):
                events.append(ev)
        return events

    def finish(self) -> None:
        if self._buf.strip():
            _warn(f"{self.url}/journal: skipping truncated journal line "
                  f"({len(self._buf.strip())} bytes)")
            self._buf = ""


class _WatchState:
    """Rolling fold of the merged event stream into one status line."""

    def __init__(self) -> None:
        self.events: List[dict] = []
        self.rounds_seen: set = set()
        self.last_round: Optional[int] = None
        self.per_round_s: Optional[float] = None
        self.loss_d: Optional[float] = None
        self.loss_g: Optional[float] = None
        self.avg_jsd: Optional[float] = None
        self.quarantines = 0
        self.drops = 0
        self.alarms = 0
        self.rollbacks = 0
        self.breaches = 0

    def fold(self, ev: dict) -> Optional[str]:
        """Update state; returns a printable line for notable events."""
        self.events.append(ev)
        kind = ev.get("type")
        if kind == "round":
            rnd = ev.get("round", ev.get("last", ev.get("first")))
            if isinstance(rnd, int):
                self.rounds_seen.add((ev.get("rank"), rnd))
                self.last_round = max(self.last_round or 0, rnd)
            if isinstance(ev.get("per_round_s"), (int, float)):
                self.per_round_s = float(ev["per_round_s"])
        elif kind == "client_contribution":
            for key, attr in (("loss_d", "loss_d"), ("loss_g", "loss_g")):
                vals = [v for v in (ev.get(key) or [])
                        if isinstance(v, (int, float))]
                if vals:
                    setattr(self, attr, sum(vals) / len(vals))
        elif kind == "similarity":
            if isinstance(ev.get("avg_jsd"), (int, float)):
                self.avg_jsd = float(ev["avg_jsd"])
        elif kind == "quarantine":
            self.quarantines += 1
        elif kind == "client_dropped":
            self.drops += 1
        elif kind == "watchdog_alarm":
            self.alarms += 1
        elif kind == "watchdog_rollback":
            self.rollbacks += 1
        elif kind == "slo_breach":
            self.breaches += 1
        if kind in _NOTABLE:
            detail = {k: v for k, v in ev.items()
                      if k not in ("ts", "type")}
            return f"[event] {kind} {json.dumps(detail, default=str)}"
        return None

    @property
    def n_rounds(self) -> int:
        return len({r for _, r in self.rounds_seen})

    def status(self) -> str:
        rps = (f"{1.0 / self.per_round_s:.2f} r/s"
               if self.per_round_s else "- r/s")

        def num(v, fmt="{:.4f}"):
            return fmt.format(v) if v is not None else "-"

        slo = "BREACH" if self.breaches else "ok"
        return (f"[watch] round {num(self.last_round, '{}')} "
                f"({self.n_rounds} seen) | {rps} | "
                f"loss_d {num(self.loss_d)} loss_g {num(self.loss_g)} | "
                f"jsd {num(self.avg_jsd)} | "
                f"quar {self.quarantines} drop {self.drops} "
                f"alarm {self.alarms} rollback {self.rollbacks} | "
                f"slo {slo}")


def _emit_breach(path: Optional[str], **fields) -> None:
    """Append a ``slo_breach`` event to the watched journal (file mode).

    Whole-line appends to the same JSONL the trainer writes; readers are
    torn-line tolerant, so a racing append can at worst cost one warning.
    """
    if path is None:
        return
    event = {"ts": round(time.time(), 6), "type": "slo_breach"}
    event.update(fields)
    try:
        with open(path, "a") as fh:
            fh.write(json.dumps(event, default=str) + "\n")
    except OSError as exc:
        _warn(f"cannot append slo_breach to {path}: {exc}")


def watch_main(args) -> int:
    """Entry for ``obs watch`` (argparse namespace: ``source`` list,
    ``follow``, ``interval``, ``slo_every``, ``budgets``,
    ``max_seconds``).  Exit 0 clean, 1 if any SLO breach was observed,
    2 on unusable budgets."""
    sources: List[object] = []
    breach_sink: Optional[str] = None
    for src in args.source:
        if src.startswith("http://") or src.startswith("https://"):
            sources.append(_UrlSource(src))
        else:
            sources.append(_FileSource(src))
            if breach_sink is None:
                breach_sink = src
    try:
        rules = load_budgets(args.budgets or default_budgets_path())
    except Exception as exc:  # noqa: BLE001 -- malformed budgets: exit 2
        print(f"obs watch: {exc}")
        return 2

    state = _WatchState()
    deadline = (time.time() + args.max_seconds
                if args.max_seconds else None)
    slo_every = max(1, int(args.slo_every))
    next_slo_at = slo_every
    last_status = ""
    while True:
        fresh: List[dict] = []
        for s in sources:
            fresh.extend(s.poll())
        for ev in fresh:
            line = state.fold(ev)
            if line:
                print(line)
        if state.n_rounds >= next_slo_at:
            next_slo_at = state.n_rounds + slo_every
            figures = journal_figures(state.events)
            regressions, _stale, matched, lines = check_figures(
                figures, rules, where=f"live@round{state.last_round}")
            if regressions:
                state.breaches += 1
                breaching = [ln for ln in lines
                             if ln.startswith("REGRESSION")]
                for ln in breaching:
                    print(f"ALERT {ln}")
                _emit_breach(breach_sink, round=state.last_round,
                             regressions=regressions, matched=matched,
                             rules=[ln.split()[1].rstrip(":")
                                    for ln in breaching])
        if fresh:
            status = state.status()
            if status != last_status:
                print(status)
                last_status = status
        if not args.follow:
            break
        if deadline is not None and time.time() >= deadline:
            break
        time.sleep(max(0.05, float(args.interval)))
    for s in sources:
        s.finish()
    if not last_status:
        print(state.status())
    return 1 if state.breaches else 0
