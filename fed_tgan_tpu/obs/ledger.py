"""Program cost ledger: per-compiled-program FLOP / memory accounting.

The obs layer so far (PR 6) measures *host* time -- spans, counters,
journal events.  This module adds the *device* side: every compiled
program gets a :class:`CostEntry` built from the XLA compiler's own
``cost_analysis()`` / ``memory_analysis()`` figures (flops, bytes
accessed, argument/output/temp bytes, generated-code size) so perf work
can compare programs against a recorded baseline instead of guessing.

Two feeds populate the process-wide ledger:

- **AOT**: :func:`contract_cost_ledger` lowers + compiles the same 37
  contracted entrypoints the hlolint harness fingerprints
  (``analysis/contracts/harness.ENTRYPOINT_FAMILIES``) and records one
  entry per program, emitting a ``program_cost`` journal event each.
- **live**: the sanitizers' ``CompileCounter`` calls
  :func:`note_compile` for every compile XLA logs, so programs that
  compile outside the contract set still show up (with count-only
  entries until someone records their analysis figures).

Import contract: this module is pure stdlib at import time -- jax is
imported *inside* the functions that need it.  That keeps
``fed_tgan_tpu.obs`` importable before jax (doctor enforces it) and
makes the ``sanitizers -> ledger`` import cycle-free.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from fed_tgan_tpu.obs.journal import emit as _emit_event

__all__ = [
    "CostEntry",
    "CostLedger",
    "contract_cost_ledger",
    "entry_from_lowered",
    "get_ledger",
    "note_compile",
]


@dataclass
class CostEntry:
    """Compiler-reported cost figures for one compiled program.

    ``flops`` / ``bytes_accessed`` / ``transcendentals`` come from
    ``cost_analysis()``; the byte-level fields from
    ``memory_analysis()``.  ``peak_bytes`` is the derived live-memory
    ceiling (arguments + outputs + temps + generated code -- XLA does
    not export a single peak-HBM figure through the AOT API, and on
    CPU ``generated_code`` may legitimately be 0).  ``donated_bytes``
    is the argument memory aliased into outputs (``alias_size``), i.e.
    what buffer donation saved.  ``compiles`` counts live compiles the
    sanitizers observed for this program name.
    """

    name: str
    family: str = ""
    flops: float = 0.0
    bytes_accessed: float = 0.0
    transcendentals: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    donated_bytes: int = 0
    peak_bytes: int = 0
    compiles: int = 0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "family": self.family,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "transcendentals": self.transcendentals,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "donated_bytes": self.donated_bytes,
            "peak_bytes": self.peak_bytes,
            "compiles": self.compiles,
        }


def _cost_dict(analysis) -> dict:
    """Normalize ``cost_analysis()`` output.

    jax's ``Lowered.cost_analysis()`` returns a plain dict;
    ``Compiled.cost_analysis()`` returns a *list* of per-device dicts
    on some jaxlib versions.  Accept both (and None on backends that
    don't implement it).
    """
    if analysis is None:
        return {}
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return dict(analysis)


def entry_from_lowered(name: str, lowered, family: str = "",
                       do_compile: bool = True) -> CostEntry:
    """Build a :class:`CostEntry` from a ``jax.stages.Lowered``.

    ``cost_analysis()`` works pre-compile; the memory figures need
    ``lowered.compile()``.  Both analyses are best-effort -- a backend
    that raises (or reports nothing) yields zeros for its fields rather
    than failing the whole ledger pass.
    """
    entry = CostEntry(name=name, family=family)
    try:
        cost = _cost_dict(lowered.cost_analysis())
    except Exception:
        cost = {}
    entry.flops = float(cost.get("flops", 0.0) or 0.0)
    entry.bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
    entry.transcendentals = float(cost.get("transcendentals", 0.0) or 0.0)
    if not do_compile:
        return entry
    try:
        compiled = lowered.compile()
    except Exception:
        return entry
    try:
        cost = _cost_dict(compiled.cost_analysis())
        # the compiled figures supersede the lowered estimate when the
        # backend reports them (post-fusion numbers are the real cost)
        if cost.get("flops"):
            entry.flops = float(cost["flops"])
        if cost.get("bytes accessed"):
            entry.bytes_accessed = float(cost["bytes accessed"])
        if cost.get("transcendentals"):
            entry.transcendentals = float(cost["transcendentals"])
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        entry.argument_bytes = int(
            getattr(mem, "argument_size_in_bytes", 0) or 0)
        entry.output_bytes = int(
            getattr(mem, "output_size_in_bytes", 0) or 0)
        entry.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
        entry.generated_code_bytes = int(
            getattr(mem, "generated_code_size_in_bytes", 0) or 0)
        entry.donated_bytes = int(
            getattr(mem, "alias_size_in_bytes", 0) or 0)
    # live-memory ceiling: everything resident while the program runs,
    # minus the donated argument bytes that alias into outputs
    entry.peak_bytes = max(0, entry.argument_bytes + entry.output_bytes
                           + entry.temp_bytes + entry.generated_code_bytes
                           - entry.donated_bytes)
    return entry


class CostLedger:
    """Thread-safe name -> :class:`CostEntry` map.

    ``record`` installs/merges analysis figures; ``note_compile`` (the
    sanitizers' hook) bumps the live-compile count, creating a bare
    entry for programs the AOT pass never saw.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, CostEntry] = {}

    def record(self, entry: CostEntry) -> CostEntry:
        with self._lock:
            prev = self._entries.get(entry.name)
            if prev is not None:
                entry.compiles = prev.compiles
            self._entries[entry.name] = entry
        return entry

    def note_compile(self, name: str) -> None:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                entry = CostEntry(name=name)
                self._entries[name] = entry
            entry.compiles += 1

    def entries(self) -> Dict[str, CostEntry]:
        with self._lock:
            return dict(self._entries)

    def snapshot(self) -> dict:
        """JSON-shaped dump: {name: entry dict}, stable key order."""
        entries = self.entries()
        return {name: entries[name].to_dict() for name in sorted(entries)}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_LEDGER = CostLedger()


def get_ledger() -> CostLedger:
    """The process-wide ledger (sanitizers and bench share it)."""
    return _LEDGER


def note_compile(name: str) -> None:
    """Module-level convenience for the sanitizers' CompileCounter."""
    _LEDGER.note_compile(name)


def contract_cost_ledger(
    families: Optional[Dict[str, Dict[str, Callable]]] = None,
    ledger: Optional[CostLedger] = None,
    journal: bool = True,
) -> Dict[str, CostEntry]:
    """Lower + compile every contracted entrypoint and ledger its cost.

    Reuses the hlolint harness registry (``ENTRYPOINT_FAMILIES``) so
    the ledger's program set is exactly the contracted one; requires
    the same 8-device mesh.  Each program emits a ``program_cost``
    journal event when a journal is installed.  Returns the recorded
    entries keyed by program name.
    """
    from fed_tgan_tpu.analysis.contracts.harness import (
        ENTRYPOINT_FAMILIES,
        require_mesh,
    )

    require_mesh()
    ledger = ledger if ledger is not None else get_ledger()
    out: Dict[str, CostEntry] = {}
    for family, programs in (families or ENTRYPOINT_FAMILIES).items():
        for name, build in programs.items():
            entry = entry_from_lowered(name, build(), family=family)
            ledger.record(entry)
            out[name] = entry
            if journal:
                _emit_event("program_cost", **entry.to_dict())
    return out


def ledger_main(argv=None) -> int:
    """``python -m fed_tgan_tpu.obs ledger [--json] [--family F ...]``

    Compiles the contracted programs (this imports jax and provisions
    the 8-device virtual CPU mesh when needed) and prints the ledger.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="fed_tgan_tpu.obs ledger",
        description="compile the contracted programs and print their "
                    "device cost ledger")
    parser.add_argument("--json", action="store_true",
                        help="emit the ledger as JSON")
    parser.add_argument("--family", action="append", default=None,
                        help="restrict to one entrypoint family "
                             "(repeatable)")
    args = parser.parse_args(argv)
    try:
        from fed_tgan_tpu.analysis.contracts.harness import (
            ENTRYPOINT_FAMILIES,
            HarnessError,
        )
    except Exception as exc:
        print(f"ledger: harness unavailable: {exc!r}")
        return 2
    families = None
    if args.family:
        unknown = [f for f in args.family if f not in ENTRYPOINT_FAMILIES]
        if unknown:
            print(f"ledger: unknown families {unknown}; "
                  f"known: {sorted(ENTRYPOINT_FAMILIES)}")
            return 2
        families = {f: ENTRYPOINT_FAMILIES[f] for f in args.family}
    try:
        entries = contract_cost_ledger(families=families, journal=False)
    except HarnessError as exc:
        print(f"ledger: {exc}")
        return 2
    if args.json:
        print(json.dumps({n: e.to_dict() for n, e in entries.items()},
                         indent=2, sort_keys=True))
        return 0
    print(f"{'program':<38} {'family':<16} {'Mflops':>10} "
          f"{'MB accessed':>12} {'peak MB':>9} {'donated MB':>11}")
    for name in sorted(entries):
        e = entries[name]
        print(f"{name:<38} {e.family:<16} {e.flops / 1e6:>10.2f} "
              f"{e.bytes_accessed / 1e6:>12.2f} "
              f"{e.peak_bytes / 1e6:>9.2f} "
              f"{e.donated_bytes / 1e6:>11.2f}")
    return 0
