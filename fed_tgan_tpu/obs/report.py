"""Run-journal summarizer behind ``python -m fed_tgan_tpu.obs report``.

Turns one JSONL journal into the questions an operator actually asks
after a run: how many rounds and how fast, did the watchdog fire, who
got quarantined or dropped, did transport flap, what compiled, where
are the checkpoints.  Text by default, ``--format json`` for tooling
(doctor round-trips a synthetic journal through the JSON path).
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Sequence, Union

from fed_tgan_tpu.obs.journal import read_journal

__all__ = ["summarize", "summarize_many", "render_text"]


def _clients_section(contribs: List[dict], quarantines: List[dict],
                     alarms: List[dict], rollbacks: List[dict],
                     drops: List[dict],
                     joins: Sequence[dict] = (),
                     lefts: Sequence[dict] = (),
                     drift_alarms: Sequence[dict] = ()) -> dict:
    """Fold ``client_contribution`` events into one per-round client table.

    Order-independent across merged rank journals: rows are keyed by
    (round, client) and folded in sorted order, so merging ``[a, b]``
    and ``[b, a]`` produces identical output.  Elastic-membership events
    (``client_joined`` / ``client_left`` / ``drift_alarm``) annotate the
    same per-client entries so one section narrates who joined, left,
    drifted, or got quarantined.
    """
    rows = []
    for ev in contribs:
        rnd = ev.get("round")
        ids = ev.get("clients") or []
        if not isinstance(rnd, int):
            continue

        def col(name, default=None):
            v = ev.get(name)
            return v if isinstance(v, list) and len(v) == len(ids) else None

        weights, ld, lg = col("weights"), col("loss_d"), col("loss_g")
        quar, strikes = col("quarantined"), col("strikes")
        for j, c in enumerate(ids):
            rows.append((
                int(rnd), int(c),
                weights[j] if weights else None,
                ld[j] if ld else None,
                lg[j] if lg else None,
                int(quar[j]) if quar else 0,
                int(strikes[j]) if strikes else 0,
            ))
    rows.sort(key=lambda r: (r[0], r[1], str(r[2:])))
    table: Dict[int, Dict[int, tuple]] = {}
    for r in rows:
        table.setdefault(r[0], {})[r[1]] = r[2:]

    per_client: Dict[str, dict] = {}
    track: Dict[int, dict] = {}
    for rnd in sorted(table):
        for c, (w, ld, lg, q, s) in sorted(table[rnd].items()):
            d = track.setdefault(c, {
                "rounds": 0, "first_round": rnd, "weight_first": w,
                "quarantined_rounds": 0, "strikes": 0,
            })
            d["rounds"] += 1
            d["last_round"] = rnd
            if d["weight_first"] is None:
                d["weight_first"] = w
            if w is not None:
                d["weight_last"] = w
            d["loss_d_last"], d["loss_g_last"] = ld, lg
            d["quarantined_rounds"] += q
            d["strikes"] = max(d["strikes"], s)
    dropped_by = {int(e["client"]): str(e.get("reason", "")) for e in drops
                  if e.get("client") is not None}
    joined_by = {int(e["client"]): e for e in joins
                 if e.get("client") is not None}
    left_by = {int(e["client"]): str(e.get("reason", "")) for e in lefts
               if e.get("client") is not None}
    drift_count: Dict[int, int] = {}
    for e in drift_alarms:
        if e.get("client") is not None:
            drift_count[int(e["client"])] = \
                drift_count.get(int(e["client"]), 0) + 1
    # membership events may name clients the contribution ledger never
    # saw (a newcomer that joined after the last ledger pull): give them
    # a row anyway so the narration is complete
    for c in set(joined_by) | set(left_by) | set(drift_count):
        track.setdefault(c, {
            "rounds": 0, "first_round": None, "weight_first": None,
            "quarantined_rounds": 0, "strikes": 0,
        })
    for c in sorted(track):
        d = track[c]
        wf, wl = d.get("weight_first"), d.get("weight_last")
        d["weight_delta"] = (round(wl - wf, 6)
                             if wf is not None and wl is not None else None)
        if c in dropped_by:
            d["dropped"] = dropped_by[c] or True
        if c in joined_by:
            je = joined_by[c]
            d["joined_round"] = je.get("round")
            if je.get("repacked"):
                d["join_repacked"] = True
        if c in left_by:
            d["left"] = left_by[c] or True
        if c in drift_count:
            d["drift_alarms"] = drift_count[c]
        per_client[str(c)] = d

    movers = sorted(
        ((c, d["weight_delta"]) for c, d in per_client.items()
         if d.get("weight_delta") is not None),
        key=lambda kv: (-abs(kv[1]), kv[0]))
    forensics = []
    wd_events = sorted(
        [("alarm", e) for e in alarms] + [("rollback", e) for e in rollbacks],
        key=lambda kv: kv[1].get("round", 0) if isinstance(
            kv[1].get("round"), int) else 0)
    for ev in quarantines:
        c = ev.get("client")
        if c is None:
            continue
        first = ev.get("first")
        entry = {
            "client": int(c),
            "first": first,
            "last": ev.get("last"),
            "rounds": ev.get("rounds"),
            "test": ev.get("test", "?"),
            "strikes": ev.get("strikes"),
        }
        # what the watchdog did next: the first alarm/rollback at or
        # after the quarantine window opened
        nxt = next((f"{kind}@{we.get('round')}"
                    + (f" ({we.get('reason')})" if we.get("reason") else "")
                    for kind, we in wd_events
                    if isinstance(we.get("round"), int)
                    and isinstance(first, int)
                    and we.get("round") >= first), None)
        if nxt:
            entry["watchdog"] = nxt
        if int(c) in dropped_by:
            entry["dropped"] = dropped_by[int(c)] or True
        forensics.append(entry)
    forensics.sort(key=lambda f: (f.get("first") or 0, f["client"]))

    out = {
        "tracked": len(per_client),
        "rounds": len(table),
        "per_client": per_client,
        "top_movers": movers[:5],
        "forensics": forensics,
    }
    if joins or lefts or drift_alarms:
        out["membership"] = {
            "joins": len(list(joins)),
            "leaves": len(list(lefts)),
            "drift_alarms": len(list(drift_alarms)),
            "join_repacks": sum(1 for e in joins if e.get("repacked")),
        }
    return out


def _similarity_section(sims: List[dict]) -> dict:
    """Drift as a first-class signal: the monitor probe's trajectory."""
    samples = [e for e in sims if isinstance(e.get("avg_jsd"), (int, float))]
    out: dict = {"samples": len(sims)}
    if samples:
        epochs = [e.get("epoch") for e in samples
                  if isinstance(e.get("epoch"), int)]
        out["first_epoch"] = min(epochs) if epochs else None
        out["last_epoch"] = max(epochs) if epochs else None
        last = samples[-1]
        out["avg_jsd_last"] = round(float(last["avg_jsd"]), 6)
        out["avg_jsd_best"] = round(
            min(float(e["avg_jsd"]) for e in samples), 6)
        if isinstance(last.get("avg_wd"), (int, float)):
            out["avg_wd_last"] = round(float(last["avg_wd"]), 6)
        per_col = last.get("per_column_jsd")
        if isinstance(per_col, dict) and per_col:
            worst = sorted(per_col.items(),
                           key=lambda kv: (-float(kv[1]), kv[0]))
            out["per_column_jsd_last"] = {
                k: round(float(v), 6) for k, v in sorted(per_col.items())}
            out["worst_columns"] = [
                [k, round(float(v), 6)] for k, v in worst[:3]]
    return out


def summarize(path: str, on_skip=None) -> dict:
    """Structured summary of one journal file."""
    return summarize_many([path], on_skip=on_skip)


def summarize_many(paths: Sequence[str], on_skip=None) -> dict:
    """One merged federation view over one or more journals.

    A multihost run writes one journal per rank; merging keys everything
    by round.  Per-rank duplicates of the round stream (every rank logs
    its own ``round`` events) are deduplicated deterministically: the
    server stream wins when present, else the lowest rank.  Per-client
    streams (``client_contribution``) union across ranks -- each rank
    contributes its own clients.  ``on_skip`` receives a warning line
    per torn/truncated journal line (crashed writer) instead of raising.
    """
    events: List[dict] = []
    for path in paths:
        events.extend(read_journal(path, on_skip=on_skip))
    # stable ts-sort: merged rank streams interleave in wall order, ties
    # keep per-journal append order (determinism for identical ts)
    events.sort(key=lambda ev: (ev.get("ts") if isinstance(
        ev.get("ts"), (int, float)) else 0.0))
    by_type: Dict[str, int] = {}
    for ev in events:
        t = str(ev.get("type", "?"))
        by_type[t] = by_type.get(t, 0) + 1

    out: dict = {
        "path": ",".join(str(p) for p in paths),
        "paths": [str(p) for p in paths],
        "events": len(events),
        "by_type": dict(sorted(by_type.items())),
        "schema": None,
        "run_id": None,
        "duration_s": None,
    }
    if events:
        first = next((e for e in events if e.get("type") == "run_start"),
                     None)
        if first is not None:
            out["schema"] = first.get("schema")
            out["run_id"] = first.get("run_id")
        ts = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
        if ts:
            out["duration_s"] = round(max(ts) - min(ts), 3)

    rounds = [e for e in events if e.get("type") == "round"]
    # multihost rank streams: every rank emits its own round events; a
    # merged view must count each round once.  The server's stream is
    # canonical when present, else the lowest-ranked client's.
    roles = {str(e.get("role")) for e in rounds if e.get("role")}
    if roles:
        if "server" in roles:
            rounds = [e for e in rounds if e.get("role") == "server"]
        else:
            ranks = sorted(int(e.get("rank", 0)) for e in rounds
                           if e.get("rank") is not None)
            if ranks:
                rounds = [e for e in rounds
                          if int(e.get("rank", 0)) == ranks[0]]
    if rounds:
        per = [e["per_round_s"] for e in rounds
               if isinstance(e.get("per_round_s"), (int, float))]
        # two event shapes coexist: legacy = one event per device program
        # (chunk) carrying `rounds`=size; current = one event per LOGICAL
        # round carrying `round` + `rounds_per_program`, where the chunk
        # head has round == first.  total_rounds and chunks therefore
        # come out invariant to --rounds-per-program for both shapes.
        heads = [e for e in rounds
                 if "round" not in e or e.get("round") == e.get("first")]
        out["rounds"] = {
            "chunks": len(heads),
            "total_rounds": sum(int(e.get("rounds", 1)) for e in rounds),
            "per_round_s_mean": round(sum(per) / len(per), 4) if per else None,
            "per_round_s_max": round(max(per), 4) if per else None,
        }
        rpp = [int(e["rounds_per_program"]) for e in rounds
               if isinstance(e.get("rounds_per_program"), int)]
        if rpp:
            out["rounds"]["rounds_per_program_max"] = max(rpp)

    cohorts = [e for e in events if e.get("type") == "cohort"]
    if cohorts:
        # one cohort event per LOGICAL round (chunk heads have
        # round == first), so every figure here is invariant to
        # --rounds-per-program, like the rounds section above
        pops = [int(e["population"]) for e in cohorts
                if isinstance(e.get("population"), int)]
        sizes = [int(e["cohort"]) for e in cohorts
                 if isinstance(e.get("cohort"), int)]
        sampled: set = set()
        for e in cohorts:
            sampled.update(int(c) for c in e.get("clients", []) or [])
        stale_hist: Dict[str, int] = {}
        for e in cohorts:
            for s_key, n in (e.get("staleness") or {}).items():
                stale_hist[str(s_key)] = max(stale_hist.get(str(s_key), 0),
                                             int(n))
        applied = [int(e["buffered_applied"]) for e in cohorts
                   if isinstance(e.get("buffered_applied"), int)]
        out["federation_scale"] = {
            "rounds": len(cohorts),
            "population": max(pops) if pops else None,
            "cohort_size": max(sizes) if sizes else None,
            "distinct_clients_sampled": len(sampled),
            "buffered_updates_applied": max(applied) if applied else 0,
            "staleness_histogram": dict(sorted(stale_hist.items())),
        }

    alarms = [e for e in events if e.get("type") == "watchdog_alarm"]
    rollbacks = [e for e in events if e.get("type") == "watchdog_rollback"]
    if alarms or rollbacks:
        out["watchdog"] = {
            "alarms": len(alarms),
            "rollbacks": len(rollbacks),
            "reasons": sorted({str(e.get("reason", "?")) for e in alarms}),
        }

    quarantines = [e for e in events if e.get("type") == "quarantine"]
    drops = [e for e in events if e.get("type") == "client_dropped"]
    if quarantines or drops:
        out["robustness"] = {
            "quarantine_events": len(quarantines),
            "clients_dropped": sorted({e.get("client") for e in drops
                                       if e.get("client") is not None}),
        }

    contribs = [e for e in events if e.get("type") == "client_contribution"]
    joins = [e for e in events if e.get("type") == "client_joined"]
    lefts = [e for e in events if e.get("type") == "client_left"]
    drift_als = [e for e in events if e.get("type") == "drift_alarm"]
    if contribs or joins or lefts or drift_als:
        out["clients"] = _clients_section(contribs, quarantines,
                                          alarms, rollbacks, drops,
                                          joins=joins, lefts=lefts,
                                          drift_alarms=drift_als)

    drift_ws = [e for e in events if e.get("type") == "drift_window"]
    if drift_ws:
        rises_j = [float(e["max_jsd_rise"]) for e in drift_ws
                   if isinstance(e.get("max_jsd_rise"), (int, float))]
        rises_w = [float(e["max_wd_rise"]) for e in drift_ws
                   if isinstance(e.get("max_wd_rise"), (int, float))]
        last = drift_ws[-1]
        out["drift"] = {
            "windows": len(drift_ws),
            "alarms_total": sum(int(e.get("alarms", 0) or 0)
                                for e in drift_ws),
            "evicted": sorted({int(c) for e in drift_ws
                               for c in (e.get("evicted") or [])}),
            "max_jsd_rise": round(max(rises_j), 6) if rises_j else None,
            "max_wd_rise": round(max(rises_w), 6) if rises_w else None,
            "final_live": last.get("live"),
            "final_population": last.get("population"),
        }

    sims = [e for e in events if e.get("type") == "similarity"]
    if sims:
        out["similarity"] = _similarity_section(sims)

    flaps = [e for e in events
             if e.get("type") in ("transport_reconnect", "transport_drop",
                                  "heartbeat_lapse")]
    if flaps:
        out["transport"] = {
            "reconnects": by_type.get("transport_reconnect", 0),
            "drops": by_type.get("transport_drop", 0),
            "heartbeat_lapses": by_type.get("heartbeat_lapse", 0),
        }

    compiles = [e for e in events if e.get("type") == "compile"]
    if compiles:
        per_prog: Dict[str, int] = {}
        for e in compiles:
            p = str(e.get("program", "?"))
            per_prog[p] = per_prog.get(p, 0) + 1
        out["compiles"] = dict(sorted(per_prog.items()))

    ckpts = [e for e in events if e.get("type") == "checkpoint"]
    if ckpts:
        out["checkpoints"] = {
            "saved": len(ckpts),
            "last_path": ckpts[-1].get("path"),
            "restores": by_type.get("checkpoint_restore", 0),
        }

    serve_evs = [e for e in events
                 if e.get("type") in ("serve_reload", "fleet_load",
                                      "fleet_evict", "tenant_shed")]
    if serve_evs:
        sheds = [e for e in serve_evs if e.get("type") == "tenant_shed"]
        shed_by_tenant: Dict[str, int] = {}
        for e in sheds:
            t = str(e.get("tenant", "?"))
            shed_by_tenant[t] = shed_by_tenant.get(t, 0) + int(
                e.get("count", 1))
        out["serving"] = {
            "reloads": by_type.get("serve_reload", 0),
            "fleet_loads": by_type.get("fleet_load", 0),
            "fleet_evicts": by_type.get("fleet_evict", 0),
            "tenants_loaded": sorted({str(e.get("tenant")) for e in serve_evs
                                      if e.get("type") == "fleet_load"
                                      and e.get("tenant") is not None}),
            "shed_by_tenant": dict(sorted(shed_by_tenant.items())),
        }

    promos = [e for e in events
              if e.get("type") in ("promotion_promoted",
                                   "promotion_rejected")]
    fails = by_type.get("serve_reload_failed", 0)
    if promos or fails:
        per_tenant: Dict[str, dict] = {}
        for e in promos:
            t = str(e.get("tenant", "?"))
            d = per_tenant.setdefault(t, {"promotions": 0, "rejections": 0})
            if e.get("type") == "promotion_promoted":
                d["promotions"] += 1
            else:
                d["rejections"] += 1
            if isinstance(e.get("avg_jsd"), (int, float)):
                d["avg_jsd_last"] = round(float(e["avg_jsd"]), 6)
            if isinstance(e.get("avg_wd"), (int, float)):
                d["avg_wd_last"] = round(float(e["avg_wd"]), 6)
        rejects = [e for e in promos
                   if e.get("type") == "promotion_rejected"]
        tripped = sorted({str(t) for e in rejects
                          for t in (e.get("tripped") or [])})
        out["quality"] = {
            "promotions": by_type.get("promotion_promoted", 0),
            "rejections": by_type.get("promotion_rejected", 0),
            "reload_failures": fails,
            "per_tenant": dict(sorted(per_tenant.items())),
            "tripped_budgets": tripped,
            "last_rejection": rejects[-1] if rejects else None,
        }

    costs = [e for e in events if e.get("type") == "program_cost"]
    traces = [e for e in events if e.get("type") == "device_trace"]
    if costs or traces:
        # last program_cost event per program wins (re-ledgering a
        # program supersedes the earlier figures)
        per_cost: Dict[str, dict] = {}
        for e in costs:
            name = str(e.get("name", "?"))
            per_cost[name] = {
                "family": e.get("family", ""),
                "flops": e.get("flops", 0),
                "bytes_accessed": e.get("bytes_accessed", 0),
                "peak_bytes": e.get("peak_bytes", 0),
                "donated_bytes": e.get("donated_bytes", 0),
                "compiles": e.get("compiles", 0),
            }
        out["programs"] = {
            "ledgered": len(per_cost),
            "total_flops": sum(float(c["flops"] or 0)
                               for c in per_cost.values()),
            "peak_bytes_max": max(
                (int(c["peak_bytes"] or 0) for c in per_cost.values()),
                default=0),
            "per_program": dict(sorted(per_cost.items())),
            # the profiling satellite: device_trace outcomes belong to
            # the program view -- the trace dir is where the per-program
            # device timelines actually live
            "device_traces": [
                {"dir": e.get("dir"), "ok": bool(e.get("ok", False)),
                 "error": e.get("error")}
                for e in traces
            ],
        }

    inits = [e for e in events if e.get("type") == "init_phase"]
    if inits:
        per_phase: Dict[str, dict] = {}
        for e in inits:
            phase = str(e.get("phase", "?"))
            d = per_phase.setdefault(phase, {"seconds": 0.0, "count": 0,
                                             "clients": 0, "rows": 0})
            d["seconds"] += float(e.get("seconds", 0) or 0)
            d["count"] += 1
            # onboarding throughput: events carry the client/row volume
            # that phase processed (max across events — repeated phases in
            # one journal re-onboard the same population)
            d["clients"] = max(d["clients"], int(e.get("clients", 0) or 0))
            d["rows"] = max(d["rows"], int(e.get("rows", 0) or 0))
        for d in per_phase.values():
            d["seconds"] = round(d["seconds"], 3)
            if d["seconds"] > 0:
                if d["clients"]:
                    d["clients_per_s"] = round(d["clients"] / d["seconds"], 1)
                if d["rows"]:
                    d["rows_per_s"] = round(d["rows"] / d["seconds"])
        out["init"] = {
            "total_seconds": round(sum(d["seconds"]
                                       for d in per_phase.values()), 3),
            "phases": dict(sorted(per_phase.items(),
                                  key=lambda kv: -kv[1]["seconds"])),
        }

    cache_evs = [e for e in events if e.get("type") == "init_cache"]
    if cache_evs:
        by_op: Dict[str, int] = {}
        for e in cache_evs:
            key = f"{e.get('op', '?')}_{e.get('scope', '?')}"
            by_op[key] = by_op.get(key, 0) + int(e.get("count", 1) or 1)
        hits = sum(n for k, n in by_op.items() if k.startswith("hit"))
        misses = sum(n for k, n in by_op.items() if k.startswith("miss"))
        out["init_cache"] = {
            "by_op": dict(sorted(by_op.items())),
            "hits": hits,
            "misses": misses,
            "corrupt": sum(n for k, n in by_op.items()
                           if k.startswith("corrupt")),
            "hit_rate": (round(hits / (hits + misses), 3)
                         if hits + misses else None),
            "roots": sorted({str(e.get("root")) for e in cache_evs
                             if e.get("root")}),
        }

    stage_evs = [e for e in events if e.get("type") == "serve_stages"]
    if stage_evs:
        per_stage: Dict[str, dict] = {}
        for e in stage_evs:
            for stage, st in (e.get("stages") or {}).items():
                if not isinstance(st, dict):
                    continue
                d = per_stage.setdefault(
                    str(stage), {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0})
                d["count"] += int(st.get("count", 0) or 0)
                # worst window observed -- the operator wants the spikes
                d["p50_ms"] = max(d["p50_ms"], float(st.get("p50_ms", 0) or 0))
                d["p99_ms"] = max(d["p99_ms"], float(st.get("p99_ms", 0) or 0))
        out["serve_stages"] = dict(sorted(per_stage.items()))

    probes = [e for e in events if e.get("type") == "backend_probe"]
    if probes:
        out["backend_probes"] = {
            "total": len(probes),
            "failed": sum(1 for e in probes if not e.get("ok", False)),
        }
    return out


def render_text(summary: dict) -> str:
    lines: List[str] = [
        f"journal: {summary['path']}",
        f"  run_id={summary.get('run_id')} schema={summary.get('schema')} "
        f"events={summary['events']} duration_s={summary.get('duration_s')}",
        "  events by type:",
    ]
    for t, n in summary.get("by_type", {}).items():
        lines.append(f"    {n:6d}  {t}")
    r = summary.get("rounds")
    if r:
        rpp = r.get("rounds_per_program_max")
        lines.append(f"  rounds: {r['total_rounds']} in {r['chunks']} "
                     f"chunk(s), per-round mean {r['per_round_s_mean']}s "
                     f"max {r['per_round_s_max']}s"
                     + (f", up to {rpp} round(s)/program" if rpp else ""))
    fs = summary.get("federation_scale")
    if fs:
        lines.append(f"  federation scale: population {fs['population']}, "
                     f"cohort {fs['cohort_size']}/round over {fs['rounds']} "
                     f"round(s), {fs['distinct_clients_sampled']} distinct "
                     f"client(s) sampled, "
                     f"{fs['buffered_updates_applied']} buffered update(s) "
                     f"applied"
                     + (f", staleness {fs['staleness_histogram']}"
                        if fs["staleness_histogram"] else ""))
    w = summary.get("watchdog")
    if w:
        lines.append(f"  watchdog: {w['alarms']} alarm(s), "
                     f"{w['rollbacks']} rollback(s) "
                     f"reasons={w['reasons']}")
    rb = summary.get("robustness")
    if rb:
        lines.append(f"  robustness: {rb['quarantine_events']} quarantine "
                     f"event(s), dropped clients {rb['clients_dropped']}")
    cl = summary.get("clients")
    if cl:
        mem = cl.get("membership")
        churn = ""
        if mem:
            churn = (f"; membership: {mem['joins']} join(s) "
                     f"({mem['join_repacks']} repack(s)), "
                     f"{mem['leaves']} departure(s), "
                     f"{mem['drift_alarms']} drift alarm(s)")
        lines.append(f"  clients: {cl['tracked']} tracked over "
                     f"{cl['rounds']} round(s){churn}")
        for c, d in cl.get("per_client", {}).items():
            wf, wl = d.get("weight_first"), d.get("weight_last")
            traj = (f"weight {wf:.4f}->{wl:.4f}"
                    if wf is not None and wl is not None else "weight n/a")
            extra = ""
            if d.get("joined_round") is not None:
                extra += (f", joined@{d['joined_round']}"
                          + (" (repack)" if d.get("join_repacked") else ""))
            if d.get("drift_alarms"):
                extra += f", {d['drift_alarms']} drift alarm(s)"
            if d.get("quarantined_rounds"):
                extra += (f", {d['quarantined_rounds']} quarantined "
                          f"round(s), {d['strikes']} strike(s)")
            if d.get("left"):
                left = d["left"]
                extra += (f" [LEFT ({left})]" if isinstance(left, str)
                          else " [LEFT]")
            elif d.get("dropped"):
                extra += " [DROPPED]"
            lines.append(f"    client {c}: {traj}, "
                         f"{d['rounds']} round(s){extra}")
        if cl.get("top_movers"):
            movers = ", ".join(f"client {c} {delta:+.4f}"
                               for c, delta in cl["top_movers"])
            lines.append(f"    top movers: {movers}")
        for f in cl.get("forensics", []):
            tail = ""
            if f.get("watchdog"):
                tail += f" -> watchdog {f['watchdog']}"
            if f.get("dropped"):
                tail += f" -> dropped ({f['dropped']})"
            lines.append(
                f"    forensics: client {f['client']} quarantined rounds "
                f"{f.get('first')}..{f.get('last')} "
                f"(test={f.get('test')}, strikes={f.get('strikes')}){tail}")
    dr = summary.get("drift")
    if dr:
        lines.append(f"  drift: {dr['alarms_total']} alarm(s) over "
                     f"{dr['windows']} window(s), max jsd rise "
                     f"{dr.get('max_jsd_rise')}, max wd rise "
                     f"{dr.get('max_wd_rise')}, "
                     f"{dr['final_live']}/{dr['final_population']} live at "
                     f"the last window"
                     + (f", evicted {dr['evicted']}" if dr["evicted"]
                        else ""))
    sim = summary.get("similarity")
    if sim and sim.get("avg_jsd_last") is not None:
        wd = (f" avg_wd {sim['avg_wd_last']}"
              if sim.get("avg_wd_last") is not None else "")
        lines.append(f"  similarity: {sim['samples']} sample(s), epochs "
                     f"{sim.get('first_epoch')}..{sim.get('last_epoch')}, "
                     f"avg_jsd last {sim['avg_jsd_last']} "
                     f"(best {sim['avg_jsd_best']}){wd}")
        if sim.get("worst_columns"):
            worst = ", ".join(f"{k}={v}" for k, v in sim["worst_columns"])
            lines.append(f"    worst columns (jsd): {worst}")
    tr = summary.get("transport")
    if tr:
        lines.append(f"  transport: {tr['reconnects']} reconnect(s), "
                     f"{tr['drops']} drop(s), "
                     f"{tr['heartbeat_lapses']} heartbeat lapse(s)")
    c = summary.get("compiles")
    if c:
        lines.append(f"  compiles: {sum(c.values())} event(s) across "
                     f"{len(c)} program(s)")
    ck = summary.get("checkpoints")
    if ck:
        lines.append(f"  checkpoints: {ck['saved']} saved, "
                     f"{ck['restores']} restore(s), last {ck['last_path']}")
    sv = summary.get("serving")
    if sv:
        lines.append(f"  serving: {sv['reloads']} hot reload(s), "
                     f"{sv['fleet_loads']} tenant load(s), "
                     f"{sv['fleet_evicts']} evict(s)"
                     + (f", shed by tenant {sv['shed_by_tenant']}"
                        if sv["shed_by_tenant"] else ""))
    q = summary.get("quality")
    if q:
        lines.append(f"  quality: {q['promotions']} promotion(s), "
                     f"{q['rejections']} rejection(s), "
                     f"{q['reload_failures']} reload failure(s)"
                     + (f", tripped {q['tripped_budgets']}"
                        if q["tripped_budgets"] else ""))
        for t, d in q.get("per_tenant", {}).items():
            scores = ""
            if d.get("avg_jsd_last") is not None:
                scores = (f"  avg_jsd {d['avg_jsd_last']} "
                          f"avg_wd {d.get('avg_wd_last')}")
            lines.append(f"    tenant {t}: {d['promotions']} promoted, "
                         f"{d['rejections']} rejected{scores}")
        lr = q.get("last_rejection")
        if lr:
            worst = sorted(
                ((c, v) for c, v in (lr.get("per_column") or {}).items()
                 if isinstance(v, dict)
                 and isinstance(v.get("delta"), (int, float))),
                key=lambda kv: (-abs(kv[1]["delta"]), kv[0]))[:3]
            cols = ", ".join(f"{c} {v['delta']:+.4f}" for c, v in worst)
            lines.append(f"    last rejection: candidate "
                         f"{lr.get('candidate')} tripped "
                         f"{lr.get('tripped')}"
                         + (f"; worst columns: {cols}" if cols else ""))
    pg = summary.get("programs")
    if pg:
        lines.append(f"  programs: {pg['ledgered']} ledgered, "
                     f"{pg['total_flops'] / 1e6:.2f} Mflops total, "
                     f"peak {pg['peak_bytes_max'] / 1e6:.2f} MB")
        for name, c in pg.get("per_program", {}).items():
            lines.append(
                f"    {name:<38} {float(c['flops'] or 0) / 1e6:>9.2f} Mflop "
                f"{float(c['bytes_accessed'] or 0) / 1e6:>9.2f} MB acc "
                f"{int(c['peak_bytes'] or 0) / 1e6:>7.2f} MB peak "
                f"x{c['compiles']}")
        for t in pg.get("device_traces", []):
            status = "ok" if t["ok"] else f"FAILED ({t.get('error')})"
            lines.append(f"    device trace: {t.get('dir')} [{status}]")
    ini = summary.get("init")
    if ini:
        lines.append(f"  init: {ini['total_seconds']}s across "
                     f"{len(ini['phases'])} phase(s)")
        for phase, d in ini["phases"].items():
            rate = ""
            if d.get("clients_per_s") is not None:
                rate += f" {d['clients_per_s']:>8.1f} clients/s"
            if d.get("rows_per_s") is not None:
                rate += f" {d['rows_per_s']:>8d} rows/s"
            lines.append(f"    {phase:<32} {d['seconds']:>9.3f}s "
                         f"x{d['count']}{rate}")
    ic = summary.get("init_cache")
    if ic:
        rate = (f", hit rate {ic['hit_rate']:.1%}"
                if ic.get("hit_rate") is not None else "")
        corrupt = (f", {ic['corrupt']} CORRUPT entry(ies) refit"
                   if ic.get("corrupt") else "")
        lines.append(f"  init cache: {ic['hits']} hit(s), "
                     f"{ic['misses']} miss(es){rate}{corrupt}")
        for k, n in ic.get("by_op", {}).items():
            lines.append(f"    {k:<32} {n:>9d}")
    ss = summary.get("serve_stages")
    if ss:
        lines.append("  serving stages (worst window):")
        for stage, d in ss.items():
            lines.append(f"    {stage:<12} p50 {d['p50_ms']:>8.2f} ms  "
                         f"p99 {d['p99_ms']:>8.2f} ms  n={d['count']}")
    bp = summary.get("backend_probes")
    if bp:
        lines.append(f"  backend probes: {bp['total']} "
                     f"({bp['failed']} failed)")
    return "\n".join(lines)


def report_main(path: Union[str, Sequence[str]], fmt: str = "text") -> int:
    paths = [path] if isinstance(path, str) else list(path)

    def warn(msg: str) -> None:
        print(f"obs report: warning: {msg}", file=sys.stderr)

    try:
        summary = summarize_many(paths, on_skip=warn)
    except OSError as exc:
        print(f"obs report: cannot read {paths}: {exc}")
        return 2
    if fmt == "json":
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(render_text(summary))
    return 0
