"""Durable run journal: one JSONL event stream per run.

Every line is one event::

    {"ts": <unix seconds>, "type": "<event type>", ...fields}

The first line of a journal is always a ``run_start`` event carrying
``schema`` (the journal schema version) and ``run_id``.  Consumers --
``python -m fed_tgan_tpu.obs report``, soak analysis, dashboards --
key off ``type``; unknown types must be ignored, unknown fields
preserved (the schema is append-only: fields are added, never renamed).

``EVENT_TYPES`` is derived from the checked-in telemetry contract
registry ``fed_tgan_tpu/obs/schema.json`` (the obslint source of
truth: per-event required/optional/external fields and producers).
The catalogue below is the prose mirror of that registry;
``tests/test_obslint.py`` holds the two in sync.

Event catalogue (``EVENT_TYPES``):

========================  ====================================================
type                      emitted by / meaning
========================  ====================================================
run_start / run_end       journal lifecycle (run_end carries ``seconds``)
round                     trainer round-chunk summary (first/last/seconds/...)
aggregate                 aggregation summary for a chunk (aggregator, clients)
cohort                    per-round partial-participation summary (population,
                          sampled client ids, staleness histogram, buffered
                          update counts)
quarantine                in-round update screen quarantined a client
                          (``test="drift"`` when charged by the elastic
                          drift detector instead of the in-graph gate)
client_dropped            dead/evicted client removed from federation
client_joined             elastic federation admitted a newcomer between
                          rounds (round, population, capacity, weight,
                          rows, whether admission forced a bucket repack)
client_left               elastic federation departure (scripted or
                          drift-evicted) before the dropout-path
                          ``client_dropped`` that executes it
drift_alarm               per-window drift probe flagged a client (raw
                          JSD/WD rises vs its onboarding baseline)
drift_window              one detection-window summary: population, scored
                          clients, alarm count, sustained/evicted lists,
                          max score rises, refit lag -- the drift
                          trajectory artifact row
watchdog_alarm            training-health watchdog tripped
watchdog_rollback         watchdog restored params from a checkpoint
checkpoint                crash-safe checkpoint published
checkpoint_restore        checkpoint loaded for resume/rollback
transport_reconnect       transport peer re-established after a drop
transport_drop            server marked a peer dead
heartbeat_lapse           liveness deadline exceeded for a peer
compile                   XLA compile event (from the sanitizer counter)
backend_probe             subprocess backend-responsiveness probe outcome
backend_plugin_registered PJRT plugin backend registered with the runtime
                          (plugin name, shared-library path)
device_trace              runtime/profiling device trace start/stop/failure
serve_reload              serving hot-reloaded a model artifact
serve_reload_failed       a new checkpoint generation failed to load (torn
                          write / bad decode artifacts); the previous model
                          keeps serving and the generation is not retried
promotion_promoted        canary gate promoted a candidate model (scores,
                          deltas vs the incumbent baseline, model ids)
promotion_rejected        canary gate auto-rejected a candidate: forensics
                          event carrying per-column deltas, the tripped
                          quality budgets, and both model ids
fleet_load                fleet admin loaded a tenant model
fleet_evict               fleet admin evicted a tenant model
tenant_shed               per-tenant admission shed requests (rate-limited
                          summary event carrying counts, never per-request)
program_cost              compiled-program cost ledger entry (flops, bytes
                          accessed, peak/argument/output/temp bytes)
init_phase                federated onboarding phase finished (phase name,
                          seconds, client count, rows)
init_cache                encoded-shard cache outcome summary (op = hit |
                          miss | store | corrupt, scope = client | global,
                          count)
serve_stages              per-stage serving latency summary (rate-limited:
                          stage means/counts since the last event)
client_contribution       per-round per-client ledger (parallel arrays keyed
                          by global client id: weights, losses, quarantine
                          mask, strikes) from the one gated metrics pull
similarity                monitor probe sample (epoch, avg_jsd, avg_wd and,
                          when available, per-column values)
slo_breach                live SLO re-evaluation flagged a budget regression
                          (rule name, figure, bound) -- emitted by obs watch
schema_violation          the runtime schema sanitizer (``validate=True``)
                          saw an emit that breaks the registry contract
                          (offending event type, problem, field); emitted
                          once per distinct violation, never raised
========================  ====================================================

Writers go through a process-wide current journal: ``set_journal``
installs one, module-level :func:`emit` is a cheap no-op while none is
installed, so library code can emit unconditionally.  ``RunJournal``
itself is thread-safe and flushes every line (durability over
throughput -- journals are low-rate by design; the hot path emits at
round granularity, never per-step).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional

__all__ = [
    "SCHEMA_VERSION",
    "EVENT_TYPES",
    "RunJournal",
    "emit",
    "get_journal",
    "read_journal",
    "set_journal",
    "validation_violations",
]

SCHEMA_VERSION = 1

SCHEMA_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "schema.json")

# journals must keep working from a tree without the registry (sdist
# subsets, very old checkouts): a missing/corrupt schema.json leaves
# EVENT_TYPES empty and the runtime sanitizer disarmed.
def _load_event_schemas() -> Dict[str, dict]:
    try:
        with open(SCHEMA_PATH) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    events = doc.get("events") if isinstance(doc, dict) else None
    if not isinstance(events, dict):
        return {}
    return {name: spec for name, spec in events.items()
            if isinstance(spec, dict)}


_EVENT_SCHEMAS = _load_event_schemas()
EVENT_TYPES = frozenset(_EVENT_SCHEMAS)

_VALIDATE_ENV = "FED_TGAN_TPU_VALIDATE_JOURNAL"
_BASE_FIELDS = frozenset({"ts", "type"})

# violations seen by env-armed journals (the tier-1 arming path);
# the test session gate asserts this stays empty across the suite
_VALIDATION_VIOLATIONS: List[dict] = []


def validation_violations() -> List[dict]:
    """Schema violations recorded by env-armed journals this process."""
    return list(_VALIDATION_VIOLATIONS)


class RunJournal:
    """Append-only JSONL event writer for one run.

    ``emit()`` never raises into the instrumented caller: a journal
    that loses its disk must not take the training run down with it.

    ``validate`` arms the runtime schema sanitizer: every emit is
    checked against the ``obs/schema.json`` contract (unknown type,
    missing required field, unlisted field on a closed event) and each
    distinct violation journals one ``schema_violation`` event, bumps
    ``self.schema_violations`` and the
    ``fed_tgan_journal_schema_violations_total`` counter -- it never
    raises.  ``validate=None`` (the default) arms from the
    ``FED_TGAN_TPU_VALIDATE_JOURNAL`` env var (how tier-1 tests, soak,
    and doctor run) and additionally tallies into the process-wide
    :func:`validation_violations` list the test session gate asserts
    empty.
    """

    def __init__(self, path: str, run_id: Optional[str] = None,
                 validate: Optional[bool] = None) -> None:
        self.path = str(path)
        self.run_id = run_id or uuid.uuid4().hex[:12]
        directory = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(directory, exist_ok=True)
        if validate is None:
            env = os.environ.get(_VALIDATE_ENV, "")
            validate = env.lower() not in ("", "0", "false", "no")
            self._tally_global = validate
        else:
            self._tally_global = False
        self.validate = bool(validate) and bool(_EVENT_SCHEMAS)
        self.schema_violations = 0
        self._violation_keys: set = set()
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", buffering=1)
        self._t0 = time.time()
        self._closed = False
        self.emit("run_start", schema=SCHEMA_VERSION, run_id=self.run_id,
                  pid=os.getpid())

    def _check_schema(self, type: str, fields: dict) -> List[tuple]:
        """``(problem, field)`` pairs for one emit; [] when clean."""
        spec = _EVENT_SCHEMAS.get(type)
        if spec is None:
            return [("unknown_type", None)]
        problems = []
        for req in spec.get("required", ()):
            if req not in fields:
                problems.append(("missing_field", req))
        if not spec.get("open", False):
            known = (set(spec.get("required", ()))
                     | set(spec.get("optional", ()))
                     | set(spec.get("external", ())) | _BASE_FIELDS)
            problems.extend(("unknown_field", f)
                            for f in sorted(fields) if f not in known)
        return problems

    def _record_violation(self, type: str, problem: str,
                          field: Optional[str]) -> None:
        key = (type, problem, field)
        with self._lock:
            if key in self._violation_keys:
                return
            self._violation_keys.add(key)
            self.schema_violations += 1
        if self._tally_global:
            _VALIDATION_VIOLATIONS.append(
                {"event": type, "problem": problem, "field": field,
                 "path": self.path})
        try:
            # lazy: the registry must not be an import-time dependency
            from fed_tgan_tpu.obs.registry import counter as _schema_counter

            _schema_counter(
                "fed_tgan_journal_schema_violations_total").inc()
        except Exception:  # noqa: BLE001 -- sanitizer never raises
            pass
        extra = {"field": field} if field is not None else {}
        self.emit("schema_violation", event=type, problem=problem, **extra)

    def emit(self, type: str, **fields) -> Optional[dict]:
        """Append one event; returns the event dict (None if closed)."""
        type = str(type)
        if self.validate and type != "schema_violation":
            for problem, field in self._check_schema(type, fields):
                self._record_violation(type, problem, field)
        event: Dict[str, object] = {"ts": round(time.time(), 6),
                                    "type": type}
        event.update(fields)
        try:
            line = json.dumps(event, default=str)
        except (TypeError, ValueError):
            event = {"ts": event["ts"], "type": event["type"],
                     "error": "unserializable fields dropped"}
            line = json.dumps(event)
        with self._lock:
            if self._closed:
                return None
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError:
                return None
        return event

    def close(self) -> None:
        self.emit("run_end", seconds=round(time.time() - self._t0, 3))
        with self._lock:
            if not self._closed:
                self._closed = True
                try:
                    self._fh.close()
                except OSError:
                    pass

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_INSTALL_LOCK = threading.Lock()
_JOURNAL: Optional[RunJournal] = None


def set_journal(journal: Optional[RunJournal]) -> Optional[RunJournal]:
    """Install ``journal`` as the process journal; returns the previous."""
    global _JOURNAL
    with _INSTALL_LOCK:
        prev, _JOURNAL = _JOURNAL, journal
        return prev


def get_journal() -> Optional[RunJournal]:
    return _JOURNAL


def emit(type: str, **fields) -> Optional[dict]:
    """Emit into the process journal; free no-op while none installed."""
    j = _JOURNAL
    if j is None:
        return None
    return j.emit(type, **fields)


def read_journal(path: str, on_skip=None) -> Iterator[dict]:
    """Yield parsed events; tolerates blank and truncated tail lines.

    ``on_skip``, when given, is called with a one-line description for
    every undecodable line (a crashed writer leaves a torn final line);
    CLI readers route it to stderr, library readers stay silent.
    """
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                # torn tail write on crash -- skip, don't die
                if on_skip is not None:
                    on_skip(f"{path}:{lineno}: skipping truncated journal "
                            f"line ({len(line)} bytes)")
                continue
            if isinstance(event, dict):
                yield event


def load_journal(path: str) -> List[dict]:
    return list(read_journal(path))
