"""Host-side span tracing with Chrome-trace / Perfetto JSON export.

``span(name, **attrs)`` is the one instrumentation point: a context
manager that records a complete ("X") event -- name, start, duration,
thread, nesting depth -- into the installed :class:`Tracer`.  With no
tracer installed it is a near-free no-op (one global read), so
instrumented code pays nothing outside profiled runs.

Spans are provably free on the device hot path: they touch only
``time.perf_counter`` and Python objects, never device arrays, so they
compose with ``analysis.sanitizers.hot_region`` (no device->host sync
is ever introduced by tracing).

The export format is the Chrome trace-event JSON flavour that Perfetto
and ``chrome://tracing`` load directly -- the same family as the XLA
device trace from ``runtime/profiling.py::device_trace``, so host
phase spans and the device timeline can be overlaid in one UI.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "Tracer",
    "current_tracer",
    "span",
    "start_tracing",
    "stop_tracing",
]


class Tracer:
    """Bounded in-memory trace-event collector."""

    def __init__(self, max_events: int = 200_000) -> None:
        self.max_events = int(max_events)
        self.dropped = 0
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._local = threading.local()
        self._t0 = time.perf_counter()
        self._pid = os.getpid()

    # ------------------------------------------------------------ record

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def _record(self, event: dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(event)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Record a complete event covering the with-block."""
        depth = self._depth()
        self._local.depth = depth + 1
        start = self._now_us()
        try:
            yield self
        finally:
            dur = self._now_us() - start
            self._local.depth = depth
            args: Dict[str, object] = {"depth": depth}
            args.update(attrs)
            self._record({
                "name": name, "ph": "X", "ts": start, "dur": dur,
                "pid": self._pid, "tid": threading.get_ident(),
                "args": args,
            })

    def instant(self, name: str, **attrs) -> None:
        """Record a zero-duration instant event (scope: thread)."""
        self._record({
            "name": name, "ph": "i", "s": "t", "ts": self._now_us(),
            "pid": self._pid, "tid": threading.get_ident(),
            "args": dict(attrs),
        })

    # ------------------------------------------------------------ export

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON object (Perfetto-loadable)."""
        meta = [{
            "name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
            "args": {"name": "fed_tgan_tpu host"},
        }]
        return {"traceEvents": meta + self.events(),
                "displayTimeUnit": "ms"}

    def export(self, path: str) -> str:
        """Write the Chrome-trace JSON to ``path``; returns ``path``."""
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self.to_chrome(), fh)
        return path

    def phase_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name attribution: ``{name: {count, total_ms, mean_ms}}``.

        Only top-level occurrences of a name are summed (a span nested
        inside a same-named parent would double-count its parent), which
        makes this the host-phase attribution table for bench reports --
        the collection side that ``scripts/trace_attribution.py`` used
        to rebuild from the device trace.
        """
        out: Dict[str, Dict[str, float]] = {}
        for ev in self.events():
            if ev.get("ph") != "X":
                continue
            rec = out.setdefault(ev["name"],
                                 {"count": 0.0, "total_ms": 0.0})
            rec["count"] += 1
            rec["total_ms"] += ev.get("dur", 0.0) / 1e3
        for rec in out.values():
            rec["mean_ms"] = rec["total_ms"] / max(1.0, rec["count"])
        return out


_INSTALL_LOCK = threading.Lock()
_TRACER: Optional[Tracer] = None


def start_tracing(max_events: int = 200_000) -> Tracer:
    """Install (or return the already-installed) process tracer."""
    global _TRACER
    with _INSTALL_LOCK:
        if _TRACER is None:
            _TRACER = Tracer(max_events=max_events)
        return _TRACER


def stop_tracing() -> Optional[Tracer]:
    """Uninstall and return the process tracer (None when inactive)."""
    global _TRACER
    with _INSTALL_LOCK:
        t, _TRACER = _TRACER, None
        return t


def current_tracer() -> Optional[Tracer]:
    return _TRACER


@contextlib.contextmanager
def span(name: str, **attrs):
    """Span against the installed tracer; free no-op when none is."""
    t = _TRACER
    if t is None:
        yield None
        return
    with t.span(name, **attrs):
        yield t
