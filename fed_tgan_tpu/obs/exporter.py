"""In-trainer telemetry exporter: /metrics, /healthz, /journal over HTTP.

The live half of the observability plane: while ``obs report`` reads a
finished journal, the exporter lets dashboards and ``obs watch`` see a
run *in flight*.  It is a daemon-threaded stdlib HTTP server started
inside the training process (opt-in via ``--obs-port``), so it must be
invisible to the device program: every endpoint reads host-side state
only -- the process metrics registry, a :class:`HealthState` dict the
trainer updates with values it already holds on host, and the journal
file on disk.  No endpoint touches a jax array; the module never
imports jax.

Endpoints:

``/metrics``
    The process-wide registry in Prometheus text format
    (``render_prometheus()``), including the per-client labeled ledger
    series the trainer publishes.

``/healthz``
    JSON snapshot of training health: round progress, rounds/s,
    watchdog alarm/rollback counts, quarantine census, cohort info.

``/journal``
    The run journal as NDJSON.  ``?offset=N`` returns bytes from file
    offset ``N`` (incremental polling; the response carries the next
    offset in ``X-Journal-Offset``).  ``?follow=1`` keeps the socket
    open and tail-streams new lines as the trainer appends them, until
    the client disconnects or the exporter drains.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from fed_tgan_tpu.obs.journal import get_journal
from fed_tgan_tpu.obs.registry import get_registry

__all__ = ["HealthState", "TelemetryExporter", "get_health"]

_FOLLOW_POLL_S = 0.1


class HealthState:
    """Thread-safe bag of host-side health fields for ``/healthz``.

    Writers (trainer, watchdog, multihost ranks) call ``update`` with
    plain scalars/lists they already hold on host -- a dict merge under
    a lock, nothing device-visible.  Readers get a copy via
    ``snapshot``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._fields: Dict[str, object] = {}
        self._started = time.time()

    def update(self, **fields) -> None:
        with self._lock:
            self._fields.update(fields)
            self._fields["updated_ts"] = round(time.time(), 3)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out = dict(self._fields)
        out.setdefault("status", "idle")
        out["uptime_s"] = round(time.time() - self._started, 3)
        return out

    def reset(self) -> None:
        with self._lock:
            self._fields.clear()


_HEALTH = HealthState()


def get_health() -> HealthState:
    """The process-wide health state the exporter serves at /healthz."""
    return _HEALTH


def _make_handler(exporter: "TelemetryExporter"):
    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:  # quiet by design
            pass

        def _send(self, code: int, body: bytes, ctype: str,
                  extra: Optional[Dict[str, str]] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
            parsed = urlparse(self.path)
            try:
                if parsed.path == "/metrics":
                    body = exporter.registry.render_prometheus().encode()
                    self._send(200, body, "text/plain; version=0.0.4")
                elif parsed.path == "/healthz":
                    body = json.dumps(exporter.health.snapshot(),
                                      default=str).encode()
                    self._send(200, body, "application/json")
                elif parsed.path == "/journal":
                    self._journal(parse_qs(parsed.query))
                else:
                    self._send(404, b"not found", "text/plain")
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-response

        def _journal(self, query) -> None:
            path = exporter.journal_path
            if path is None:
                self._send(404, b"no journal installed", "text/plain")
                return
            try:
                offset = int(query.get("offset", ["0"])[0])
            except ValueError:
                offset = 0
            follow = query.get("follow", ["0"])[0] in ("1", "true")
            try:
                fh = open(path, "rb")
            except OSError:
                self._send(404, b"journal file missing", "text/plain")
                return
            with fh:
                fh.seek(offset)
                data = fh.read()
                if not follow:
                    self._send(200, data, "application/x-ndjson",
                               {"X-Journal-Offset": str(offset + len(data))})
                    return
                # follow mode: close-delimited stream, flushed per poll
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Connection", "close")
                self.end_headers()
                while True:
                    if data:
                        self.wfile.write(data)
                        self.wfile.flush()
                    if exporter.draining:
                        return
                    time.sleep(_FOLLOW_POLL_S)
                    data = fh.read()

    return _Handler


class TelemetryExporter:
    """Opt-in background HTTP exporter for one training process.

    Lifecycle mirrors ``serve.service.SynthService``: ``start()`` binds
    and spins a daemon serve thread, ``shutdown()`` drains follow
    streams, stops the server, and joins.  ``port=0`` binds an
    ephemeral port (tests); the bound port is ``self.port``.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry=None, journal_path: Optional[str] = None,
                 health: Optional[HealthState] = None) -> None:
        self._port = int(port)
        self.host = host
        self.registry = registry if registry is not None else get_registry()
        self._journal_path = journal_path
        self.health = health if health is not None else get_health()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.draining = False

    @property
    def journal_path(self) -> Optional[str]:
        if self._journal_path is not None:
            return self._journal_path
        j = get_journal()
        return j.path if j is not None else None

    @property
    def port(self) -> int:
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "TelemetryExporter":
        if self._httpd is not None:
            return self
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self._port), handler)
        self._httpd.daemon_threads = True
        self.draining = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="obs-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        if self._httpd is None:
            return
        self.draining = True  # unblocks ?follow=1 streams
        time.sleep(_FOLLOW_POLL_S)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None

    def __enter__(self) -> "TelemetryExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()
