"""Process-wide metrics registry: counters, gauges, histograms.

Pure stdlib, thread-safe, importable before jax/numpy warm-up.  One
process-wide default registry (:func:`get_registry`) collects metrics
from every subsystem -- training, transport, checkpointing, watchdog,
serving -- and renders them in Prometheus text exposition format.
Isolated :class:`MetricsRegistry` instances exist for tests and for
per-service scoping (``serve.metrics.ServiceMetrics`` holds its own).

Metric names follow Prometheus conventions: ``<subsystem>_<what>_total``
for counters, bare gauges for instantaneous values.  ``counter()`` /
``gauge()`` / ``histogram()`` are get-or-create: the same name always
returns the same object, and a name collision across metric kinds
raises ``TypeError`` rather than silently aliasing.
"""

from __future__ import annotations

import bisect
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "get_registry",
    "counter",
    "gauge",
    "histogram",
]

#: default histogram bucket upper bounds (seconds-flavoured, like
#: Prometheus client defaults) -- override per-histogram for other units
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Counter:
    """Monotonically increasing value.  ``inc(amount)`` with amount >= 0."""

    kind = "counter"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Instantaneous value: ``set`` / ``inc`` / ``dec``."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self.value


class Histogram:
    """Distribution: Prometheus cumulative buckets + an exact-quantile
    reservoir.

    The bucket counts / sum / count follow the Prometheus histogram
    exposition; the bounded ``reservoir`` (most recent N observations)
    additionally gives exact ``quantile()`` answers over the recent
    window -- the serving p50/p99 contract predates this registry and
    is preserved by it.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Iterable[float] = DEFAULT_BUCKETS,
                 reservoir: int = 4096) -> None:
        self.name = name
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(sorted(set(buckets)))
        self._lock = threading.Lock()
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._count = 0
        self._sum = 0.0
        self._reservoir: deque = deque(maxlen=max(1, int(reservoir)))

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._bucket_counts[idx] += 1
            self._count += 1
            self._sum += value
            self._reservoir.append(value)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def reservoir_values(self) -> List[float]:
        """Sorted copy of the recent-observation reservoir."""
        with self._lock:
            return sorted(self._reservoir)

    def quantile(self, q: float) -> float:
        """Exact nearest-rank quantile over the reservoir window."""
        lat = self.reservoir_values()
        if not lat:
            return 0.0
        idx = min(len(lat) - 1, max(0, round(q * (len(lat) - 1))))
        return lat[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            counts = list(self._bucket_counts)
            total, s = self._count, self._sum
        out: Dict[str, float] = {"count": total, "sum": s}
        cum = 0
        for le, n in zip(self.buckets, counts):
            cum += n
            out[f"le_{le:g}"] = cum
        return out


_METRIC_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Thread-safe, get-or-create registry of named metrics."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, requested {cls.kind}")
                return existing
            metric = cls(name, help=help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS,
                  reservoir: int = 4096) -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   buckets=buckets, reservoir=reservoir)

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, object]:
        """``{name: value-or-dict}`` for every registered metric."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.snapshot() for m in metrics}

    def render_prometheus(self) -> str:
        """Prometheus text exposition of every registered metric."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                snap = m.snapshot()
                cum = 0.0
                for le in m.buckets:
                    cum = snap[f"le_{le:g}"]
                    lines.append(f'{m.name}_bucket{{le="{le:g}"}} {cum:g}')
                lines.append(f'{m.name}_bucket{{le="+Inf"}} '
                             f'{snap["count"]:g}')
                lines.append(f"{m.name}_sum {snap['sum']:g}")
                lines.append(f"{m.name}_count {snap['count']:g}")
            else:
                lines.append(f"{m.name} {m.snapshot():g}")
        return "\n".join(lines) + "\n"


#: the process-wide default registry every subsystem publishes into
_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _DEFAULT


def counter(name: str, help: str = "") -> Counter:
    return _DEFAULT.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _DEFAULT.gauge(name, help)


def histogram(name: str, help: str = "", **kwargs) -> Histogram:
    return _DEFAULT.histogram(name, help, **kwargs)
