"""Process-wide metrics registry: counters, gauges, histograms.

Pure stdlib, thread-safe, importable before jax/numpy warm-up.  One
process-wide default registry (:func:`get_registry`) collects metrics
from every subsystem -- training, transport, checkpointing, watchdog,
serving -- and renders them in Prometheus text exposition format.
Isolated :class:`MetricsRegistry` instances exist for tests and for
per-service scoping (``serve.metrics.ServiceMetrics`` holds its own).

Metric names follow Prometheus conventions: ``<subsystem>_<what>_total``
for counters, bare gauges for instantaneous values.  ``counter()`` /
``gauge()`` / ``histogram()`` are get-or-create: the same name always
returns the same object, and a name collision across metric kinds
raises ``TypeError`` rather than silently aliasing.
"""

from __future__ import annotations

import bisect
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "get_registry",
    "counter",
    "gauge",
    "histogram",
]

#: default histogram bucket upper bounds (seconds-flavoured, like
#: Prometheus client defaults) -- override per-histogram for other units
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Counter:
    """Monotonically increasing value.  ``inc(amount)`` with amount >= 0."""

    kind = "counter"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Instantaneous value: ``set`` / ``inc`` / ``dec``."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self.value


class Histogram:
    """Distribution: Prometheus cumulative buckets + an exact-quantile
    reservoir.

    The bucket counts / sum / count follow the Prometheus histogram
    exposition; the bounded ``reservoir`` (most recent N observations)
    additionally gives exact ``quantile()`` answers over the recent
    window -- the serving p50/p99 contract predates this registry and
    is preserved by it.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Iterable[float] = DEFAULT_BUCKETS,
                 reservoir: int = 4096) -> None:
        self.name = name
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(sorted(set(buckets)))
        self._lock = threading.Lock()
        self._bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._count = 0
        self._sum = 0.0
        self._reservoir: deque = deque(maxlen=max(1, int(reservoir)))

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._bucket_counts[idx] += 1
            self._count += 1
            self._sum += value
            self._reservoir.append(value)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def reservoir_values(self) -> List[float]:
        """Sorted copy of the recent-observation reservoir."""
        with self._lock:
            return sorted(self._reservoir)

    def quantile(self, q: float) -> float:
        """Exact nearest-rank quantile over the reservoir window."""
        lat = self.reservoir_values()
        if not lat:
            return 0.0
        idx = min(len(lat) - 1, max(0, round(q * (len(lat) - 1))))
        return lat[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            counts = list(self._bucket_counts)
            total, s = self._count, self._sum
        out: Dict[str, float] = {"count": total, "sum": s}
        cum = 0
        for le, n in zip(self.buckets, counts):
            cum += n
            out[f"le_{le:g}"] = cum
        return out


_METRIC_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


def _series_key(name: str, labels: Optional[dict]) -> str:
    """Prometheus series identity: ``name`` or ``name{k="v",...}`` with
    labels in sorted key order — the registry key AND the exposition
    form, so labeled lookups and rendering cannot disagree."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Thread-safe, get-or-create registry of named metrics.

    Metrics may carry Prometheus labels (``labels={"tenant": "a"}``):
    each label set is its own series (own counter object), sharing the
    base name's HELP/TYPE header in the exposition.  Unlabeled metrics
    are keyed, snapshotted, and rendered exactly as before.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Optional[dict] = None, **kwargs):
        key = _series_key(name, labels)
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise TypeError(
                        f"metric {key!r} already registered as "
                        f"{existing.kind}, requested {cls.kind}")
                return existing
            metric = cls(name, help=help, **kwargs)
            metric.labels = dict(labels) if labels else None
            metric.series = key
            self._metrics[key] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS,
                  reservoir: int = 4096,
                  labels: Optional[dict] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets, reservoir=reservoir)

    def get(self, name: str) -> Optional[object]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, object]:
        """``{series: value-or-dict}`` for every registered metric (the
        series key is the bare name for unlabeled metrics)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {getattr(m, "series", m.name): m.snapshot() for m in metrics}

    def render_prometheus(self) -> str:
        """Prometheus text exposition of every registered metric.  Labeled
        series of one base name share a single HELP/TYPE header."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: (m.name, getattr(m, "series",
                                                            m.name)))
        lines: List[str] = []
        headered = set()
        for m in metrics:
            if m.name not in headered:
                headered.add(m.name)
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
            labels = getattr(m, "labels", None)
            inner = ",".join(f'{k}="{v}"' for k, v in
                             sorted((labels or {}).items()))
            if isinstance(m, Histogram):
                snap = m.snapshot()
                suffix = f"{{{inner}}}" if inner else ""

                def bucket_label(le: str) -> str:
                    return (f'{{{inner},le="{le}"}}' if inner
                            else f'{{le="{le}"}}')

                cum = 0.0
                for le in m.buckets:
                    cum = snap[f"le_{le:g}"]
                    lines.append(f"{m.name}_bucket{bucket_label(f'{le:g}')} "
                                 f"{cum:g}")
                lines.append(f"{m.name}_bucket{bucket_label('+Inf')} "
                             f"{snap['count']:g}")
                lines.append(f"{m.name}_sum{suffix} {snap['sum']:g}")
                lines.append(f"{m.name}_count{suffix} {snap['count']:g}")
            else:
                series = getattr(m, "series", m.name)
                lines.append(f"{series} {m.snapshot():g}")
        return "\n".join(lines) + "\n"


#: the process-wide default registry every subsystem publishes into
_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _DEFAULT


def counter(name: str, help: str = "") -> Counter:
    return _DEFAULT.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _DEFAULT.gauge(name, help)


def histogram(name: str, help: str = "", **kwargs) -> Histogram:
    return _DEFAULT.histogram(name, help, **kwargs)
