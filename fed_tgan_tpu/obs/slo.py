"""SLO regression gate: check bench records / journals against budgets.

``python -m fed_tgan_tpu.obs slo <input> [--budgets FILE]`` reads one
input -- a bench record JSON (single record or a ``{"records": [...]}``
bundle like ``BENCH_r07.json``) or a run-journal JSONL -- and checks it
against the checked-in budget file.  The exit-code policy mirrors the
hlolint contract checker:

- **regression** (a budget violated)  -> exit 1
- **improvement** far inside a budget -> exit 0 + a *stale budget*
  warning telling the owner to re-seed the number
- pass / nothing matched              -> exit 0
- malformed budgets or input          -> exit 2

Budget file shape (``obs/budgets.json`` is the packaged default)::

    {"schema": 1, "budgets": [
        {"name": "serving-p99",              # unique label for output
         "select": {"metric_prefix": "bench_serving(",  # optional
                    "backend": "cpu"},       # optional backend gate
         "metric": "p99_ms",                 # dotted path / figure key
         "max": 35.0,                        # or "min": <floor>
         "stale_frac": 0.4},                 # optional staleness knobs
        ...]}

For bench inputs ``metric`` is a dotted path into the record
(``per_tenant.t0.p99_ms``); ``select.metric_prefix`` restricts the rule
to records whose ``metric`` string starts with the prefix, and
``select.backend`` to records whose top-level ``backend`` field matches
(records without the field count as ``cpu`` — every pre-seam artifact is
a CPU number), so CPU-seeded budgets never misfire on ``*_tpu``
artifacts.  For journal inputs the events are first folded into flat
figures:

- ``program_cost``  -> ``program/<name>/flops|bytes_accessed|peak_bytes``
  (last event per program wins)
- ``serve_stages``  -> ``stage/<stage>/p99_ms|p50_ms`` (worst observed)
- ``init_phase``    -> ``init/<phase>/seconds`` (summed)
- ``promotion_promoted`` / ``promotion_rejected`` ->
  ``quality/avg_jsd|avg_wd|jsd_delta|wd_delta|ml_acc_delta`` (worst
  observed -- the canary gate's shadow scores)
- ``drift_window``    -> ``drift/windows|alarms_total|evicted_total``
  (counts), ``drift/max_jsd_rise|max_wd_rise|recompute_lag_rounds``
  (worst observed), ``drift/final_live`` (last event wins) -- the
  elastic-federation drift trajectory
- ``client_joined`` / ``client_left`` -> ``churn/joins_total``,
  ``churn/join_repacks`` (admissions that forced a bucket repack, i.e.
  a recompile -- budgeted to 0 inside capacity), ``churn/leaves_total``

and ``metric`` is looked up as an exact figure key (program names may
contain dots/brackets, so no dotted traversal on journal figures).

Pure stdlib -- never imports jax; safe for CI front doors.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["check_slo", "check_figures", "default_budgets_path",
           "load_budgets", "slo_main"]

#: improvement thresholds that flag a budget as stale (overridable
#: per-rule): a value under ``stale_frac * max`` or over
#: ``stale_mult * min`` means the budget no longer bounds anything.
STALE_FRAC = 0.4
STALE_MULT = 2.5


class SLOError(Exception):
    """Malformed budgets or input -- maps to exit code 2."""


def default_budgets_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "budgets.json")


def load_budgets(path: str) -> List[dict]:
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        raise SLOError(f"cannot read budgets {path!r}: {exc}") from exc
    if not isinstance(doc, dict) or not isinstance(doc.get("budgets"), list):
        raise SLOError(f"budgets {path!r}: expected "
                       '{"budgets": [...]} document')
    rules = doc["budgets"]
    for i, rule in enumerate(rules):
        if not isinstance(rule, dict) or "metric" not in rule:
            raise SLOError(f"budgets {path!r}: rule #{i} needs a 'metric'")
        if "min" not in rule and "max" not in rule:
            raise SLOError(f"budgets {path!r}: rule "
                           f"{rule.get('name', i)!r} needs 'min' or 'max'")
    return rules


# ------------------------------------------------------------------ input


def _load_input(path: str) -> Tuple[str, object]:
    """Classify the input file: ('bench', [records]) or
    ('journal', [events])."""
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as exc:
        raise SLOError(f"cannot read input {path!r}: {exc}") from exc
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        if isinstance(doc.get("records"), list):
            recs = [r for r in doc["records"] if isinstance(r, dict)]
            if recs:
                return "bench", recs
        if "metric" in doc:
            return "bench", [doc]
        raise SLOError(f"input {path!r}: JSON object is neither a bench "
                       "record nor a records bundle")
    # JSONL journal
    events = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            # torn tail line (crashed writer): skip with a warning, the
            # remaining events are still a valid gate input
            print(f"slo: warning: {path}: skipping truncated journal line "
                  f"({len(line)} bytes)", file=sys.stderr)
            continue
        if isinstance(ev, dict) and "type" in ev:
            events.append(ev)
    if not events:
        raise SLOError(f"input {path!r}: not a bench record and no "
                       "journal events parsed")
    return "journal", events


def journal_figures(events: List[dict]) -> Dict[str, float]:
    """Fold journal events into the flat figure map the rules read."""
    figures: Dict[str, float] = {}
    for ev in events:
        kind = ev.get("type")
        if kind == "program_cost":
            name = ev.get("name")
            if not name:
                continue
            for k in ("flops", "bytes_accessed", "peak_bytes",
                      "argument_bytes", "temp_bytes"):
                if k in ev:
                    figures[f"program/{name}/{k}"] = float(ev[k] or 0)
        elif kind == "serve_stages":
            stages = ev.get("stages")
            if not isinstance(stages, dict):
                continue
            for stage, st in stages.items():
                if not isinstance(st, dict):
                    continue
                for k in ("p50_ms", "p99_ms"):
                    if k in st:
                        key = f"stage/{stage}/{k}"
                        val = float(st[k] or 0)
                        figures[key] = max(figures.get(key, 0.0), val)
        elif kind == "init_phase":
            phase = ev.get("phase")
            if not phase:
                continue
            key = f"init/{phase}/seconds"
            figures[key] = figures.get(key, 0.0) + float(
                ev.get("seconds", 0) or 0)
        elif kind in ("promotion_promoted", "promotion_rejected"):
            # worst observed shadow score / delta across the run; keys
            # match the canary gate's own figure names, so the same
            # quality/* budget rules gate live promotion AND this
            # offline re-check of a journal
            for k in ("avg_jsd", "avg_wd", "jsd_delta", "wd_delta",
                      "ml_acc_delta"):
                if isinstance(ev.get(k), (int, float)):
                    key = f"quality/{k}"
                    val = float(ev[k])
                    figures[key] = max(figures.get(key, val), val)
        elif kind == "drift_window":
            figures["drift/windows"] = figures.get("drift/windows", 0.0) + 1
            figures["drift/alarms_total"] = (
                figures.get("drift/alarms_total", 0.0)
                + float(ev.get("alarms", 0) or 0))
            evicted = ev.get("evicted")
            figures["drift/evicted_total"] = (
                figures.get("drift/evicted_total", 0.0)
                + float(len(evicted) if isinstance(evicted, list) else 0))
            for k in ("max_jsd_rise", "max_wd_rise",
                      "recompute_lag_rounds"):
                if isinstance(ev.get(k), (int, float)):
                    key = f"drift/{k}"
                    val = float(ev[k])
                    figures[key] = max(figures.get(key, val), val)
            if isinstance(ev.get("live"), (int, float)):
                figures["drift/final_live"] = float(ev["live"])
        elif kind == "client_joined":
            figures["churn/joins_total"] = (
                figures.get("churn/joins_total", 0.0) + 1)
            figures["churn/join_repacks"] = (
                figures.get("churn/join_repacks", 0.0)
                + float(bool(ev.get("repacked"))))
        elif kind == "client_left":
            figures["churn/leaves_total"] = (
                figures.get("churn/leaves_total", 0.0) + 1)
    return figures


def _dotted(record: dict, path: str):
    cur: object = record
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


# ------------------------------------------------------------------ check


def _check_rule(rule: dict, value: float, where: str,
                lines: List[str]) -> Tuple[int, int]:
    """Returns (regressions, stale_warnings) for one matched value."""
    name = rule.get("name", rule["metric"])
    reg = stale = 0
    if "max" in rule:
        ceil = float(rule["max"])
        if value > ceil:
            lines.append(f"REGRESSION {name}: {value:g} > max {ceil:g} "
                         f"({where})")
            reg += 1
        elif value < ceil * float(rule.get("stale_frac", STALE_FRAC)):
            lines.append(f"stale budget {name}: {value:g} is far below "
                         f"max {ceil:g} ({where}) -- re-seed the budget "
                         "to lock in the improvement")
            stale += 1
        else:
            lines.append(f"ok {name}: {value:g} <= max {ceil:g} ({where})")
    if "min" in rule:
        floor = float(rule["min"])
        if value < floor:
            lines.append(f"REGRESSION {name}: {value:g} < min {floor:g} "
                         f"({where})")
            reg += 1
        elif value > floor * float(rule.get("stale_mult", STALE_MULT)):
            lines.append(f"stale budget {name}: {value:g} is far above "
                         f"min {floor:g} ({where}) -- re-seed the budget "
                         "to lock in the improvement")
            stale += 1
        else:
            lines.append(f"ok {name}: {value:g} >= min {floor:g} ({where})")
    return reg, stale


def check_figures(figures: Dict[str, float], rules: List[dict],
                  where: str = "journal"
                  ) -> Tuple[int, int, int, List[str]]:
    """Evaluate budget rules against an already-folded figure map.

    The live half of the gate: ``obs watch`` re-folds the journal every
    K rounds and calls this in memory, without re-reading budgets or
    touching disk.  Returns ``(regressions, stale, matched, lines)``.
    """
    lines: List[str] = []
    regressions = stale = matched = 0
    for rule in rules:
        value = figures.get(rule["metric"])
        if value is None:
            continue
        matched += 1
        r, s = _check_rule(rule, value, where, lines)
        regressions += r
        stale += s
    return regressions, stale, matched, lines


def check_slo(input_path: str, budgets_path: str) -> Tuple[int, List[str]]:
    """Check one input against the budget file.

    Returns ``(exit_code, report_lines)``; raises :class:`SLOError`
    (exit 2 territory) on malformed budgets or input.
    """
    rules = load_budgets(budgets_path)
    kind, payload = _load_input(input_path)
    lines: List[str] = []
    regressions = stale = matched = 0
    if kind == "bench":
        records: List[dict] = payload  # type: ignore[assignment]
        for rule in rules:
            select = rule.get("select") or {}
            prefix = select.get("metric_prefix", "")
            want_backend = select.get("backend")
            for rec in records:
                metric = str(rec.get("metric", ""))
                if prefix and not metric.startswith(prefix):
                    continue
                # records predating the backend field are CPU-era by
                # construction (every headline to date is CPU-tagged), so
                # a missing field matches "cpu" and checked-in BENCH_r*
                # artifacts keep passing re-tagged budgets
                if want_backend and str(
                        rec.get("backend", "cpu")) != want_backend:
                    continue
                value = _dotted(rec, rule["metric"])
                if not isinstance(value, (int, float)):
                    continue
                matched += 1
                r, s = _check_rule(rule, float(value), metric, lines)
                regressions += r
                stale += s
    else:
        figures = journal_figures(payload)  # type: ignore[arg-type]
        r, s, m, rule_lines = check_figures(figures, rules)
        regressions += r
        stale += s
        matched += m
        lines.extend(rule_lines)
    if not matched:
        lines.append(f"warning: no budget rule matched {input_path!r} "
                     f"({kind} input, {len(rules)} rules)")
    summary = (f"slo: {matched} checked, {regressions} regressions, "
               f"{stale} stale budgets")
    lines.append(summary)
    return (1 if regressions else 0), lines


def slo_main(args) -> int:
    """Entry point for the ``obs slo`` subcommand (argparse namespace
    with ``input`` and ``budgets``)."""
    budgets = args.budgets or default_budgets_path()
    try:
        code, lines = check_slo(args.input, budgets)
    except SLOError as exc:
        print(f"slo: {exc}")
        return 2
    for line in lines:
        print(line)
    return code
