"""``python -m fed_tgan_tpu.obs`` -- observability CLI.

Subcommands:

- ``report <journal.jsonl> [--format text|json]`` -- summarize a run
  journal (rounds, watchdog, robustness, transport, compiles,
  checkpoints, program costs, init phases, serving stages).
- ``slo <bench-or-journal> [--budgets FILE]`` -- SLO regression gate:
  check a bench record / journal against checked-in budgets.  Exit 1
  on a regression, 0 on pass (stale-budget improvements warn), 2 on
  malformed input/budgets.
- ``ledger [--json] [--family F]`` -- compile the hlolint-contracted
  programs and print their device cost ledger.  This subcommand (and
  only this one) imports jax.

Exit codes: 0 ok, 1 SLO regression, 2 usage / unreadable input.  The
module itself stays pure stdlib at import time -- ``report`` and
``slo`` never import jax; ``ledger`` imports it lazily inside the
handler.
"""

from __future__ import annotations

import argparse

from fed_tgan_tpu.obs.report import report_main
from fed_tgan_tpu.obs.slo import slo_main


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m fed_tgan_tpu.obs",
        description="run-journal tooling for fed_tgan_tpu telemetry",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="summarize a run journal (JSONL)")
    rep.add_argument("journal", help="path to the journal JSONL file")
    rep.add_argument("--format", choices=("text", "json"), default="text")
    slo = sub.add_parser(
        "slo", help="check a bench record or journal against SLO budgets")
    slo.add_argument("input", help="bench record JSON or journal JSONL")
    slo.add_argument("--budgets", default=None,
                     help="budget file (default: packaged obs/budgets.json)")
    led = sub.add_parser(
        "ledger", help="compile contracted programs, print the cost ledger")
    led.add_argument("--json", action="store_true",
                     help="emit the ledger as JSON")
    led.add_argument("--family", action="append", default=None,
                     help="restrict to one entrypoint family (repeatable)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        return report_main(args.journal, fmt=args.format)
    if args.cmd == "slo":
        return slo_main(args)
    if args.cmd == "ledger":
        # lazy: the ledger pass compiles programs, so only it pulls jax
        from fed_tgan_tpu.obs.ledger import ledger_main

        return ledger_main(["--json"] * bool(args.json)
                           + sum((["--family", f]
                                  for f in args.family or ()), []))
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
