"""``python -m fed_tgan_tpu.obs`` -- observability CLI.

Subcommands:

- ``report <journal.jsonl>... [--format text|json]`` -- summarize one
  or more run journals (rounds, clients, watchdog, robustness,
  transport, compiles, checkpoints, program costs, init phases,
  serving stages); several journals (a multihost run's per-rank
  streams) merge into one federation view keyed by round.
- ``slo <bench-or-journal> [--budgets FILE]`` -- SLO regression gate:
  check a bench record / journal against checked-in budgets.  Exit 1
  on a regression, 0 on pass (stale-budget improvements warn), 2 on
  malformed input/budgets.
- ``watch <journal|url>... [--follow]`` -- live terminal view over
  journal files or a training process's ``--obs-port`` exporter, with
  the SLO gate re-evaluated every K rounds as an in-run alarm.
- ``ledger [--json] [--family F]`` -- compile the hlolint-contracted
  programs and print their device cost ledger.  This subcommand (and
  only this one) imports jax.

Exit codes: 0 ok, 1 SLO regression/breach, 2 usage / unreadable input.
The module itself stays pure stdlib at import time -- ``report``,
``slo`` and ``watch`` never import jax; ``ledger`` imports it lazily
inside the handler.
"""

from __future__ import annotations

import argparse

from fed_tgan_tpu.obs.report import report_main
from fed_tgan_tpu.obs.slo import slo_main
from fed_tgan_tpu.obs.watch import watch_main


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m fed_tgan_tpu.obs",
        description="run-journal tooling for fed_tgan_tpu telemetry",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report", help="summarize run journal(s) (JSONL; multihost "
                       "per-rank journals merge into one view)")
    rep.add_argument("journal", nargs="+",
                     help="path(s) to journal JSONL file(s)")
    rep.add_argument("--format", choices=("text", "json"), default="text")
    slo = sub.add_parser(
        "slo", help="check a bench record or journal against SLO budgets")
    slo.add_argument("input", help="bench record JSON or journal JSONL")
    slo.add_argument("--budgets", default=None,
                     help="budget file (default: packaged obs/budgets.json)")
    wat = sub.add_parser(
        "watch", help="live view: tail journal file(s) or poll an "
                      "--obs-port exporter URL")
    wat.add_argument("source", nargs="+",
                     help="journal JSONL path(s) or http://host:port of a "
                          "training exporter")
    wat.add_argument("--follow", action="store_true",
                     help="keep tailing until interrupted (default: one "
                          "pass over what exists now)")
    wat.add_argument("--interval", type=float, default=1.0,
                     help="poll interval in seconds (default 1)")
    wat.add_argument("--slo-every", type=int, default=25,
                     help="re-evaluate SLO budgets every K observed "
                          "rounds (default 25)")
    wat.add_argument("--budgets", default=None,
                     help="budget file (default: packaged obs/budgets.json)")
    wat.add_argument("--max-seconds", type=float, default=None,
                     help="stop following after this many seconds "
                          "(testing/automation)")
    led = sub.add_parser(
        "ledger", help="compile contracted programs, print the cost ledger")
    led.add_argument("--json", action="store_true",
                     help="emit the ledger as JSON")
    led.add_argument("--family", action="append", default=None,
                     help="restrict to one entrypoint family (repeatable)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        return report_main(args.journal, fmt=args.format)
    if args.cmd == "slo":
        return slo_main(args)
    if args.cmd == "watch":
        return watch_main(args)
    if args.cmd == "ledger":
        # lazy: the ledger pass compiles programs, so only it pulls jax
        from fed_tgan_tpu.obs.ledger import ledger_main

        return ledger_main(["--json"] * bool(args.json)
                           + sum((["--family", f]
                                  for f in args.family or ()), []))
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
