"""``python -m fed_tgan_tpu.obs`` -- observability CLI.

Subcommands:

- ``report <journal.jsonl> [--format text|json]`` -- summarize a run
  journal (rounds, watchdog, robustness, transport, compiles,
  checkpoints).

Exit codes: 0 ok, 2 usage / unreadable journal.  Pure stdlib -- never
imports jax.
"""

from __future__ import annotations

import argparse

from fed_tgan_tpu.obs.report import report_main


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m fed_tgan_tpu.obs",
        description="run-journal tooling for fed_tgan_tpu telemetry",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="summarize a run journal (JSONL)")
    rep.add_argument("journal", help="path to the journal JSONL file")
    rep.add_argument("--format", choices=("text", "json"), default="text")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "report":
        return report_main(args.journal, fmt=args.format)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
