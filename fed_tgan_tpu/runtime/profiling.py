"""Device-timeline tracing.

The reference's only profiling is host wall-clock lists it writes to CSVs
(reference Server/dtds/distributed.py:790-824); on a TPU the interesting
question — how much of a round is MXU compute vs HBM traffic vs the D2H
snapshot transfer — needs the XLA device timeline.  ``device_trace`` wraps
``jax.profiler`` (TensorBoard profile plugin / Perfetto output) as a
best-effort context manager: a backend that cannot trace (some tunneled
transports) degrades to a warning, never a failed run.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def device_trace(profile_dir: str):
    import jax

    started = False
    try:
        jax.profiler.start_trace(profile_dir)
        started = True
    except Exception as exc:  # pragma: no cover - backend-dependent
        print(f"WARNING: profiler trace unavailable ({exc}); "
              "running untraced")
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                print(f"profiler trace written to {profile_dir} "
                      "(open with TensorBoard -> Profile, or Perfetto)")
            except Exception as exc:  # pragma: no cover - backend-dependent
                # never mask the traced body's exception with a profiler
                # teardown failure (best-effort contract)
                print(f"WARNING: profiler stop_trace failed ({exc}); "
                      "trace may be incomplete")
