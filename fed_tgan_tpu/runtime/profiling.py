"""Device-timeline tracing.

The reference's only profiling is host wall-clock lists it writes to CSVs
(reference Server/dtds/distributed.py:790-824); on a TPU the interesting
question — how much of a round is MXU compute vs HBM traffic vs the D2H
snapshot transfer — needs the XLA device timeline.  ``device_trace`` wraps
``jax.profiler`` (TensorBoard profile plugin / Perfetto output) as a
best-effort context manager: a backend that cannot trace (some tunneled
transports) degrades to a warning, never a failed run.

Outcomes go through the logger and the run journal (``device_trace``
events) instead of bare prints, and the context yields the trace dir
(or ``None`` when tracing could not start) so callers can record where
the device timeline landed next to their own host spans.
"""

from __future__ import annotations

import contextlib
import logging

from fed_tgan_tpu.obs.journal import emit as _emit_event

log = logging.getLogger("fed_tgan_tpu.profiling")


@contextlib.contextmanager
def device_trace(profile_dir: str):
    import jax

    started = False
    try:
        jax.profiler.start_trace(profile_dir)
        started = True
    except Exception as exc:  # pragma: no cover - backend-dependent
        log.warning("profiler trace unavailable (%s); running untraced", exc)
        _emit_event("device_trace", dir=str(profile_dir), ok=False,
                    error=str(exc))
    try:
        yield profile_dir if started else None
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                log.info("profiler trace written to %s (open with "
                         "TensorBoard -> Profile, or Perfetto)", profile_dir)
                _emit_event("device_trace", dir=str(profile_dir), ok=True)
            except Exception as exc:  # pragma: no cover - backend-dependent
                # never mask the traced body's exception with a profiler
                # teardown failure (best-effort contract)
                log.warning("profiler stop_trace failed (%s); trace may be "
                            "incomplete", exc)
                _emit_event("device_trace", dir=str(profile_dir), ok=False,
                            error=str(exc))
