from fed_tgan_tpu.runtime.transport import ClientTransport, ServerTransport

__all__ = ["ClientTransport", "ServerTransport"]
