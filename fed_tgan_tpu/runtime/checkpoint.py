"""Checkpoint / resume.

The reference's only persistence is ``MDGANServer.save_model`` — a
``torch.save`` of ``[generator, cond_generator, transformer, batch_size,
embedding_dim]`` that is never called from the training loop, and there is
no resume path at all (reference Server/dtds/distributed.py:560-563; SURVEY
§5.4).  Here both halves exist:

- ``save_synthesizer`` / ``load_synthesizer`` — the reference-parity
  sampling artifact: generator params + conditional sampler + transformer +
  config, enough to ``sample()`` without the training data.
- ``save_federated`` / ``load_federated`` — full training-state checkpoints
  for the SPMD trainer: every client's model/optimizer pytree, the RNG key
  schedule, the round counter, and the federated-init artifacts (global
  meta, encoders, GMMs, aggregation weights), so a restored run continues
  bit-for-bit where it stopped.

Format: a directory holding ``host.pkl`` (plain-Python/numpy objects) and
``arrays.npz`` (every pytree leaf, keyed by flatten order).  Leaves are
restored into a freshly-constructed trainer whose pytree *structure* is
rebuilt from the checkpointed config, so no treedef serialization is needed.
"""

from __future__ import annotations

import os
import pickle

import jax
import numpy as np

FORMAT_VERSION = 2  # v2: optional EMA leaves in federated checkpoints

# Federated checkpoints WITHOUT an EMA chain keep writing v1 so older
# builds still load them; EMA checkpoints write v2, which older builds
# refuse cleanly (their loader guards version > 1) instead of silently
# dropping the EMA leaves and resuming a different run.
_V1 = 1

_HOST = "host.pkl"
_ARRAYS = "arrays.npz"


def _save_leaves(tree, extra: dict, path: str) -> None:
    leaves = jax.tree.leaves(tree)
    arrays = {f"leaf_{i:05d}": np.asarray(l) for i, l in enumerate(leaves)}
    arrays.update({k: np.asarray(v) for k, v in extra.items()})
    np.savez(os.path.join(path, _ARRAYS), **arrays)


def _load_leaves(template, data) -> tuple:
    n = len(jax.tree.leaves(template))
    leaves = [data[f"leaf_{i:05d}"] for i in range(n)]
    return jax.tree.unflatten(jax.tree.structure(template), leaves)


# --------------------------------------------------------------- federated


def save_federated(trainer, path: str, run_name: str | None = None) -> None:
    """Write a full-resume checkpoint of a trainer to ``path``.

    Accepts a ``FederatedTrainer`` (kind "federated") or an ``MDGANTrainer``
    (kind "mdgan" — the replicated generator bundle plus the per-client
    discriminator stack).  ``run_name`` (the dataset/output identity, e.g.
    "Intrusion") rides along so a resumed run keeps writing to the same
    output layout without the original CLI flags."""
    os.makedirs(path, exist_ok=True)
    is_mdgan = hasattr(trainer, "gen")
    if not is_mdgan and not hasattr(trainer, "models"):
        raise TypeError(
            f"save_federated expects a FederatedTrainer or MDGANTrainer, "
            f"got {type(trainer).__name__}"
        )
    has_ema = not is_mdgan and getattr(trainer, "ema", None) is not None
    host = {
        "version": FORMAT_VERSION if has_ema else _V1,
        "ema": has_ema,
        "ema_updates": getattr(trainer, "_ema_updates", 0),
        "kind": "mdgan" if is_mdgan else "federated",
        "init": trainer.init,
        "cfg": trainer.cfg,
        "seed": trainer.seed,
        "completed_epochs": trainer.completed_epochs,
        "epoch_times": list(trainer.epoch_times),
        # a mid-hook save sees the in-flight round's train phase recorded but
        # not its total; keep only fully-completed rounds so resume stays
        # consistent with epoch_times
        "phase_times": {
            k: list(v)[: len(trainer.epoch_times)]
            for k, v in getattr(trainer, "phase_times", {}).items()
        },
        "run_name": run_name,
    }
    with open(os.path.join(path, _HOST), "wb") as f:
        pickle.dump(host, f)
    if is_mdgan:
        state = (trainer.gen, trainer.disc)
    elif has_ema:
        # EMA runs (cfg.ema_decay > 0) persist the smoothed generator too —
        # resume must continue the same EMA chain bit-exactly
        state = (trainer.models, trainer.ema)
    else:
        state = trainer.models
    _save_leaves(
        state,
        {"rng_key": jax.random.key_data(trainer._key)},
        path,
    )


def load_federated(path: str, mesh=None):
    """Reconstruct a ``FederatedTrainer`` from ``save_federated`` output.

    The trainer is rebuilt from the checkpointed ``FederatedInit`` (so all
    sampler tables, shardings and compiled programs are regenerated), then
    its evolving state — models, optimizer moments, RNG key, round counter —
    is overwritten from the checkpoint.
    """
    from fed_tgan_tpu.train.federated import FederatedTrainer
    from fed_tgan_tpu.train.mdgan import MDGANTrainer

    with open(os.path.join(path, _HOST), "rb") as f:
        host = pickle.load(f)
    kind = host.get("kind")
    if kind not in ("federated", "mdgan"):
        raise ValueError(f"{path} is not a federated checkpoint")
    if host["version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint version {host['version']} is newer than supported "
            f"{FORMAT_VERSION}"
        )

    cls = MDGANTrainer if kind == "mdgan" else FederatedTrainer
    trainer = cls(host["init"], config=host["cfg"], mesh=mesh, seed=host["seed"])
    with np.load(os.path.join(path, _ARRAYS)) as data:
        if kind == "mdgan":
            trainer.gen, trainer.disc = _load_leaves(
                (trainer.gen, trainer.disc), data
            )
        elif getattr(trainer, "ema", None) is not None:
            # cfg.ema_decay > 0 (cfg rides in the checkpoint), so the
            # rebuilt trainer has an EMA template matching the saved layout
            if not host.get("ema"):
                raise ValueError(
                    f"{path}: cfg.ema_decay > 0 but the checkpoint carries "
                    "no EMA leaves (saved by a pre-EMA build?)"
                )
            trainer.models, trainer.ema = _load_leaves(
                (trainer.models, trainer.ema), data
            )
            trainer._ema_updates = int(host.get("ema_updates", 0))
        else:
            trainer.models = _load_leaves(trainer.models, data)
        trainer._key = jax.random.wrap_key_data(data["rng_key"])
        if kind != "mdgan":
            # keep the key committed to the mesh like __init__ does, so the
            # resumed run's epoch programs compile once (uncommitted-then-
            # committed key shardings would compile each chunk size twice)
            from jax.sharding import NamedSharding, PartitionSpec as P

            trainer._key = jax.device_put(
                trainer._key, NamedSharding(trainer.mesh, P())
            )
    trainer.completed_epochs = host["completed_epochs"]
    trainer.epoch_times = list(host["epoch_times"])
    if hasattr(trainer, "phase_times"):
        for k, v in host.get("phase_times", {}).items():
            trainer.phase_times[k] = list(v)
    trainer.run_name = host.get("run_name")
    return trainer


# ------------------------------------------------------------- synthesizer


class SavedSynthesizer:
    """A sampling-only artifact (the reference ``save_model`` payload)."""

    def __init__(self, params_g, state_g, cond, transformer, cfg, spec,
                 key_offset: int = 17):
        from fed_tgan_tpu.train.steps import SampleProgramCache

        self.params_g = params_g
        self.state_g = state_g
        self.cond = cond
        self.transformer = transformer
        self.cfg = cfg
        self.spec = spec
        # the source object's sampling-key offset, so a loaded artifact
        # reproduces the exact draws its source would have made
        self.key_offset = key_offset
        self._cache = SampleProgramCache(spec, cfg)

    def sample_encoded(self, n: int, seed: int = 0) -> np.ndarray:
        return self._cache.sample(
            self.params_g, self.state_g, self.cond, n,
            jax.random.key(seed + self.key_offset),
        )

    def sample(self, n: int, seed: int = 0) -> np.ndarray:
        return self.transformer.inverse_transform(self.sample_encoded(n, seed))


def save_synthesizer(synth, path: str) -> None:
    """Persist the sampling artifact of a trained synthesizer/trainer.

    Accepts a ``StandaloneSynthesizer`` or a ``FederatedTrainer`` (which
    contributes its post-aggregation global generator and the pooled
    conditional sampler, like the reference server's snapshot model).
    """
    os.makedirs(path, exist_ok=True)
    if hasattr(synth, "_global_model"):  # FederatedTrainer
        params_g, state_g = synth._global_model()
        cond = synth.server_cond
        transformer = synth.init.transformers[0]
        key_offset = 29  # FederatedTrainer.sample_encoded's offset
    else:
        params_g, state_g = synth.models.params_g, synth.models.state_g
        cond = synth.cond
        transformer = synth.transformer
        key_offset = 17  # StandaloneSynthesizer.sample_encoded's offset
    host = {
        # layout unchanged since v1 (EMA runs bake the debiased generator
        # into params_g, no extra leaves) — stay loadable on older builds
        "version": _V1,
        "kind": "synthesizer",
        "cfg": synth.cfg,
        "transformer": transformer,
        "output_info": transformer.output_info,
        "key_offset": key_offset,
    }
    with open(os.path.join(path, _HOST), "wb") as f:
        pickle.dump(host, f)
    _save_leaves((params_g, state_g, cond), {}, path)


def load_synthesizer(path: str) -> SavedSynthesizer:
    from fed_tgan_tpu.ops.segments import SegmentSpec
    from fed_tgan_tpu.train.sampler import CondSampler
    from fed_tgan_tpu.train.steps import TrainConfig, init_models

    with open(os.path.join(path, _HOST), "rb") as f:
        host = pickle.load(f)
    if host.get("kind") != "synthesizer":
        raise ValueError(f"{path} is not a synthesizer checkpoint")

    cfg: TrainConfig = host["cfg"]
    spec = SegmentSpec.from_output_info(host["output_info"])
    # rebuild the pytree structure, then fill it with checkpointed leaves
    template_models = init_models(jax.random.key(0), spec, cfg)
    zeros = np.zeros((max(spec.n_discrete, 1), max(int(spec.cond_sizes.max()) if spec.n_discrete else 1, 1)))
    template_cond = CondSampler(p_train=zeros, p_empirical=zeros, spec=spec)
    template = (template_models.params_g, template_models.state_g, template_cond)
    with np.load(os.path.join(path, _ARRAYS)) as data:
        params_g, state_g, cond = _load_leaves(template, data)
    return SavedSynthesizer(
        params_g, state_g, cond, host["transformer"], cfg, spec,
        key_offset=host.get("key_offset", 17),
    )
