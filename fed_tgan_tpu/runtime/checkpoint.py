"""Checkpoint / resume.

The reference's only persistence is ``MDGANServer.save_model`` — a
``torch.save`` of ``[generator, cond_generator, transformer, batch_size,
embedding_dim]`` that is never called from the training loop, and there is
no resume path at all (reference Server/dtds/distributed.py:560-563; SURVEY
§5.4).  Here both halves exist:

- ``save_synthesizer`` / ``load_synthesizer`` — the reference-parity
  sampling artifact: generator params + conditional sampler + transformer +
  config, enough to ``sample()`` without the training data.
- ``save_federated`` / ``load_federated`` — full training-state checkpoints
  for the SPMD trainer: every client's model/optimizer pytree, the RNG key
  schedule, the round counter, and the federated-init artifacts (global
  meta, encoders, GMMs, aggregation weights), so a restored run continues
  bit-for-bit where it stopped.

Format: a directory holding ``host.pkl`` (plain-Python/numpy objects) and
``arrays.npz`` (every pytree leaf, keyed by flatten order).  Leaves are
restored into a freshly-constructed trainer whose pytree *structure* is
rebuilt from the checkpointed config, so no treedef serialization is needed.

Crash safety: saves are staged in a sibling temp directory (every file
fsynced, then a ``COMPLETE`` marker, then the directory itself) and
published with atomic renames, rotating the previous checkpoint to
``<path>.1`` … ``<path>.K-1`` (``keep`` last-K).  A crash at ANY point
leaves the newest previously-published checkpoint loadable;
:func:`find_resumable` picks it up for auto-resume.
"""

from __future__ import annotations

import logging
import os
import pickle
import shutil

import jax
import numpy as np

from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.obs.registry import counter as _metric_counter

_CKPT_SAVES = _metric_counter(
    "fed_tgan_checkpoints_saved_total", "crash-safe checkpoints published")
_CKPT_RESTORES = _metric_counter(
    "fed_tgan_checkpoints_restored_total", "checkpoints loaded for resume")

log = logging.getLogger("fed_tgan_tpu.checkpoint")

FORMAT_VERSION = 2  # v2: optional EMA leaves in federated checkpoints

# Federated checkpoints WITHOUT an EMA chain keep writing v1 so older
# builds still load them; EMA checkpoints write v2, which older builds
# refuse cleanly (their loader guards version > 1) instead of silently
# dropping the EMA leaves and resuming a different run.
_V1 = 1

_HOST = "host.pkl"
_ARRAYS = "arrays.npz"
_MARKER = "COMPLETE"


def _fsync_file(f) -> None:
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _stage_dir(path: str) -> str:
    """Fresh sibling temp directory the save is staged into."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    base = os.path.basename(path)
    # sweep stale stages from earlier crashed writers (single-writer layout)
    for entry in os.listdir(parent):
        if entry.startswith(f"{base}.tmp-"):
            shutil.rmtree(os.path.join(parent, entry), ignore_errors=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    os.makedirs(tmp)
    return tmp


def _seal_dir(tmp: str) -> None:
    """Marker + dir fsync: after this, ``tmp`` is a valid checkpoint."""
    with open(os.path.join(tmp, _MARKER), "wb") as f:
        _fsync_file(f)
    _fsync_dir(tmp)


def _publish_dir(tmp: str, path: str, keep: int) -> None:
    """Atomically publish sealed ``tmp`` as ``path``, rotating the previous
    checkpoint into ``path.1`` … ``path.{keep-1}`` (oldest falls off)."""
    keep = max(1, int(keep))
    doomed = f"{path}.{keep}"
    if os.path.isdir(doomed):
        shutil.rmtree(doomed)
    for i in range(keep - 1, 0, -1):
        src = f"{path}.{i}"
        if os.path.isdir(src):
            os.replace(src, f"{path}.{i + 1}")
    if os.path.isdir(path):
        os.replace(path, f"{path}.1")
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))
    if keep == 1:
        # the rotation slot was only a publish staging step
        transient = f"{path}.1"
        if os.path.isdir(transient):
            shutil.rmtree(transient)


def _is_valid_checkpoint(path: str) -> bool:
    """Both payload files present and readable.  The ``COMPLETE`` marker is
    checked when present-able but not required: checkpoints written before
    the atomic layout lack it yet are fully usable."""
    try:
        with open(os.path.join(path, _HOST), "rb") as f:
            pickle.load(f)
        with np.load(os.path.join(path, _ARRAYS)) as data:
            _ = data.files
        return True
    except Exception:
        return False


def list_resumable(path: str, max_rotations: int = 8) -> list[str]:
    """Every valid checkpoint generation at ``path``, newest first
    (primary, then rotation slots ``path.1`` …).  The watchdog walks this
    list when the newest generation turns out to hold already-poisoned
    state (a corruption at round E only surfaces in round E+1's losses,
    after E's checkpoint was published)."""
    candidates = [path] + [f"{path}.{i}" for i in range(1, max_rotations + 1)]
    return [c for c in candidates
            if os.path.isdir(c) and _is_valid_checkpoint(c)]


def find_resumable(path: str, max_rotations: int = 8) -> str | None:
    """Newest valid checkpoint at ``path`` (or its rotation slots
    ``path.1`` … — a crash can leave the primary slot empty or torn while
    an older rotation is intact).  None when nothing loadable exists."""
    gens = list_resumable(path, max_rotations)
    if gens and gens[0] != path:
        log.warning(
            "checkpoint: primary %s unusable, resuming from %s",
            path, gens[0],
        )
    return gens[0] if gens else None


def checkpoint_fingerprint(path: str) -> str:
    """Content hash of a checkpoint directory (12 hex chars).

    Streams ``host.pkl`` + ``arrays.npz`` through sha256, so the id is a
    pure function of the artifact bytes: the serving registry uses it as
    the model id, and hot-reload fires exactly when a new generation's
    bytes differ (a rewrite of identical content keeps the same id)."""
    import hashlib

    h = hashlib.sha256()
    for fname in (_HOST, _ARRAYS):
        with open(os.path.join(path, fname), "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()[:12]


def _fault_hook(path: str) -> None:
    """Mid-write fault-injection point (no-op unless a plan is active)."""
    try:
        from fed_tgan_tpu.testing.faults import active_plan
    except Exception:
        return
    plan = active_plan()
    if plan is not None:
        plan.on_checkpoint_write(path)


def _snapshot_fault_hook(path: str) -> None:
    """Post-publish fault-injection point for sampling checkpoints
    (``degrade_snapshot``): the save has already succeeded atomically,
    the fault mutates the published payload in place (no-op unless a
    plan with a degrade fault is active)."""
    try:
        from fed_tgan_tpu.testing.faults import active_plan
    except Exception:
        return
    plan = active_plan()
    if plan is not None:
        plan.on_snapshot_publish(path)


def _save_leaves(tree, extra: dict, path: str) -> None:
    leaves = jax.tree.leaves(tree)
    arrays = {f"leaf_{i:05d}": np.asarray(l) for i, l in enumerate(leaves)}
    arrays.update({k: np.asarray(v) for k, v in extra.items()})
    with open(os.path.join(path, _ARRAYS), "wb") as f:
        np.savez(f, **arrays)
        _fsync_file(f)


def _load_leaves(template, data) -> tuple:
    n = len(jax.tree.leaves(template))
    leaves = [data[f"leaf_{i:05d}"] for i in range(n)]
    return jax.tree.unflatten(jax.tree.structure(template), leaves)


# --------------------------------------------------------------- federated


def save_federated(trainer, path: str, run_name: str | None = None,
                   keep: int = 1) -> None:
    """Write a full-resume checkpoint of a trainer to ``path``.

    Accepts a ``FederatedTrainer`` (kind "federated") or an ``MDGANTrainer``
    (kind "mdgan" — the replicated generator bundle plus the per-client
    discriminator stack).  ``run_name`` (the dataset/output identity, e.g.
    "Intrusion") rides along so a resumed run keeps writing to the same
    output layout without the original CLI flags.

    The write is crash-safe: staged in a temp dir, fsynced, and published
    by atomic rename; ``keep`` > 1 retains the previous K-1 checkpoints as
    ``path.1`` … for :func:`find_resumable`."""
    is_mdgan = hasattr(trainer, "gen")
    if not is_mdgan and not hasattr(trainer, "models"):
        raise TypeError(
            f"save_federated expects a FederatedTrainer or MDGANTrainer, "
            f"got {type(trainer).__name__}"
        )
    has_ema = not is_mdgan and getattr(trainer, "ema", None) is not None
    host = {
        "version": FORMAT_VERSION if has_ema else _V1,
        "ema": has_ema,
        "ema_updates": getattr(trainer, "_ema_updates", 0),
        "kind": "mdgan" if is_mdgan else "federated",
        # elastic slot count (0 = legacy exact-population trainer): resume
        # must rebuild the same padded stacks or the saved model leaves
        # (leading capacity axis) would not fit the template
        "capacity": (getattr(trainer, "capacity", 0)
                     if getattr(trainer, "elastic", False) else 0),
        # live-population state (churn): departures, the survivor-
        # renormalized weights and the quarantine strike ledger must
        # survive a rollback — a restored run must NOT resurrect departed
        # clients or forget a repeat offender's record
        "dropped_clients": sorted(
            int(i) for i in (getattr(trainer, "dropped_clients", None) or ())
        ),
        "weights": (None if is_mdgan
                    else np.asarray(trainer.weights).copy()),
        "strikes": (None if is_mdgan
                    else np.asarray(trainer._strikes).copy()),
        "init": trainer.init,
        "cfg": trainer.cfg,
        "seed": trainer.seed,
        "completed_epochs": trainer.completed_epochs,
        "epoch_times": list(trainer.epoch_times),
        # a mid-hook save sees the in-flight round's train phase recorded but
        # not its total; keep only fully-completed rounds so resume stays
        # consistent with epoch_times
        "phase_times": {
            k: list(v)[: len(trainer.epoch_times)]
            for k, v in getattr(trainer, "phase_times", {}).items()
        },
        "run_name": run_name,
    }
    tmp = _stage_dir(path)
    try:
        with open(os.path.join(tmp, _HOST), "wb") as f:
            pickle.dump(host, f)
            _fsync_file(f)
        _fault_hook(path)  # simulated crash: tmp is partial, path untouched
        if is_mdgan:
            state = (trainer.gen, trainer.disc)
        elif has_ema:
            # EMA runs (cfg.ema_decay > 0) persist the smoothed generator
            # too — resume must continue the same EMA chain bit-exactly
            state = (trainer.models, trainer.ema)
        else:
            state = trainer.models
        _save_leaves(
            state,
            {"rng_key": jax.random.key_data(trainer._key)},
            tmp,
        )
        _seal_dir(tmp)
    except BaseException as exc:
        # an injected fault SIMULATES a hard crash: leave the partial stage
        # on disk exactly as kill -9 would, so tests prove resume ignores it
        if type(exc).__name__ != "FaultInjected":
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    _publish_dir(tmp, path, keep)
    _CKPT_SAVES.inc()
    _emit_event("checkpoint", path=str(path), kind=host["kind"],
                round=int(host["completed_epochs"]), keep=int(keep))


def load_federated(path: str, mesh=None):
    """Reconstruct a ``FederatedTrainer`` from ``save_federated`` output.

    The trainer is rebuilt from the checkpointed ``FederatedInit`` (so all
    sampler tables, shardings and compiled programs are regenerated), then
    its evolving state — models, optimizer moments, RNG key, round counter —
    is overwritten from the checkpoint.
    """
    from fed_tgan_tpu.train.federated import FederatedTrainer
    from fed_tgan_tpu.train.mdgan import MDGANTrainer

    with open(os.path.join(path, _HOST), "rb") as f:
        host = pickle.load(f)
    kind = host.get("kind")
    if kind not in ("federated", "mdgan"):
        raise ValueError(f"{path} is not a federated checkpoint")
    if host["version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint version {host['version']} is newer than supported "
            f"{FORMAT_VERSION}"
        )

    cls = MDGANTrainer if kind == "mdgan" else FederatedTrainer
    kwargs = {}
    if kind == "federated" and host.get("capacity", 0):
        kwargs["capacity"] = int(host["capacity"])
    trainer = cls(host["init"], config=host["cfg"], mesh=mesh,
                  seed=host["seed"], **kwargs)
    with np.load(os.path.join(path, _ARRAYS)) as data:
        if kind == "mdgan":
            trainer.gen, trainer.disc = _load_leaves(
                (trainer.gen, trainer.disc), data
            )
        elif getattr(trainer, "ema", None) is not None:
            # cfg.ema_decay > 0 (cfg rides in the checkpoint), so the
            # rebuilt trainer has an EMA template matching the saved layout
            if not host.get("ema"):
                raise ValueError(
                    f"{path}: cfg.ema_decay > 0 but the checkpoint carries "
                    "no EMA leaves (saved by a pre-EMA build?)"
                )
            trainer.models, trainer.ema = _load_leaves(
                (trainer.models, trainer.ema), data
            )
            trainer._ema_updates = int(host.get("ema_updates", 0))
        else:
            trainer.models = _load_leaves(trainer.models, data)
        trainer._key = jax.random.wrap_key_data(data["rng_key"])
        if kind != "mdgan":
            # keep the key committed to the mesh like __init__ does, so the
            # resumed run's epoch programs compile once (uncommitted-then-
            # committed key shardings would compile each chunk size twice)
            from jax.sharding import NamedSharding, PartitionSpec as P

            trainer._key = jax.device_put(
                trainer._key, NamedSharding(trainer.mesh, P())
            )
    if kind == "federated":
        # replay the live-population state: departed clients stay departed
        # (zero steps, zero weight), survivors keep their renormalized —
        # and possibly drift-recomputed — weights, repeat offenders keep
        # their strikes.  Host arrays only: the device stacks upload from
        # them on the first fit(), so no extra transfers and no recompile.
        dropped = host.get("dropped_clients") or []
        if dropped:
            trainer.dropped_clients = {int(i) for i in dropped}
            alive = np.ones(len(trainer.steps), dtype=bool)
            alive[sorted(trainer.dropped_clients)] = False
            trainer.steps = np.where(alive, trainer.steps, 0)
        w = host.get("weights")
        if w is not None and np.shape(w) == np.shape(trainer.weights):
            trainer.weights = np.asarray(w, dtype=np.float32).copy()
        s = host.get("strikes")
        if s is not None and np.shape(s) == np.shape(trainer._strikes):
            trainer._strikes = np.asarray(s, dtype=np.int64).copy()
    trainer.completed_epochs = host["completed_epochs"]
    trainer.epoch_times = list(host["epoch_times"])
    if hasattr(trainer, "phase_times"):
        for k, v in host.get("phase_times", {}).items():
            trainer.phase_times[k] = list(v)
    trainer.run_name = host.get("run_name")
    _CKPT_RESTORES.inc()
    _emit_event("checkpoint_restore", path=str(path), kind=kind,
                round=int(trainer.completed_epochs))
    return trainer


# ------------------------------------------------------------- synthesizer


class SavedSynthesizer:
    """A sampling-only artifact (the reference ``save_model`` payload)."""

    def __init__(self, params_g, state_g, cond, transformer, cfg, spec,
                 key_offset: int = 17):
        from fed_tgan_tpu.train.steps import SampleProgramCache

        self.params_g = params_g
        self.state_g = state_g
        self.cond = cond
        self.transformer = transformer
        self.cfg = cfg
        self.spec = spec
        # the source object's sampling-key offset, so a loaded artifact
        # reproduces the exact draws its source would have made
        self.key_offset = key_offset
        self._cache = SampleProgramCache(spec, cfg)

    def sample_encoded(self, n: int, seed: int = 0) -> np.ndarray:
        return self._cache.sample(
            self.params_g, self.state_g, self.cond, n,
            jax.random.key(seed + self.key_offset),
        )

    def sample(self, n: int, seed: int = 0) -> np.ndarray:
        return self.transformer.inverse_transform(self.sample_encoded(n, seed))


def save_synthesizer(synth, path: str) -> None:
    """Persist the sampling artifact of a trained synthesizer/trainer.

    Accepts a ``StandaloneSynthesizer``, a ``FederatedTrainer`` (which
    contributes its post-aggregation global generator and the pooled
    conditional sampler, like the reference server's snapshot model), or
    a ``SavedSynthesizer`` being republished (the canary helpers reload
    an artifact, bump its ``key_offset``, and save it back as a new
    generation).  Crash-safe like ``save_federated``: staged, fsynced,
    atomic rename.
    """
    if hasattr(synth, "_global_model"):  # FederatedTrainer
        params_g, state_g = synth._global_model()
        cond = synth.server_cond
        transformer = synth.init.transformers[0]
        key_offset = 29  # FederatedTrainer.sample_encoded's offset
    elif hasattr(synth, "params_g"):  # SavedSynthesizer republish
        params_g, state_g = synth.params_g, synth.state_g
        cond = synth.cond
        transformer = synth.transformer
        key_offset = synth.key_offset
    else:
        params_g, state_g = synth.models.params_g, synth.models.state_g
        cond = synth.cond
        transformer = synth.transformer
        key_offset = 17  # StandaloneSynthesizer.sample_encoded's offset
    host = {
        # layout unchanged since v1 (EMA runs bake the debiased generator
        # into params_g, no extra leaves) — stay loadable on older builds
        "version": _V1,
        "kind": "synthesizer",
        "cfg": synth.cfg,
        "transformer": transformer,
        "output_info": transformer.output_info,
        "key_offset": key_offset,
    }
    tmp = _stage_dir(path)
    try:
        with open(os.path.join(tmp, _HOST), "wb") as f:
            pickle.dump(host, f)
            _fsync_file(f)
        _save_leaves((params_g, state_g, cond), {}, tmp)
        _seal_dir(tmp)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _publish_dir(tmp, path, keep=1)
    _snapshot_fault_hook(path)


def load_synthesizer(path: str) -> SavedSynthesizer:
    from fed_tgan_tpu.ops.segments import SegmentSpec
    from fed_tgan_tpu.train.sampler import CondSampler
    from fed_tgan_tpu.train.steps import TrainConfig, init_models

    with open(os.path.join(path, _HOST), "rb") as f:
        host = pickle.load(f)
    if host.get("kind") != "synthesizer":
        raise ValueError(f"{path} is not a synthesizer checkpoint")

    cfg: TrainConfig = host["cfg"]
    spec = SegmentSpec.from_output_info(host["output_info"])
    # rebuild the pytree structure, then fill it with checkpointed leaves
    template_models = init_models(jax.random.key(0), spec, cfg)
    zeros = np.zeros((max(spec.n_discrete, 1), max(int(spec.cond_sizes.max()) if spec.n_discrete else 1, 1)))
    template_cond = CondSampler(p_train=zeros, p_empirical=zeros, spec=spec)
    template = (template_models.params_g, template_models.state_g, template_cond)
    with np.load(os.path.join(path, _ARRAYS)) as data:
        params_g, state_g, cond = _load_leaves(template, data)
    return SavedSynthesizer(
        params_g, state_g, cond, host["transformer"], cfg, spec,
        key_offset=host.get("key_offset", 17),
    )
