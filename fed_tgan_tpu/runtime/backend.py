"""Portable backend runtime: one seam between the program and the platform.

Everything the rest of the codebase needs to know about *where* it runs
lives here: device discovery, platform selection, virtual-CPU
provisioning, PJRT plugin registration, mesh construction, the
``hierarchical_psum`` host-group topology, and the probe/watchdog
machinery that keeps a wedged accelerator tunnel from hanging a run.
``parallel/mesh.py`` re-exports the historical entry points as thin
shims, so existing imports (and test monkeypatch seams) keep working.

Backends are named by a ``--backend`` spec:

- ``cpu``  — the virtual-device host platform (tests/CI recipe; the
  default everywhere, byte-identical to the pre-seam lowered programs)
- ``tpu`` / ``gpu`` — native PJRT discovery, probed through a
  subprocess before first use so a hung tunnel is diagnosed, not hung on
- ``plugin:<name>`` — an out-of-tree PJRT plugin registered via
  ``xla_bridge.register_plugin`` + ``jax_platforms`` (SNIPPETS.md [3]);
  the shared library path comes from ``FED_TGAN_PJRT_<NAME>_PATH`` and a
  missing plugin fails fast with :class:`PluginRegistrationError`
  instead of a deep jax traceback.

This module is importable before jax (jax is imported lazily inside
functions): the pod launcher's ``--dry-run`` parent and the obs tooling
stay jax-free.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path

from fed_tgan_tpu.obs.journal import emit as _emit_event

CLIENTS_AXIS = "clients"

#: closed set of first-class platform names; anything else must be a
#: ``plugin:<name>`` spec
KNOWN_PLATFORMS = ("cpu", "tpu", "gpu")

_PLUGIN_PREFIX = "plugin:"


class PluginRegistrationError(RuntimeError):
    """A ``plugin:<name>`` backend could not be registered (missing or
    unreadable PJRT shared library, bad plugin name).  Named so callers —
    and the doctor's ``backend-seam`` check — can fail fast with the
    plugin's identity instead of surfacing a deep jax traceback."""


def parse_backend(spec):
    """Validate a ``--backend`` spec; returns the canonical name.

    Accepts ``cpu``/``tpu``/``gpu``/``plugin:<name>`` (and ``None``,
    passed through: auto mode — probe the accelerator, fall back to CPU).
    Raises ``ValueError`` with the accepted grammar otherwise, so argparse
    ``type=`` callers surface a one-line usage error.
    """
    if spec is None:
        return None
    name = str(spec).strip()
    low = name.lower()
    if low in KNOWN_PLATFORMS:
        return low
    if low.startswith(_PLUGIN_PREFIX):
        plugin = name[len(_PLUGIN_PREFIX):].strip()
        if plugin and all(c.isalnum() or c in "_-" for c in plugin):
            return _PLUGIN_PREFIX + plugin
        raise ValueError(
            f"bad plugin backend {spec!r}: expected plugin:<name> with an "
            "alphanumeric/_/- name")
    raise ValueError(
        f"unknown backend {spec!r}: expected one of cpu, tpu, gpu, or "
        "plugin:<name>")


def plugin_env_var(plugin: str) -> str:
    """Env var naming the PJRT shared library for ``plugin:<plugin>``."""
    return "FED_TGAN_PJRT_%s_PATH" % plugin.upper().replace("-", "_")


def register_pjrt_plugin(plugin: str, library_path: str | None = None) -> None:
    """Register an out-of-tree PJRT plugin and put it on the platform list.

    The SNIPPETS.md [3] pattern: ``xla_bridge.register_plugin(name,
    library_path=...)`` then ``jax_platforms = "cpu,<name>"`` so the host
    platform stays available for staging buffers.  Must run before any
    backend initializes.  A missing/unset library raises
    :class:`PluginRegistrationError` naming the plugin and the env var —
    fail fast, before jax is even imported.
    """
    env = plugin_env_var(plugin)
    if library_path is None:
        library_path = os.environ.get(env, "")
    if not library_path:
        raise PluginRegistrationError(
            f"PJRT plugin '{plugin}' has no shared library configured: "
            f"set {env}=/path/to/pjrt_plugin_{plugin}.so")
    if not os.path.exists(library_path):
        raise PluginRegistrationError(
            f"PJRT plugin '{plugin}' shared library not found at "
            f"{library_path} (from {env}); is the plugin built?")
    from jax._src import xla_bridge as xb

    import jax

    xb.register_plugin(plugin, priority=10, library_path=library_path,
                       options=None)
    jax.config.update("jax_platforms", f"cpu,{plugin}")
    _emit_event("backend_plugin_registered", plugin=plugin,
                library_path=library_path)


def cpu_pinned() -> bool:
    """Whether this process can only ever see the cpu platform.  The config
    value only reflects ``config.update``; an env-var pin is read by jax at
    backend-init time, so consult both.  NOTE: on hosts whose site hook
    pre-imports jax against an accelerator plugin, a fresh subprocess may
    ignore an env-var cpu pin — in-process ``jax.config.update`` is the
    reliable route (provision_virtual_cpu does this)."""
    import jax

    platforms = getattr(jax.config, "jax_platforms", None) or os.environ.get(
        "JAX_PLATFORMS"
    )
    return bool(platforms) and set(str(platforms).split(",")) <= {"cpu"}


def backend_initialized() -> bool:
    """True once any JAX backend client exists in this process."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False  # private API moved: assume uninitialized


def probe_backend_responsive(
    timeout_s: int = 15,
    attempts: int = 1,
    backoff_s: float = 60.0,
    log=None,
    ignore_cache: bool = False,
) -> tuple[bool, str]:
    """Whether ``jax.devices()`` completes in a fresh interpreter.

    A wedged accelerator tunnel hangs ``jax.devices()`` indefinitely (seen
    on the tunneled TPU transport under sustained load); probing in a
    SUBPROCESS with a timeout lets callers fall back to a CPU mesh instead
    of hanging with it.  Only meaningful before this process initializes a
    backend.

    The deadline is a hard ~15 s by default: a healthy backend answers in
    low single-digit seconds, and BENCH_r05 measured a wedged tunnel
    holding the old 120–300 s deadlines for their full duration on every
    attempt — CPU failover should cost seconds, not minutes.

    Returns ``(ok, reason)`` — ``reason`` distinguishes a hang from a fast
    crash and carries the child's stderr tail so misconfigurations (e.g. a
    plugin version mismatch) aren't misreported as "unresponsive".

    ``attempts`` > 1 retries a failed probe after ``backoff_s`` seconds —
    for callers (the benchmark) whose entire purpose is the accelerator
    number, one transient wedge or a probe racing another process holding
    the chip should not flip the run to CPU permanently.  ``log`` (callable
    taking a string) narrates each failed attempt so a fallback is
    self-explaining.

    A successful probe is cached on disk for ``cache_s`` seconds (keyed by
    platform selection and uid) so bursts of CLI runs on a healthy machine
    don't pay the backend double-initialization.  The cache is a liveness
    tradeoff — a wedge arriving inside the window hangs the NEXT run like
    an unprobed one would (the probe is inherently a point-in-time check:
    even an uncached probe races a wedge arriving right after it); callers
    close that hole with ``touch_backend_with_watchdog``.  The window is
    kept short for that reason; failures are never cached.
    """
    import subprocess
    import sys
    import time

    cache_s = 300
    stamp = _probe_stamp_path()
    if not ignore_cache:
        # ``ignore_cache``: callers whose whole point is CURRENT liveness
        # (doctor --wait-healthy gating a relaunch) must not be vouched for
        # by a stamp that may predate a fresh wedge
        try:
            st = os.lstat(stamp)  # lstat: never trust a symlinked stamp
            import stat as _stat

            if (_stat.S_ISREG(st.st_mode) and st.st_uid == os.getuid()
                    and time.time() - st.st_mtime < cache_s):
                return True, "cached"
        except OSError:
            pass

    reason = ""
    for attempt in range(1, max(1, attempts) + 1):
        if attempt > 1:
            if log is not None:
                log(f"backend probe attempt {attempt - 1}/{attempts} failed "
                    f"({reason}); retrying in {backoff_s:.0f}s")
            time.sleep(backoff_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            reason = (f"jax.devices() did not return within {timeout_s}s "
                      "(hung backend)")
            continue
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            reason = ("backend probe crashed: "
                      + (" | ".join(tail) or f"rc={proc.returncode}"))
            continue
        try:
            fd = os.open(stamp, os.O_WRONLY | os.O_CREAT | os.O_NOFOLLOW,
                         0o600)
            os.utime(fd)
            os.close(fd)
        except OSError:
            pass
        _emit_event("backend_probe", ok=True, attempts=attempt,
                    timeout_s=timeout_s)
        return True, "" if attempt == 1 else f"ok after {attempt} attempts"
    if attempts > 1:
        reason += f" (after {attempts} attempts over ~" \
                  f"{attempts * timeout_s + (attempts - 1) * backoff_s:.0f}s)"
    _emit_event("backend_probe", ok=False, reason=reason,
                timeout_s=timeout_s)
    return False, reason


def _probe_stamp_path() -> str:
    """Path of the positive-probe cache stamp.

    uid in the key + O_NOFOLLOW on create (see caller): on a shared box
    another user's stale stamp must not vouch for this user's tunnel, nor
    may a planted symlink at the predictable path redirect the create.
    """
    import hashlib
    import sys
    import tempfile

    key = hashlib.sha256(
        (os.environ.get("JAX_PLATFORMS", "") + sys.executable
         + str(os.getuid())).encode()
    ).hexdigest()[:16]
    return os.path.join(tempfile.gettempdir(), f".fed_tgan_backend_ok_{key}")


def arm_watchdog(timeout_s: float, on_fire, name: str = "watchdog"):
    """Daemon thread that calls ``on_fire()`` unless cancelled within
    ``timeout_s``; returns the cancel callable.  Shared core of the
    backend-touch watchdog and the bench run deadline, so the
    Event/daemon-thread/force-exit shape cannot drift between them."""
    import threading

    done = threading.Event()

    def _watch() -> None:
        if not done.wait(timeout_s):
            on_fire()

    threading.Thread(target=_watch, daemon=True, name=name).start()
    return done.set


def touch_backend_with_watchdog(
    timeout_s: float = 180.0,
    who: str = "",
    _touch=None,
    _abort=None,
    _initialized=None,
) -> tuple[bool, str]:
    """Initialize the accelerator backend NOW, guarded by a watchdog.

    The probe cache means a run can start inside the positive-cache window
    of a probe that predates a fresh wedge; that run's first real
    ``jax.devices()`` then hangs exactly like an unprobed one.  Calling
    this right after platform selection closes the hole: the touch happens
    immediately, and a watchdog thread aborts the process with the same
    diagnosis the probe produces if it doesn't complete in ``timeout_s``.

    A touch that CRASHES instead of hanging (e.g. another process grabbed
    the chip between probe and touch) returns ``(False, reason)`` — the
    probe-style contract — so callers route it through their normal
    fallback/abort policy instead of dying on a raw traceback.  A hang
    cannot return: the watchdog ``os._exit``\\ s (not ``sys.exit``) because
    the main thread is stuck inside an uninterruptible C extension call —
    no Python exception can reach it.  Both failure modes invalidate the
    positive stamp so the next run re-probes for real.
    ``_touch``/``_abort`` are test seams; ``_initialized`` lets the
    ``parallel/mesh.py`` shim route the already-initialized early exit
    through its own (monkeypatchable) ``backend_initialized`` global.
    """
    if (_initialized or backend_initialized)():
        return True, ""
    import sys

    import jax

    def _drop_stamp() -> None:
        # invalidate the (now-stale) positive stamp so the NEXT run
        # re-probes for real and can fall back to CPU gracefully
        # instead of repeating this failure for the cache window
        try:
            os.unlink(_probe_stamp_path())
        except OSError:
            pass

    def _fire() -> None:
        _drop_stamp()
        print(
            f"{who}accelerator backend unusable (jax.devices() did not "
            f"return within {timeout_s:.0f}s after a positive probe — "
            "the tunnel likely wedged inside the probe-cache window); "
            "aborting — retry later or use --backend cpu",
            file=sys.stderr,
            flush=True,
        )
        (_abort or os._exit)(3)

    cancel = arm_watchdog(timeout_s, _fire, name="backend-touch-watchdog")
    try:
        (jax.devices if _touch is None else _touch)()
    except Exception as exc:
        _drop_stamp()
        return False, f"backend init crashed after a positive probe: {exc}"
    finally:
        cancel()
    return True, ""


def provision_virtual_cpu(n_devices: int) -> None:
    """Force an ``n_devices`` virtual CPU platform (the tests/CI recipe).

    Must run before any JAX backend initializes.  Sets
    ``--xla_force_host_platform_device_count`` in XLA_FLAGS — replacing any
    existing (possibly smaller) value — then overrides the platform through
    the config API, because this environment pre-imports jax with
    JAX_PLATFORMS=axon via a site hook, making the env-var route too late.
    Raises RuntimeError if the devices don't materialize (i.e. a backend was
    already initialized in this process).
    """
    import re

    import jax

    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"could not provision {n_devices} virtual CPU devices "
            f"(got {len(jax.devices())}); was a backend already initialized?"
        )


def client_mesh(n_devices: int | None = None, devices=None):
    """A 1-D mesh over ``n_devices`` (default: all) with axis 'clients'."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (CLIENTS_AXIS,))


def host_axis_groups(mesh):
    """``axis_index_groups`` pair for a two-tier (intra-host, cross-host)
    psum over the clients axis, or ``None`` when tiering buys nothing.

    Tier 1 groups the mesh positions living on one host process (reduced
    over fast intra-host interconnect); tier 2 groups one representative
    column across hosts, so the cross-host hop moves one partial per host
    instead of one per device.  Returns ``None`` — callers then emit the
    plain flat psum, byte-identical to pre-tier programs — when the mesh
    spans fewer than two processes, hosts hold unequal device counts
    (grouped psums need rectangular groups), or each host has a single
    device (tier 1 would be a no-op).
    """
    by_proc: dict[int, list[int]] = {}
    for idx, d in enumerate(mesh.devices.flat):
        by_proc.setdefault(d.process_index, []).append(idx)
    groups = [by_proc[p] for p in sorted(by_proc)]
    if len(groups) < 2:
        return None
    width = len(groups[0])
    if width < 2 or any(len(g) != width for g in groups):
        return None
    inter = [[g[j] for g in groups] for j in range(width)]
    return groups, inter


# --------------------------------------------------------------------------
# the Backend object: one handle for "which platform, is it alive, and how
# do I stand a mesh up on it"


@dataclasses.dataclass(frozen=True)
class BackendHealth:
    """Result of :meth:`Backend.probe` / :meth:`Backend.touch`.

    ``ok`` is the verdict; ``reason`` narrates a failure (or carries the
    probe's "cached"/"ok after N attempts" provenance on success);
    ``cached`` flags a positive verdict vouched for by the probe-stamp
    cache rather than a fresh subprocess run.
    """

    ok: bool
    reason: str = ""
    cached: bool = False
    backend: str = "cpu"

    def __bool__(self) -> bool:  # allows `if backend.probe():`
        return self.ok


class Backend:
    """A named execution platform and the policy for standing it up.

    Construction is cheap and jax-free; jax is touched only by
    :meth:`provision`/:meth:`touch`/:meth:`mesh`.  One instance per spec —
    use :func:`get_backend`.
    """

    def __init__(self, name: str):
        self.name = parse_backend(name) or "cpu"

    # -- identity ----------------------------------------------------------
    @property
    def is_cpu(self) -> bool:
        return self.name == "cpu"

    @property
    def is_plugin(self) -> bool:
        return self.name.startswith(_PLUGIN_PREFIX)

    @property
    def plugin_name(self) -> str | None:
        return self.name[len(_PLUGIN_PREFIX):] if self.is_plugin else None

    @property
    def platform(self) -> str:
        """The jax platform name this backend resolves to ('cpu', 'tpu',
        'gpu', or the plugin's registered name)."""
        return self.plugin_name or self.name

    def __repr__(self) -> str:
        return f"Backend({self.name!r})"

    # -- health ------------------------------------------------------------
    def probe(self, timeout_s: int = 15, attempts: int = 1,
              backoff_s: float = 60.0, log=None,
              ignore_cache: bool = False) -> BackendHealth:
        """Subprocess-probe the platform (see
        :func:`probe_backend_responsive`).  The cpu backend is trivially
        healthy — the host platform cannot wedge — so no subprocess is
        spent on it."""
        if self.is_cpu:
            return BackendHealth(True, "host platform", backend=self.name)
        ok, reason = probe_backend_responsive(
            timeout_s=timeout_s, attempts=attempts, backoff_s=backoff_s,
            log=log, ignore_cache=ignore_cache)
        return BackendHealth(ok, reason, cached=(reason == "cached"),
                             backend=self.name)

    def touch(self, timeout_s: float = 180.0, who: str = "") -> BackendHealth:
        """Initialize the backend now under a watchdog (see
        :func:`touch_backend_with_watchdog`)."""
        ok, reason = touch_backend_with_watchdog(timeout_s=timeout_s, who=who)
        return BackendHealth(ok, reason, backend=self.name)

    # -- provisioning ------------------------------------------------------
    def provision(self, n_virtual_devices: int = 8) -> None:
        """Make the platform selectable before jax initializes.

        cpu: force the ``n_virtual_devices`` virtual host mesh (the exact
        pre-seam ``provision_virtual_cpu`` path — lowered programs stay
        byte-identical).  plugin: register the PJRT plugin (fail-fast
        :class:`PluginRegistrationError` when absent).  tpu/gpu: nothing —
        native PJRT discovery owns them.
        """
        if self.is_cpu:
            provision_virtual_cpu(n_virtual_devices)
        elif self.is_plugin:
            register_pjrt_plugin(self.plugin_name)

    # -- topology ----------------------------------------------------------
    def mesh(self, n_devices: int | None = None, devices=None):
        return client_mesh(n_devices=n_devices, devices=devices)

    def host_groups(self, mesh):
        return host_axis_groups(mesh)

    # -- artifact routing --------------------------------------------------
    def contracts_dir(self) -> Path:
        return contracts_dir_for(self.name)

    def record_fields(self) -> dict:
        """Top-level ``backend``/``platform`` fields for bench records, so
        budgets select by backend (``obs slo`` ``select.backend``) and a
        future TPU session lands ``*_tpu`` artifacts next to CPU twins.
        ``platform`` reports what jax actually initialized when a backend
        is live (a cpu-fallback run says so); the spec's platform
        otherwise."""
        platform = self.platform
        if backend_initialized():
            try:
                import jax

                platform = jax.default_backend()
            except Exception:
                pass
        return {"backend": self.name, "platform": platform}


def get_backend(spec=None) -> Backend:
    """Backend for a ``--backend`` spec; ``None`` (auto mode) and ``cpu``
    both resolve to the cpu backend — auto-mode *policy* (probe, fall back)
    lives in the callers that own the fallback decision."""
    return Backend(spec if spec is not None else "cpu")


def contracts_dir_for(backend) -> Path:
    """hlolint contract directory for a backend.

    cpu (and auto) is the checked-in ``analysis/contracts/`` — the 41
    contract JSONs stay byte-identical.  Other backends get a sibling
    subdirectory (``analysis/contracts/tpu/``,
    ``analysis/contracts/plugin_<name>/``) so a future TPU session records
    its fingerprints next to the CPU twins instead of overwriting them.
    """
    root = Path(__file__).resolve().parent.parent / "analysis" / "contracts"
    name = parse_backend(backend) or "cpu"
    if name == "cpu":
        return root
    return root / name.replace(_PLUGIN_PREFIX, "plugin_")
