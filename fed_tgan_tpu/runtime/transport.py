"""Python bindings for the native host transport (native/transport.cpp).

Replaces the role of PyTorch RPC over Gloo/TensorPipe in the reference
(reference Server/dtds/distributed.py:849-857): a TCP rendezvous of one
server and N clients carrying pickled control-plane objects (metadata,
encoders, mixture models).  The hot path — per-epoch model aggregation —
never touches this: it is an XLA collective on the device mesh.

The shared library is built on demand with g++ (ctypes, no pybind11
dependency) and cached next to the source.

SECURITY: payloads are deserialized with ``pickle`` — the SAME trust model
as the reference's torch RPC (arbitrary code execution if the peer is
hostile).  Only run the init protocol between mutually trusted hosts on a
trusted network, exactly as the reference assumes for its TCP rendezvous.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading
from typing import Any, Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libfttransport.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _last_errno_suffix(lib) -> str:
    """' (strerror)' for the native layer's last create failure, or ''."""
    try:
        e = int(lib.ft_last_errno())
        return f" ({os.strerror(e)})" if e else ""
    except Exception:
        return ""


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_NATIVE_DIR, "transport.cpp")
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-Wall", "-fPIC", "-std=c++17", "-shared",
                 "-o", _LIB_PATH, src],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ft_last_errno.restype = ctypes.c_int
        lib.ft_last_errno.argtypes = []
        lib.ft_server_create.restype = ctypes.c_void_p
        lib.ft_server_create.argtypes = [ctypes.c_int]
        lib.ft_server_accept.restype = ctypes.c_int
        lib.ft_server_accept.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.ft_client_create.restype = ctypes.c_void_p
        lib.ft_client_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.ft_send.restype = ctypes.c_int
        lib.ft_send.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_int,
        ]
        lib.ft_recv.restype = ctypes.c_int
        lib.ft_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ]
        lib.ft_free.restype = None
        lib.ft_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.ft_close.restype = None
        lib.ft_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class TransportError(RuntimeError):
    pass


_ERRORS = {-1: "socket error", -2: "timeout", -3: "peer closed", -4: "bad argument"}


def _check(rc: int, what: str) -> None:
    if rc < 0:
        raise TransportError(f"{what}: {_ERRORS.get(rc, rc)}")


class _Endpoint:
    def __init__(self, handle: int):
        self._lib = _load_library()
        self._handle = handle

    def _send_bytes(self, peer: int, payload: bytes, timeout_ms: int) -> None:
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        _check(
            self._lib.ft_send(self._handle, peer, buf, len(payload), timeout_ms),
            "send",
        )

    def _recv_bytes(self, peer: int, timeout_ms: int) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        _check(
            self._lib.ft_recv(
                self._handle, peer, ctypes.byref(out), ctypes.byref(out_len), timeout_ms
            ),
            "recv",
        )
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.ft_free(out)

    def close(self) -> None:
        if self._handle:
            self._lib.ft_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ServerTransport(_Endpoint):
    """Rank-0 endpoint: accepts n clients, then object send/recv per rank."""

    def __init__(self, port: int, n_clients: int, timeout_ms: int = 600_000):
        lib = _load_library()
        handle = lib.ft_server_create(port)
        if not handle:
            raise TransportError(
                f"cannot listen on port {port}{_last_errno_suffix(lib)}"
            )
        super().__init__(handle)
        self.n_clients = n_clients
        rc = lib.ft_server_accept(handle, n_clients, timeout_ms)
        if rc < 0:
            self.close()
            _check(rc, "accept")

    def send_obj(self, rank: int, obj: Any, timeout_ms: int = 600_000) -> None:
        self._send_bytes(rank, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL), timeout_ms)

    def recv_obj(self, rank: int, timeout_ms: int = 600_000) -> Any:
        return pickle.loads(self._recv_bytes(rank, timeout_ms))

    def broadcast(self, obj: Any, timeout_ms: int = 600_000) -> None:
        for rank in range(1, self.n_clients + 1):
            self.send_obj(rank, obj, timeout_ms)

    def gather(self, timeout_ms: int = 600_000) -> list:
        return [self.recv_obj(rank, timeout_ms) for rank in range(1, self.n_clients + 1)]


class ClientTransport(_Endpoint):
    """Rank >= 1 endpoint; retries the rendezvous until the server is up."""

    def __init__(self, host: str, port: int, rank: int, timeout_ms: int = 600_000):
        lib = _load_library()
        handle = lib.ft_client_create(host.encode(), port, rank, timeout_ms)
        if not handle:
            raise TransportError(
                f"cannot reach server at {host}:{port}{_last_errno_suffix(lib)}"
            )
        super().__init__(handle)
        self.rank = rank

    def send_obj(self, obj: Any, timeout_ms: int = 600_000) -> None:
        self._send_bytes(0, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL), timeout_ms)

    def recv_obj(self, timeout_ms: int = 600_000) -> Any:
        return pickle.loads(self._recv_bytes(0, timeout_ms))
