"""Python bindings for the native host transport (native/transport.cpp).

Replaces the role of PyTorch RPC over Gloo/TensorPipe in the reference
(reference Server/dtds/distributed.py:849-857): a TCP rendezvous of one
server and N clients carrying pickled control-plane objects (metadata,
encoders, mixture models).  The hot path — per-epoch model aggregation —
never touches this: it is an XLA collective on the device mesh.

Fault tolerance (this layer, not the native one):

- Every message is framed with a per-direction sequence number; a retried
  send after a reconnect is IDEMPOTENT because the receiver drops frames
  whose sequence it has already accepted.
- Clients reconnect with exponential backoff (bounded tries) when the
  connection drops mid-protocol, then run a RESYNC handshake that resends
  whichever single in-flight message the cut may have eaten (the protocol
  is strictly alternating per rank, so the gap is at most one frame each
  way).
- Clients emit a lightweight heartbeat so the server can distinguish a
  SLOW peer (heartbeats flowing, no data yet — keep waiting until the
  phase deadline) from a DEAD one (heartbeat lapse — raise PeerDeadError
  early instead of burning the whole deadline).
- Per-phase deadlines (``Deadlines``) replace the old flat 600 s timeout
  and can be overridden per field via ``FED_TGAN_TPU_TRANSPORT_*`` env
  vars.

The shared library is built on demand with g++ (ctypes, no pybind11
dependency) and cached next to the source.

SECURITY: payloads are deserialized with ``pickle`` — the SAME trust model
as the reference's torch RPC (arbitrary code execution if the peer is
hostile).  Only run the init protocol between mutually trusted hosts on a
trusted network, exactly as the reference assumes for its TCP rendezvous.
"""

from __future__ import annotations

import ctypes
import dataclasses
import logging
import os
import pickle
import struct
import subprocess
import threading
import time
from typing import Any, Optional

from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.obs.registry import counter as _metric_counter

log = logging.getLogger("fed_tgan_tpu.transport")

_RECONNECTS = _metric_counter(
    "fed_tgan_transport_reconnects_total",
    "transport connections re-established after a drop")
_DROPS = _metric_counter(
    "fed_tgan_transport_drops_total",
    "peers marked dead by the server")
_LAPSES = _metric_counter(
    "fed_tgan_transport_heartbeat_lapses_total",
    "heartbeat liveness deadlines exceeded")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libfttransport.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

# frame header: u64 LE sequence number + one type byte
_HEADER = struct.Struct("<QB")
_DATA, _HEARTBEAT, _RESYNC, _RESYNC_ACK = 0, 1, 2, 3


def _last_errno_suffix(lib) -> str:
    """' (strerror)' for the native layer's last create failure, or ''."""
    try:
        e = int(lib.ft_last_errno())
        return f" ({os.strerror(e)})" if e else ""
    except Exception:
        return ""


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_NATIVE_DIR, "transport.cpp")
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-Wall", "-fPIC", "-std=c++17", "-shared",
                 "-o", _LIB_PATH, src],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.ft_last_errno.restype = ctypes.c_int
        lib.ft_last_errno.argtypes = []
        lib.ft_server_create.restype = ctypes.c_void_p
        lib.ft_server_create.argtypes = [ctypes.c_int]
        lib.ft_server_accept.restype = ctypes.c_int
        lib.ft_server_accept.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.ft_server_poll_accept.restype = ctypes.c_int
        lib.ft_server_poll_accept.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ft_peer_close.restype = ctypes.c_int
        lib.ft_peer_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ft_poll.restype = ctypes.c_int
        lib.ft_poll.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.ft_client_create.restype = ctypes.c_void_p
        lib.ft_client_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.ft_send.restype = ctypes.c_int
        lib.ft_send.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_int,
        ]
        lib.ft_recv.restype = ctypes.c_int
        lib.ft_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ]
        lib.ft_free.restype = None
        lib.ft_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.ft_close.restype = None
        lib.ft_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class TransportError(RuntimeError):
    pass


class DeadlineError(TransportError):
    """The phase deadline passed while the peer was still alive (slow)."""


class PeerDeadError(TransportError):
    """The peer's heartbeat lapsed or it exhausted its reconnect budget."""


_ERRORS = {-1: "socket error", -2: "timeout", -3: "peer closed", -4: "bad argument"}
_TIMEOUT, _CLOSED = -2, -3


def _check(rc: int, what: str) -> None:
    if rc < 0:
        cls = DeadlineError if rc == _TIMEOUT else TransportError
        raise cls(f"{what}: {_ERRORS.get(rc, rc)}")


@dataclasses.dataclass(frozen=True)
class Deadlines:
    """Per-phase transport deadlines and retry policy (all times in ms).

    Replaces the flat 600 s timeout: the rendezvous, the object-valued init
    phase, and the (much longer) training-loop waits each get their own
    budget.  Every field can be overridden with an env var named
    ``FED_TGAN_TPU_TRANSPORT_<FIELD>`` (upper-cased), e.g.
    ``FED_TGAN_TPU_TRANSPORT_HEARTBEAT_TIMEOUT_MS=5000``.
    """

    connect_ms: int = 600_000        # initial rendezvous / accept
    init_ms: int = 600_000           # init-protocol sends/recvs
    train_ms: int = 3_600_000        # training-loop recvs (rounds are slow)
    heartbeat_interval_ms: int = 2_000
    heartbeat_timeout_ms: int = 30_000
    reconnect_max_tries: int = 5
    reconnect_base_ms: int = 100     # backoff: base * 2^attempt, capped
    reconnect_cap_ms: int = 5_000

    @classmethod
    def from_env(cls, **overrides) -> "Deadlines":
        vals = dict(overrides)
        for f in dataclasses.fields(cls):
            env = os.environ.get(f"FED_TGAN_TPU_TRANSPORT_{f.name.upper()}")
            if env is not None and f.name not in vals:
                vals[f.name] = int(env)
        return cls(**vals)


class _Endpoint:
    def __init__(self, handle: int):
        self._lib = _load_library()
        self._handle = handle

    def _send_bytes(self, peer: int, payload: bytes, timeout_ms: int) -> None:
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        _check(
            self._lib.ft_send(self._handle, peer, buf, len(payload), timeout_ms),
            "send",
        )

    def _recv_bytes(self, peer: int, timeout_ms: int) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        _check(
            self._lib.ft_recv(
                self._handle, peer, ctypes.byref(out), ctypes.byref(out_len), timeout_ms
            ),
            "recv",
        )
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.ft_free(out)

    def close(self) -> None:
        if self._handle:
            self._lib.ft_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _frame(seq: int, mtype: int, payload: bytes = b"") -> bytes:
    return _HEADER.pack(seq, mtype) + payload


def _unframe(raw: bytes) -> tuple[int, int, bytes]:
    if len(raw) < _HEADER.size:
        raise TransportError(f"short frame ({len(raw)} bytes)")
    seq, mtype = _HEADER.unpack_from(raw)
    return seq, mtype, raw[_HEADER.size:]


def _fault_plan():
    """The process-wide fault-injection plan, or None (lazy import: the
    testing package must not be a hard dependency of the wire path)."""
    try:
        from fed_tgan_tpu.testing.faults import active_plan

        return active_plan()
    except Exception:
        return None


class ServerTransport(_Endpoint):
    """Rank-0 endpoint: accepts n clients, then object send/recv per rank.

    Tracks per-rank liveness from heartbeats, services mid-protocol
    reconnections (a lost rank re-appears through the listening socket and
    resyncs), and exposes ``dropped``/``mark_dropped`` so the federation
    layer can degrade gracefully instead of hanging on a dead peer.
    """

    _SLICE_MS = 200  # recv granularity: heartbeat/reconnect service cadence

    def __init__(self, port: int, n_clients: int, timeout_ms: int | None = None,
                 deadlines: Deadlines | None = None):
        self.deadlines = deadlines or Deadlines.from_env()
        if timeout_ms is None:
            timeout_ms = self.deadlines.connect_ms
        lib = _load_library()
        handle = lib.ft_server_create(port)
        if not handle:
            raise TransportError(
                f"cannot listen on port {port}{_last_errno_suffix(lib)}"
            )
        super().__init__(handle)
        self.n_clients = n_clients
        self.dropped: set[int] = set()
        # guards membership + per-rank sequence/liveness maps: the maps are
        # read on the protocol thread but a future multi-threaded server
        # (and jaxlint J05) require every mutation to ride under one lock
        self._state_lock = threading.Lock()
        now = time.monotonic()
        self._send_seq = {r: 0 for r in range(1, n_clients + 1)}
        self._recv_seq = {r: 0 for r in range(1, n_clients + 1)}
        self._last_sent: dict[int, bytes] = {}
        self._last_alive = {r: now for r in range(1, n_clients + 1)}
        rc = lib.ft_server_accept(handle, n_clients, timeout_ms)
        if rc < 0:
            self.close()
            _check(rc, "accept")
        now = time.monotonic()
        for r in self._last_alive:
            self._last_alive[r] = now

    # -- liveness / membership ------------------------------------------------

    def live_ranks(self) -> list[int]:
        return [r for r in range(1, self.n_clients + 1) if r not in self.dropped]

    def mark_dropped(self, rank: int, reason: str = "") -> None:
        if rank in self.dropped:
            return
        with self._state_lock:
            self.dropped.add(rank)
        self._lib.ft_peer_close(self._handle, rank)
        _DROPS.inc()
        _emit_event("transport_drop", rank=rank, reason=reason)
        log.warning("transport: dropped client rank %d%s", rank,
                    f" ({reason})" if reason else "")

    def _service_reconnects(self, budget_ms: int = 0) -> Optional[int]:
        """Absorb at most one pending reconnection; returns its rank."""
        rank = self._lib.ft_server_poll_accept(self._handle, budget_ms)
        if rank <= 0:
            return None
        if rank in self.dropped:
            # membership is final once weights were renormalized
            self._lib.ft_peer_close(self._handle, rank)
            log.warning("transport: refused reconnect from dropped rank %d", rank)
            return None
        self._resync(rank)
        with self._state_lock:
            self._last_alive[rank] = time.monotonic()
        _RECONNECTS.inc()
        _emit_event("transport_reconnect", role="server", rank=rank)
        log.warning("transport: client rank %d reconnected", rank)
        return rank

    def _resync(self, rank: int) -> None:
        """Server half of the reconnect handshake: learn what the client saw,
        acknowledge what we saw, and resend the one frame the cut may have
        eaten in our direction."""
        raw = self._recv_bytes(rank, 10_000)
        seq, mtype, payload = _unframe(raw)
        if mtype != _RESYNC:
            raise TransportError(
                f"rank {rank}: expected RESYNC after reconnect, got type {mtype}"
            )
        cl_recv, cl_send = pickle.loads(payload)
        ack = pickle.dumps((self._recv_seq[rank], self._send_seq[rank]),
                           protocol=pickle.HIGHEST_PROTOCOL)
        self._send_bytes(rank, _frame(0, _RESYNC_ACK, ack), 10_000)
        if cl_recv < self._send_seq[rank]:
            if self._send_seq[rank] - cl_recv != 1 or rank not in self._last_sent:
                raise TransportError(
                    f"rank {rank}: unrecoverable sequence gap "
                    f"(peer saw {cl_recv}, we sent {self._send_seq[rank]})"
                )
            self._send_bytes(rank, self._last_sent[rank], 10_000)
        # if cl_send > self._recv_seq[rank] the client resends after the ack;
        # the sequence check in recv_obj dedups anything duplicated

    def _check_liveness(self, rank: int) -> None:
        lapse_s = self.deadlines.heartbeat_timeout_ms / 1000.0
        if time.monotonic() - self._last_alive[rank] > lapse_s:
            _LAPSES.inc()
            _emit_event("heartbeat_lapse", rank=rank,
                        timeout_ms=self.deadlines.heartbeat_timeout_ms)
            raise PeerDeadError(
                f"rank {rank}: heartbeat lapsed "
                f"(> {self.deadlines.heartbeat_timeout_ms} ms without a frame)"
            )

    # -- object API -----------------------------------------------------------

    def send_obj(self, rank: int, obj: Any, timeout_ms: int | None = None) -> None:
        if rank in self.dropped:
            raise PeerDeadError(f"rank {rank} was dropped")
        budget = timeout_ms if timeout_ms is not None else self.deadlines.init_ms
        deadline = time.monotonic() + budget / 1000.0
        plan = _fault_plan()
        if plan is not None:
            plan.maybe_delay()
        seq = self._send_seq[rank] + 1
        frame = _frame(seq, _DATA,
                       pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        while True:
            remaining = int((deadline - time.monotonic()) * 1000)
            if remaining <= 0:
                raise DeadlineError(f"send to rank {rank}: deadline passed")
            try:
                self._send_bytes(rank, frame, remaining)
                break
            except DeadlineError:
                raise
            except TransportError:
                # connection gone: wait for the client to reconnect, resync,
                # then retry (the sequence number makes the retry idempotent)
                self._await_reconnect(rank, deadline)
        with self._state_lock:
            self._send_seq[rank] = seq
            self._last_sent[rank] = frame

    def recv_obj(self, rank: int, timeout_ms: int | None = None) -> Any:
        if rank in self.dropped:
            raise PeerDeadError(f"rank {rank} was dropped")
        budget = timeout_ms if timeout_ms is not None else self.deadlines.init_ms
        deadline = time.monotonic() + budget / 1000.0
        while True:
            remaining = int((deadline - time.monotonic()) * 1000)
            if remaining <= 0:
                raise DeadlineError(f"recv from rank {rank}: deadline passed")
            # poll first (no bytes consumed): a slice timeout mid-frame must
            # not corrupt the stream; the real recv below gets the full
            # remaining budget once a frame has started arriving
            ready = self._lib.ft_poll(self._handle, rank,
                                      min(self._SLICE_MS, remaining))
            if ready == 0:
                self._service_reconnects(0)
                self._check_liveness(rank)
                continue
            if ready < 0:
                self._await_reconnect(rank, deadline)
                continue
            try:
                raw = self._recv_bytes(rank, remaining)
            except DeadlineError:
                raise
            except TransportError:
                self._await_reconnect(rank, deadline)
                continue
            with self._state_lock:
                self._last_alive[rank] = time.monotonic()
            seq, mtype, payload = _unframe(raw)
            if mtype == _HEARTBEAT:
                continue
            if mtype == _RESYNC:
                # fd survived but the CLIENT saw a cut and reconnected races
                # are absorbed in _service_reconnects; a stray RESYNC on the
                # live fd means our previous fd died and poll_accept already
                # swapped it — run the same handshake minus the recv
                raise TransportError(
                    f"rank {rank}: unexpected RESYNC on live connection"
                )
            if mtype != _DATA:
                raise TransportError(f"rank {rank}: unknown frame type {mtype}")
            if seq <= self._recv_seq[rank]:
                continue  # duplicate of an already-accepted frame
            if seq != self._recv_seq[rank] + 1:
                raise TransportError(
                    f"rank {rank}: sequence gap (got {seq}, "
                    f"expected {self._recv_seq[rank] + 1})"
                )
            with self._state_lock:
                self._recv_seq[rank] = seq
            return pickle.loads(payload)

    def _await_reconnect(self, rank: int, deadline: float) -> None:
        """Block until ``rank`` re-appears through the listening socket (its
        connection died under us), bounded by heartbeat lapse and the phase
        deadline."""
        lapse_s = self.deadlines.heartbeat_timeout_ms / 1000.0
        lost_at = time.monotonic()
        log.warning("transport: lost connection to rank %d; awaiting reconnect",
                    rank)
        while True:
            now = time.monotonic()
            if now >= deadline:
                raise DeadlineError(
                    f"rank {rank}: deadline passed awaiting reconnect"
                )
            if now - lost_at > lapse_s:
                raise PeerDeadError(
                    f"rank {rank}: no reconnect within "
                    f"{self.deadlines.heartbeat_timeout_ms} ms"
                )
            if self._service_reconnects(self._SLICE_MS) == rank:
                return

    def broadcast(self, obj: Any, timeout_ms: int | None = None) -> None:
        for rank in self.live_ranks():
            self.send_obj(rank, obj, timeout_ms)

    def gather(self, timeout_ms: int | None = None) -> list:
        return [self.recv_obj(rank, timeout_ms) for rank in self.live_ranks()]

    def broadcast_surviving(
        self, obj: Any, timeout_ms: int | None = None
    ) -> list[int]:
        """Broadcast to every live rank, DROPPING any that is unreachable
        instead of failing the whole phase.  Returns the ranks dropped in
        this call."""
        newly_dropped: list[int] = []
        for rank in self.live_ranks():
            try:
                self.send_obj(rank, obj, timeout_ms)
            except TransportError as exc:
                self.mark_dropped(rank, str(exc))
                newly_dropped.append(rank)
        return newly_dropped

    def gather_surviving(
        self, timeout_ms: int | None = None
    ) -> tuple[dict[int, Any], list[int]]:
        """Gather from every live rank, DROPPING any that dies or misses the
        deadline instead of failing the whole phase.  Returns ``(results by
        rank, ranks dropped in this call)``."""
        results: dict[int, Any] = {}
        newly_dropped: list[int] = []
        for rank in self.live_ranks():
            try:
                results[rank] = self.recv_obj(rank, timeout_ms)
            except TransportError as exc:
                self.mark_dropped(rank, str(exc))
                newly_dropped.append(rank)
        return results, newly_dropped


class ClientTransport(_Endpoint):
    """Rank >= 1 endpoint; retries the rendezvous until the server is up.

    On a mid-protocol connection loss, reconnects with exponential backoff
    (bounded tries), resyncs sequence numbers with the server, and resends
    the one frame that may have been lost — so callers see a slow call, not
    a dead run.  A daemon heartbeat thread keeps the server's liveness view
    fresh between protocol messages.
    """

    def __init__(self, host: str, port: int, rank: int,
                 timeout_ms: int | None = None,
                 deadlines: Deadlines | None = None,
                 heartbeat: bool = True):
        self.deadlines = deadlines or Deadlines.from_env()
        if timeout_ms is None:
            timeout_ms = self.deadlines.connect_ms
        self._host, self._port = host, port
        lib = _load_library()
        handle = lib.ft_client_create(host.encode(), port, rank, timeout_ms)
        if not handle:
            raise TransportError(
                f"cannot reach server at {host}:{port}{_last_errno_suffix(lib)}"
            )
        super().__init__(handle)
        self.rank = rank
        self._send_seq = 0
        self._recv_seq = 0
        self._last_sent: Optional[bytes] = None
        self._sent_count = 0
        # serializes sends and the handle swap between the caller thread(s)
        # and the heartbeat thread (recv shares the socket full-duplex and
        # only ever runs in the thread that also reconnects)
        self._io_lock = threading.RLock()
        self._hb_stop = threading.Event()
        if heartbeat and self.deadlines.heartbeat_interval_ms > 0:
            t = threading.Thread(target=self._heartbeat_loop, daemon=True,
                                 name=f"ft-heartbeat-r{rank}")
            t.start()

    def _heartbeat_loop(self) -> None:
        interval = self.deadlines.heartbeat_interval_ms / 1000.0
        beat = _frame(0, _HEARTBEAT)
        while not self._hb_stop.wait(interval):
            try:
                with self._io_lock:
                    if not self._handle:
                        return
                    self._send_bytes(0, beat, 1_000)
            except TransportError:
                pass  # the protocol path owns reconnecting

    def close(self) -> None:
        self._hb_stop.set()
        with self._io_lock:
            super().close()

    # -- reconnect ------------------------------------------------------------

    def _reconnect(self) -> None:
        """Re-establish the connection with exponential backoff, then resync
        sequence numbers with the server (bounded tries -> PeerDeadError)."""
        dl = self.deadlines
        last_exc: Optional[Exception] = None
        for attempt in range(dl.reconnect_max_tries):
            if attempt:
                backoff = min(dl.reconnect_cap_ms,
                              dl.reconnect_base_ms * (2 ** (attempt - 1)))
                log.warning(
                    "transport: rank %d reconnect attempt %d/%d in %d ms",
                    self.rank, attempt + 1, dl.reconnect_max_tries, backoff)
                time.sleep(backoff / 1000.0)
            lib = self._lib
            handle = lib.ft_client_create(
                self._host.encode(), self._port, self.rank,
                max(dl.reconnect_base_ms, 1_000))
            if not handle:
                last_exc = TransportError(
                    f"reconnect to {self._host}:{self._port} failed"
                    f"{_last_errno_suffix(lib)}")
                continue
            with self._io_lock:
                if self._handle:
                    lib.ft_close(self._handle)
                self._handle = handle
            try:
                self._resync()
                _RECONNECTS.inc()
                _emit_event("transport_reconnect", role="client",
                            rank=self.rank, attempts=attempt + 1)
                log.warning("transport: rank %d reconnected and resynced",
                            self.rank)
                return
            except TransportError as exc:
                last_exc = exc
                continue
        raise PeerDeadError(
            f"rank {self.rank}: gave up after {dl.reconnect_max_tries} "
            f"reconnect attempts: {last_exc}")

    def _resync(self) -> None:
        state = pickle.dumps((self._recv_seq, self._send_seq),
                             protocol=pickle.HIGHEST_PROTOCOL)
        with self._io_lock:
            self._send_bytes(0, _frame(0, _RESYNC, state), 10_000)
        raw = self._recv_bytes(0, 10_000)
        seq, mtype, payload = _unframe(raw)
        if mtype != _RESYNC_ACK:
            raise TransportError(f"expected RESYNC_ACK, got type {mtype}")
        srv_recv, _srv_send = pickle.loads(payload)
        if srv_recv < self._send_seq:
            if self._send_seq - srv_recv != 1 or self._last_sent is None:
                raise TransportError(
                    f"unrecoverable sequence gap (server saw {srv_recv}, "
                    f"we sent {self._send_seq})")
            with self._io_lock:
                self._send_bytes(0, self._last_sent, 10_000)
        # any frame the SERVER resends is deduped by recv_obj's seq check

    # -- object API -----------------------------------------------------------

    def send_obj(self, obj: Any, timeout_ms: int | None = None) -> None:
        budget = timeout_ms if timeout_ms is not None else self.deadlines.init_ms
        deadline = time.monotonic() + budget / 1000.0
        plan = _fault_plan()
        if plan is not None:
            plan.maybe_delay()
        seq = self._send_seq + 1
        frame = _frame(seq, _DATA,
                       pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        while True:
            remaining = int((deadline - time.monotonic()) * 1000)
            if remaining <= 0:
                raise DeadlineError("send: deadline passed")
            try:
                with self._io_lock:
                    self._send_bytes(0, frame, remaining)
                    # bookkeeping rides inside the same lock as the send so
                    # a concurrent reader never sees a frame on the wire
                    # with stale seq/replay state (jaxlint J05)
                    self._send_seq = seq
                    self._last_sent = frame
                    self._sent_count += 1
                break
            except DeadlineError:
                raise
            except TransportError:
                self._reconnect()
        if plan is not None and plan.should_sever(self.rank, self._sent_count):
            # fault injection: sever our own live connection AFTER a
            # successful send so the next op exercises reconnect+resync
            log.warning("transport: FAULT severing rank %d connection",
                        self.rank)
            self._lib.ft_peer_close(self._handle, 0)

    def recv_obj(self, timeout_ms: int | None = None) -> Any:
        budget = timeout_ms if timeout_ms is not None else self.deadlines.init_ms
        deadline = time.monotonic() + budget / 1000.0
        while True:
            remaining = int((deadline - time.monotonic()) * 1000)
            if remaining <= 0:
                raise DeadlineError("recv: deadline passed")
            try:
                raw = self._recv_bytes(0, remaining)
            except DeadlineError:
                raise
            except TransportError:
                self._reconnect()
                continue
            seq, mtype, payload = _unframe(raw)
            if mtype in (_HEARTBEAT, _RESYNC_ACK):
                continue  # stale handshake leftovers are harmless
            if mtype != _DATA:
                raise TransportError(f"unknown frame type {mtype}")
            if seq <= self._recv_seq:
                continue  # duplicate after a resync resend
            if seq != self._recv_seq + 1:
                raise TransportError(
                    f"sequence gap (got {seq}, expected {self._recv_seq + 1})")
            self._recv_seq = seq
            return pickle.loads(payload)
