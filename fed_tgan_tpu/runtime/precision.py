"""Mixed-precision policy: bf16 compute with pinned f32 islands.

One small frozen policy object, resolved from ``TrainConfig.precision``
(``"f32"`` | ``"bf16"``), is consumed by every layer that does math:

* the models cast parameters/inputs to the COMPUTE dtype at loss-function
  entry, so matmuls (the MXU path) run in bf16 while ``jax.grad`` returns
  f32 gradients automatically — the vjp of ``convert_element_type`` casts
  cotangents back to the cast's input dtype, which keeps MASTER params and
  Adam moments f32 with zero optimizer changes;
* numerically fragile reductions stay f32 ISLANDS regardless of mode:
  the WGAN-GP gradient-penalty norm (``models/losses.py``), loss mean
  reductions and the conditional cross-entropy logits (``train/steps.py``,
  ``ops/segments.py``), Gumbel-softmax logits (``ops/segments.py`` /
  ``ops/activate_pallas.py``), batch-norm statistics (``models/ctgan.py``),
  and the FedAvg accumulation (``parallel/fedavg.py``);
* the aggregation payload that crosses the wire each round is re-encoded
  to bf16 (``weighted_delta_average``) — roughly half the collective
  bytes, contract-checked by ``analysis/contracts``.

Every hook is a same-dtype ``astype`` in f32 mode: jax elides
same-dtype ``convert_element_type`` at trace time, so f32-mode programs
stay BYTE-IDENTICAL to pre-precision builds (the existing IR contracts
prove this property on every run).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

PRECISIONS = ("f32", "bf16")


@dataclass(frozen=True)
class Precision:
    """Resolved precision policy; construct via :func:`resolve_precision`."""

    name: str  # "f32" | "bf16"

    @property
    def compute_dtype(self):
        """dtype of matmuls / activations inside the loss functions."""
        return jnp.bfloat16 if self.name == "bf16" else jnp.float32

    @property
    def param_dtype(self):
        """Master parameters and optimizer moments are ALWAYS f32; the
        compute cast happens inside the loss function, never on the
        stored pytrees."""
        return jnp.float32

    def cast(self, tree):
        """Cast every floating leaf of ``tree`` (a pytree or bare array)
        to the compute dtype.  Identity in f32 mode — not merely cheap:
        no convert op is even traced, so f32 programs keep their exact
        pre-precision IR."""
        if self.name == "f32":
            return tree
        dt = self.compute_dtype
        return jax.tree.map(
            lambda x: x.astype(dt)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            tree,
        )

    @property
    def payload_dtype(self):
        """dtype of the FedAvg collective payload (None = leave f32)."""
        return jnp.bfloat16 if self.name == "bf16" else None


def resolve_precision(name: str) -> Precision:
    """Validate and freeze a precision selection."""
    if name not in PRECISIONS:
        raise ValueError(
            f"unknown precision {name!r}; expected one of {PRECISIONS}")
    return Precision(name)


def f32_island(x):
    """Pin ``x`` to f32 for a numerically fragile region (no-op on f32
    input — same-dtype casts trace to nothing)."""
    return x.astype(jnp.float32)
