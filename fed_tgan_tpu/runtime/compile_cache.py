"""Machine-scoped persistent XLA compile cache.

XLA:CPU persists AOT-compiled executables keyed by HLO only — NOT by the
host's CPU features.  An entry built on one box loads on another with
"Machine type used for XLA:CPU compilation doesn't match" warnings (or
SIGILL), and because existing entries are never overwritten, a stale cache
poisons every later run with failed-load + recompile on each lookup.  The
repo moves between driver/judge/builder machines across rounds, so the
cache directory must be scoped to the machine that built it.
"""

from __future__ import annotations

import hashlib
import os
import platform


def _machine_fingerprint() -> str:
    """Stable id for this host's instruction-set capabilities."""
    bits = [platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    bits.append(" ".join(sorted(line.split(":", 1)[1].split())))
                    break
    except OSError:
        bits.append(platform.processor())
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def enable_persistent_cache(base_dir: str) -> str:
    """Point JAX's persistent compile cache at a machine-scoped subdir of
    ``base_dir`` and lower the size/time thresholds so tiny test/bench
    programs are cached too.  Returns the directory used."""
    import jax

    cache_dir = os.path.join(base_dir, _machine_fingerprint())
    _sweep_flat_layout_entries(base_dir)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


_SWEEP_MARKER = ".flat_layout_swept"


def _looks_like_xla_entry(name: str) -> bool:
    """XLA persistent-cache entries are ``jit_<fn>-<hex>`` /  long-hex
    names; anything else in the dir is NOT ours to delete."""
    import re

    return bool(re.match(r"^jit_", name) or re.fullmatch(r"[0-9a-f]{16,}", name))


def _sweep_flat_layout_entries(base_dir: str) -> None:
    """Delete entries from the pre-fingerprint flat layout: they were built
    by whichever machine last held the repo and would sit as dead weight
    (JAX only reads the fingerprint subdir now).  One-time (marker-gated)
    and restricted to XLA-looking names, so pointing ``base_dir`` at a
    non-dedicated directory can't silently eat unrelated files."""
    marker = os.path.join(base_dir, _SWEEP_MARKER)
    if os.path.exists(marker):
        return
    try:
        for name in os.listdir(base_dir):
            path = os.path.join(base_dir, name)
            if os.path.isfile(path) and _looks_like_xla_entry(name):
                os.unlink(path)
        with open(marker, "w"):
            pass
    except OSError:
        pass
