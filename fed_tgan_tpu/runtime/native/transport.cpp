// fed_tgan_tpu host-transport: length-prefixed TCP message passing.
//
// Native replacement for the role PyTorch RPC over Gloo/TensorPipe plays in
// the reference (Server/dtds/distributed.py:849-857, .gitmodules Gloo +
// TensorPipe submodules): a rendezvous of one server (rank 0) and N clients
// over TCP, exchanging opaque byte payloads (the Python layer pickles).
//
// Design notes:
// - The device-side FedAvg runs over XLA collectives (ICI/DCN); this
//   transport carries only the cold, object-valued init phase (metadata,
//   encoders, mixture models) and control messages, so simplicity and
//   robustness beat throughput tricks.
// - Frames: 8-byte little-endian payload length, then payload.
// - All calls are blocking with an optional deadline; errors are negative
//   return codes (never exceptions across the C ABI).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <vector>

namespace {

constexpr int kErrSocket = -1;
constexpr int kErrTimeout = -2;
constexpr int kErrClosed = -3;
constexpr int kErrArg = -4;

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Block until fd is ready for events or deadline passes.
int wait_fd(int fd, short events, int64_t deadline_ms) {
  while (true) {
    int64_t budget = deadline_ms < 0 ? -1 : deadline_ms - now_ms();
    if (deadline_ms >= 0 && budget <= 0) return kErrTimeout;
    struct pollfd p = {fd, events, 0};
    int rc = poll(&p, 1, deadline_ms < 0 ? -1 : static_cast<int>(budget));
    if (rc > 0) return 0;
    if (rc == 0) return kErrTimeout;
    if (errno != EINTR) return kErrSocket;
  }
}

int send_all(int fd, const uint8_t* buf, size_t len, int64_t deadline_ms) {
  size_t off = 0;
  while (off < len) {
    int rc = wait_fd(fd, POLLOUT, deadline_ms);
    if (rc < 0) return rc;
    ssize_t n = ::send(fd, buf + off, len - off, MSG_NOSIGNAL);
    if (n > 0) {
      off += static_cast<size_t>(n);
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)) {
      continue;
    } else {
      return kErrClosed;
    }
  }
  return 0;
}

int recv_all(int fd, uint8_t* buf, size_t len, int64_t deadline_ms) {
  size_t off = 0;
  while (off < len) {
    int rc = wait_fd(fd, POLLIN, deadline_ms);
    if (rc < 0) return rc;
    ssize_t n = ::recv(fd, buf + off, len - off, 0);
    if (n > 0) {
      off += static_cast<size_t>(n);
    } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)) {
      continue;
    } else {
      return kErrClosed;
    }
  }
  return 0;
}

void set_common_opts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // non-blocking + poll gives us deadlines everywhere
  // (fcntl O_NONBLOCK)
  int flags = 0;
  flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

struct Endpoint {
  std::vector<int> peers;  // server: fd per client rank; client: single fd
  int listen_fd = -1;
  bool is_server = false;
};

}  // namespace

extern "C" {

// errno of the most recent failed create on this thread, for diagnostics
// (a bare null handle told callers nothing about WHY the bind failed)
static thread_local int g_last_errno = 0;

int ft_last_errno() { return g_last_errno; }

// ---- server ----------------------------------------------------------------

// Create a listening endpoint on port; returns handle (>0 pointer) or null.
void* ft_server_create(int port) {
  g_last_errno = 0;  // never report a stale, unrelated failure
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    g_last_errno = errno;
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    g_last_errno = errno;
    close(fd);
    return nullptr;
  }
  auto* ep = new Endpoint();
  ep->listen_fd = fd;
  ep->is_server = true;
  return ep;
}

// Accept n clients; each must send a 4-byte rank (1..n) right after connect.
// Returns 0 or a negative error.
int ft_server_accept(void* handle, int n_clients, int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(handle);
  if (!ep || !ep->is_server || n_clients <= 0) return kErrArg;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  ep->peers.assign(static_cast<size_t>(n_clients), -1);
  int connected = 0;
  set_common_opts(ep->listen_fd);
  while (connected < n_clients) {
    int rc = wait_fd(ep->listen_fd, POLLIN, deadline);
    if (rc < 0) return rc;
    int cfd = accept(ep->listen_fd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) continue;
      return kErrSocket;
    }
    set_common_opts(cfd);
    uint32_t rank_le = 0;
    rc = recv_all(cfd, reinterpret_cast<uint8_t*>(&rank_le), 4, deadline);
    if (rc < 0) {
      close(cfd);
      return rc;
    }
    uint32_t rank = le32toh(rank_le);
    if (rank < 1 || rank > static_cast<uint32_t>(n_clients) ||
        ep->peers[rank - 1] != -1) {
      close(cfd);
      return kErrArg;  // duplicate or out-of-range rank
    }
    ep->peers[rank - 1] = cfd;
    ++connected;
  }
  return 0;
}

// Accept ONE (re)connecting client if a connection lands within timeout_ms.
// The client announces its 4-byte rank exactly like the initial rendezvous;
// any existing fd for that rank is closed and replaced, so a client that
// lost its connection can rejoin mid-protocol.  Returns the rank (>= 1),
// 0 if nothing arrived before the deadline, or a negative error.
int ft_server_poll_accept(void* handle, int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(handle);
  if (!ep || !ep->is_server || ep->peers.empty()) return kErrArg;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  int rc = wait_fd(ep->listen_fd, POLLIN, deadline);
  if (rc == kErrTimeout) return 0;
  if (rc < 0) return rc;
  int cfd = accept(ep->listen_fd, nullptr, nullptr);
  if (cfd < 0) {
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    return kErrSocket;
  }
  set_common_opts(cfd);
  uint32_t rank_le = 0;
  // the rank announcement is 4 bytes from an already-connected peer; give
  // it a short fixed budget so a half-open connection can't wedge us
  rc = recv_all(cfd, reinterpret_cast<uint8_t*>(&rank_le), 4,
                now_ms() + 5000);
  if (rc < 0) {
    close(cfd);
    return rc;
  }
  uint32_t rank = le32toh(rank_le);
  if (rank < 1 || rank > ep->peers.size()) {
    close(cfd);
    return kErrArg;
  }
  if (ep->peers[rank - 1] >= 0) close(ep->peers[rank - 1]);
  ep->peers[rank - 1] = cfd;
  return static_cast<int>(rank);
}

// Close the connection to one peer (server: 1-based rank; client: 0) while
// keeping the endpoint alive — marks a dropped client, and lets the
// fault-injection harness sever a live connection to exercise reconnect.
int ft_peer_close(void* handle, int peer) {
  auto* ep = static_cast<Endpoint*>(handle);
  if (!ep) return kErrArg;
  size_t idx;
  if (ep->is_server) {
    if (peer < 1 || static_cast<size_t>(peer) > ep->peers.size())
      return kErrArg;
    idx = static_cast<size_t>(peer - 1);
  } else {
    if (ep->peers.empty()) return kErrArg;
    idx = 0;
  }
  if (ep->peers[idx] >= 0) {
    close(ep->peers[idx]);
    ep->peers[idx] = -1;
  }
  return 0;
}

// ---- client ----------------------------------------------------------------

// Connect to host:port and announce rank (1-based); retries until deadline
// so client and server start order doesn't matter (the reference's
// rendezvous behavior).
void* ft_client_create(const char* host, int port, int rank, int timeout_ms) {
  g_last_errno = 0;  // never report a stale, unrelated failure
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    g_last_errno = EINVAL;  // host is not a numeric IPv4 address
    return nullptr;
  }

  while (true) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
      g_last_errno = errno;
      return nullptr;
    }
    set_common_opts(fd);  // O_NONBLOCK first so connect honors the deadline
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    bool ok = rc == 0;
    if (!ok && errno == EINPROGRESS) {
      if (wait_fd(fd, POLLOUT, deadline) == 0) {
        int err = 0;
        socklen_t len = sizeof(err);
        ok = getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) == 0 && err == 0;
        if (!ok && err) errno = err;  // surface the real connect failure
      }
    }
    if (ok) {
      uint32_t rank_le = htole32(static_cast<uint32_t>(rank));
      if (send_all(fd, reinterpret_cast<uint8_t*>(&rank_le), 4, deadline) != 0) {
        g_last_errno = errno ? errno : EPIPE;
        close(fd);
        return nullptr;
      }
      auto* ep = new Endpoint();
      ep->peers.push_back(fd);
      return ep;
    }
    int connect_errno = errno;
    close(fd);
    if (deadline >= 0 && now_ms() >= deadline) {
      // EINPROGRESS means the final nonblocking connect was still pending
      // when the rendezvous deadline hit — report the timeout, not it
      g_last_errno = (connect_errno && connect_errno != EINPROGRESS)
                         ? connect_errno
                         : ETIMEDOUT;
      return nullptr;
    }
    usleep(100 * 1000);  // retry rendezvous every 100 ms
  }
}

// ---- messaging -------------------------------------------------------------

static int peer_fd(Endpoint* ep, int peer) {
  if (!ep) return -1;
  if (ep->is_server) {
    if (peer < 1 || static_cast<size_t>(peer) > ep->peers.size()) return -1;
    return ep->peers[static_cast<size_t>(peer - 1)];
  }
  return ep->peers.empty() ? -1 : ep->peers[0];
}

// Send one framed message to peer (server: 1-based client rank; client: 0).
int ft_send(void* handle, int peer, const uint8_t* buf, uint64_t len,
            int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(handle);
  int fd = peer_fd(ep, peer);
  if (fd < 0) return kErrArg;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  uint64_t len_le = htole64(len);
  int rc = send_all(fd, reinterpret_cast<uint8_t*>(&len_le), 8, deadline);
  if (rc < 0) return rc;
  return send_all(fd, buf, len, deadline);
}

// Receive one framed message from peer. *out is malloc'd (caller frees via
// ft_free); *out_len receives the payload size.  Returns 0 or negative error.
int ft_recv(void* handle, int peer, uint8_t** out, uint64_t* out_len,
            int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(handle);
  int fd = peer_fd(ep, peer);
  if (fd < 0 || !out || !out_len) return kErrArg;
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  uint64_t len_le = 0;
  int rc = recv_all(fd, reinterpret_cast<uint8_t*>(&len_le), 8, deadline);
  if (rc < 0) return rc;
  uint64_t len = le64toh(len_le);
  uint8_t* buf = static_cast<uint8_t*>(malloc(len ? len : 1));
  if (!buf) return kErrSocket;
  rc = recv_all(fd, buf, len, deadline);
  if (rc < 0) {
    free(buf);
    return rc;
  }
  *out = buf;
  *out_len = len;
  return 0;
}

// Poll a peer for readability WITHOUT consuming bytes: the Python layer
// slices its waits to service heartbeats/reconnects, and consuming a
// partial frame on a slice timeout would corrupt the stream.  Returns 1
// (readable/EOF), 0 (nothing within timeout), or a negative error.
int ft_poll(void* handle, int peer, int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(handle);
  int fd = peer_fd(ep, peer);
  if (fd < 0) return kErrArg;
  int rc = wait_fd(fd, POLLIN, timeout_ms < 0 ? -1 : now_ms() + timeout_ms);
  if (rc == kErrTimeout) return 0;
  if (rc < 0) return rc;
  return 1;
}

void ft_free(uint8_t* buf) { free(buf); }

int ft_n_peers(void* handle) {
  auto* ep = static_cast<Endpoint*>(handle);
  return ep ? static_cast<int>(ep->peers.size()) : 0;
}

void ft_close(void* handle) {
  auto* ep = static_cast<Endpoint*>(handle);
  if (!ep) return;
  for (int fd : ep->peers)
    if (fd >= 0) close(fd);
  if (ep->listen_fd >= 0) close(ep->listen_fd);
  delete ep;
}

}  // extern "C"
