"""Fast CSV snapshot writing.

The reference writes a 40k-row synthetic CSV every epoch with pandas
``to_csv`` (reference Server/dtds/distributed.py:589-590) — which costs ~1 s
per snapshot and would dominate a TPU training round that itself takes a
fraction of that.  ``write_csv`` routes through pyarrow's multithreaded
writer (~7x faster) whenever the frame is representable, falling back to
pandas for anything pyarrow would format differently (timestamps, mixed
object columns from missing-value tokens).

Formatting notes: pyarrow quotes strings and headers where pandas does not,
and both emit shortest-round-trip float reprs — ``pd.read_csv`` parses
either output to identical values, which is what the evaluation suite (and
the reference's own offline scripts) consume.
"""

from __future__ import annotations

import pandas as pd


def _arrow_friendly(df: pd.DataFrame) -> bool:
    if df.shape[1] == 1:
        # arrow writes a null in a one-column frame as a blank line, which
        # pd.read_csv(skip_blank_lines=True) drops — rows would vanish
        return False
    for name in df.columns:
        col = df[name]
        if str(col.dtype).startswith(("datetime", "timedelta")):
            return False  # pandas formats these as bare dates; arrow differs
        if col.dtype == object:
            kinds = {type(v) for v in col.iloc[: min(len(col), 64)]}
            if not kinds <= {str}:
                return False  # mixed float/'empty' etc.: keep pandas repr
    return True


def write_table_csv(table, path: str) -> None:
    """Write a ``pyarrow.Table`` (from ``decode_to_table``) to CSV.

    ``quoting_style="needed"`` matches the pandas convention (strings
    unquoted unless they contain separators) and measures ~12% faster than
    arrow's quote-everything default on the reference's 40k x 42 snapshot;
    older pyarrow without the option falls back to the default quoting —
    both parse identically under ``pd.read_csv``.
    """
    import pyarrow.csv as pacsv

    try:
        opts = pacsv.WriteOptions(quoting_style="needed")
    except (TypeError, ValueError):  # pyarrow too old for quoting_style
        pacsv.write_csv(table, path)
        return
    pacsv.write_csv(table, path, write_options=opts)


def write_csv(df: pd.DataFrame, path: str) -> None:
    """Write ``df`` to ``path`` (no index), fast path when possible."""
    try:
        import pyarrow as pa
        import pyarrow.csv as pacsv
    except ImportError:
        df.to_csv(path, index=False)
        return
    if not _arrow_friendly(df):
        df.to_csv(path, index=False)
        return
    try:
        table = pa.Table.from_pandas(df, preserve_index=False)
    except (pa.ArrowInvalid, pa.ArrowTypeError):
        df.to_csv(path, index=False)
        return
    pacsv.write_csv(table, path)


def csv_segments(df: pd.DataFrame):
    """``csv_bytes`` split into ``(header_line, [row_line, ...])``.

    Every segment keeps its line terminator, so ``header + b"".join(rows)``
    reconstructs :func:`csv_bytes` exactly — the serving row pool stores the
    per-row segments and streams arbitrary contiguous slices of them without
    re-serializing.  Raises :class:`ValueError` when the frame's rows are not
    line-splittable (a quoted cell containing a newline would make row slices
    ambiguous); callers fall back to the per-request serialize path.
    """
    blob = csv_bytes(df)
    parts = blob.splitlines(keepends=True)
    if len(parts) != len(df) + 1:
        raise ValueError(
            f"frame is not row-sliceable: {len(df)} rows split into "
            f"{len(parts) - 1} CSV lines (embedded newline in a cell?)")
    return parts[0], parts[1:]


def csv_bytes(df: pd.DataFrame) -> bytes:
    """``write_csv``'s exact output as bytes (same routing, same writer).

    The serving layer returns these directly, so a served response is
    byte-identical to the file the one-shot ``--sample-from`` path writes
    for the same frame."""
    import io

    try:
        import pyarrow as pa
        import pyarrow.csv as pacsv
    except ImportError:
        return df.to_csv(index=False).encode()
    if not _arrow_friendly(df):
        return df.to_csv(index=False).encode()
    try:
        table = pa.Table.from_pandas(df, preserve_index=False)
    except (pa.ArrowInvalid, pa.ArrowTypeError):
        return df.to_csv(index=False).encode()
    buf = io.BytesIO()
    pacsv.write_csv(table, buf)
    return buf.getvalue()
