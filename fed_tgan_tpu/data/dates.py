"""Date column splitting and rejoining.

Behavioral equivalent of the reference's ``Date`` utility
(reference Server/dtds/data/utils/date.py:14-200): a date column declared as
e.g. ``{"date": "yymmdd|YYYY-MM-DD"}`` is parsed and split into categorical
part-columns (``date-year``, ``date-month``, ...); on inverse, parts are
rejoined and impossible day-of-month values are clamped.

Deviations from the reference (documented, intentional):
- leap years use the correct Gregorian rule (the reference requires
  ``y%4==0 and y%100==0 and y%400==0`` at date.py:166-170, which mislabels
  ordinary leap years such as 2024);
- vectorized pandas ops instead of per-row Python loops.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from fed_tgan_tpu.data.constants import MISSING_TOKEN

# part-name suffix per format token (reference date.py:78)
_PART_SUFFIX = {
    "YYYY": "-year",
    "MM": "-month",
    "DD": "-day",
    "hh": "-hour",
    "mm": "-minute",
    "ss": "-second",
}
_PART_STRFTIME = {
    "YYYY": "%y",  # reference emits 2-digit years for YYYY (date.py:84-86)
    "MM": "%m",
    "DD": "%d",
    "hh": "%H",
    "mm": "%M",
    "ss": "%S",
}

_DAYS_IN_MONTH = {1: 31, 2: 28, 3: 31, 4: 30, 5: 31, 6: 30, 7: 31, 8: 31, 9: 30, 10: 31, 11: 30, 12: 31}


def _parse_format(fmt: str) -> tuple[str | None, str]:
    """Split ``"origin|PARTS"`` into (origin_format, part_format)."""
    pieces = fmt.split("|")
    if len(pieces) == 2:
        return pieces[0], pieces[1]
    return None, pieces[0]


def part_columns(column: str, fmt: str) -> list[str]:
    _, d_format = _parse_format(fmt)
    return [column + _PART_SUFFIX[tok] for tok in d_format.split("-")]


def split_date_columns(
    df: pd.DataFrame, date_formats: dict[str, str], categorical_list: list[str]
) -> pd.DataFrame:
    """Replace each declared date column by categorical part-columns.

    ``categorical_list`` is edited in place the same way the reference does
    (date column removed, part columns appended; date.py:28,113).
    """
    df = df.copy()
    for column, fmt in date_formats.items():
        if column in categorical_list:
            categorical_list.remove(column)
        o_format, d_format = _parse_format(fmt)

        raw = df[column]
        missing = raw.astype(str).eq(MISSING_TOKEN) | raw.isna()
        if o_format == "yymmdd":
            # numeric yymmdd stamps; floats appear when the column had NaNs.
            # Zero-pad and parse with an explicit format — years 2000-2009
            # lose their leading zero through the int cast.
            parseable = raw[~missing].astype(float).astype(int).astype(str).str.zfill(6)
            parsed = pd.to_datetime(parseable, format="%y%m%d")
        else:
            parsed = pd.to_datetime(raw[~missing].astype(str))

        for tok in d_format.split("-"):
            part = column + _PART_SUFFIX[tok]
            out = pd.Series(MISSING_TOKEN, index=df.index, dtype=object)
            out.loc[~missing] = parsed.dt.strftime(_PART_STRFTIME[tok])
            df[part] = out
            categorical_list.append(part)

        df = df.drop(columns=[column])
    return df


def _is_leap(year: np.ndarray) -> np.ndarray:
    return ((year % 4 == 0) & (year % 100 != 0)) | (year % 400 == 0)


def join_date_columns(df: pd.DataFrame, date_formats: dict[str, str]) -> pd.DataFrame:
    """Rejoin part-columns into the original date column, clamping bad days.

    Mirrors reference date.py:119-200: a row is "empty" if any part is empty;
    day-of-month beyond the month's maximum is clamped (Feb respecting leap
    years, other overlong days to 30 like the reference).
    """
    df = df.copy()
    for column, fmt in date_formats.items():
        o_format, d_format = _parse_format(fmt)
        parts = [column + _PART_SUFFIX[tok] for tok in d_format.split("-")]
        part_vals = df[parts].astype(str)

        missing = part_vals.apply(lambda s: s.str.contains(MISSING_TOKEN)).any(axis=1)
        # object dtype: the column ends up holding Timestamps or ints plus
        # the missing token (pandas 3 string dtype would reject those)
        joined = part_vals.apply(lambda row: "-".join(row), axis=1).astype(object)

        if {"-year", "-month", "-day"} <= {s[len(column):] for s in parts}:
            ok = ~missing
            pieces = part_vals.loc[ok]
            year = pieces[column + "-year"].astype(int).to_numpy()
            month = pieces[column + "-month"].astype(int).to_numpy()
            day = pieces[column + "-day"].astype(int).to_numpy()
            max_day = np.array([_DAYS_IN_MONTH[m] for m in month])
            max_day = np.where((month == 2) & _is_leap(2000 + year % 100), 29, max_day)
            # reference clamps non-February overruns to 30 (date.py:175)
            clamped = np.where(day > max_day, np.where(month == 2, max_day, 30), day)
            fixed = [
                "-".join([y, m, f"{d:02d}"])
                for y, m, d in zip(
                    pieces[column + "-year"], pieces[column + "-month"], clamped
                )
            ]
            joined.loc[ok] = fixed

        joined.loc[missing] = MISSING_TOKEN

        ok = ~missing
        stamped = pd.to_datetime(joined.loc[ok], format="%y-%m-%d")
        if o_format == "yymmdd":
            joined.loc[ok] = stamped.dt.strftime("%y%m%d").astype(int)
        else:
            # reference restores full datetimes (date.py:190), so the output
            # CSV carries e.g. '2023-01-31', matching the raw column format
            joined.loc[ok] = stamped
        df[column] = joined
        df = df.drop(columns=parts)
    return df
