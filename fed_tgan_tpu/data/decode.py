"""Decoding synthetic matrices back to raw-format dataframes/CSV.

Behavioral equivalent of the reference ``Transform.inverse``
(reference Server/dtds/data/utils/transform.py:12-69) with the optional
integer casting of ``decode_train_data``
(reference Server/dtds/features/transformers.py:629-699):

- categorical codes -> original category values via the global encoders;
- non-negative columns: ``exp(x) - 1`` (ceil when negative), ``-1`` -> 'empty';
- date part-columns rejoined; 'empty' -> ' '.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import pandas as pd

from fed_tgan_tpu.data.constants import MISSING_CONTINUOUS, MISSING_TOKEN
from fed_tgan_tpu.data.dates import join_date_columns
from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.schema import TableMeta


def decode_matrix(
    data: np.ndarray,
    meta: TableMeta,
    encoders: Sequence[CategoryEncoder],
    round_integers: bool = False,
) -> pd.DataFrame:
    """Decode a synthesized (or encoded-real) matrix to raw values.

    ``round_integers=False`` reproduces the reference's federated sampling
    path (Transform.inverse leaves integer continuous columns as floats);
    ``True`` additionally casts integer columns like decode_train_data does.
    """
    df = pd.DataFrame(np.asarray(data), columns=meta.column_names)

    cat_names = meta.categorical_columns
    assert len(cat_names) == len(encoders), (len(cat_names), len(encoders))
    for name, enc in zip(cat_names, encoders):
        df[name] = enc.inverse_transform(df[name].to_numpy().astype(int))

    cont_names = set(meta.continuous_columns)
    for name in df.columns:
        if name in meta.non_negative_columns:
            x = np.exp(df[name].astype(float).to_numpy()) - 1.0
            x = np.where(x < 0, np.ceil(x), x)
            if (x == -1).any():
                vals = pd.Series(x, index=df.index, dtype=object)
                vals[x == -1] = MISSING_TOKEN
                df[name] = vals
            else:
                # keep the numeric dtype: identical CSV output, and the
                # frame stays on the fast (pyarrow) snapshot-writer path
                df[name] = x
        elif name in cont_names:
            x = df[name].astype(float).to_numpy()
            if (x == MISSING_CONTINUOUS).any():
                vals = pd.Series(x, index=df.index, dtype=object)
                vals[x == MISSING_CONTINUOUS] = MISSING_TOKEN
                df[name] = vals

    if meta.date_info:
        df = join_date_columns(df, meta.date_info)

    df = df.replace(MISSING_TOKEN, " ")

    if round_integers:
        for name in meta.integer_columns:
            if name in df.columns:
                df[name] = df[name].apply(
                    lambda x: int(float(x)) if x != " " else " "
                )
    return df
