"""Decoding synthetic matrices back to raw-format dataframes/CSV.

Behavioral equivalent of the reference ``Transform.inverse``
(reference Server/dtds/data/utils/transform.py:12-69) with the optional
integer casting of ``decode_train_data``
(reference Server/dtds/features/transformers.py:629-699):

- categorical codes -> original category values via the global encoders;
- non-negative columns: ``exp(x) - 1`` (ceil when negative), ``-1`` -> 'empty';
- date part-columns rejoined; 'empty' -> ' '.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import pandas as pd

from fed_tgan_tpu.data.constants import MISSING_CONTINUOUS, MISSING_TOKEN
from fed_tgan_tpu.data.dates import join_date_columns
from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.schema import TableMeta


def decode_to_table(
    data: np.ndarray,
    meta: TableMeta,
    encoders: Sequence[CategoryEncoder],
):
    """Decode a synthesized matrix straight to a ``pyarrow.Table``, or return
    ``None`` when the exact pandas path (`decode_matrix`) must run instead.

    Same math as ``decode_matrix`` for the cases it accepts; the win is
    representational: categorical columns become ``DictionaryArray``s built
    from the integer codes the matrix already holds (no 40k-row object-array
    of Python strings is ever materialized — the reference's decode loop and
    our own pandas path both pay that, reference
    Server/dtds/data/utils/transform.py:12-69).  On the snapshot writer
    thread this cuts the per-snapshot decode from ~120 ms to ~10 ms at the
    reference's 40k-row size.

    Returns ``None`` (caller falls back to ``decode_matrix``) when:
    pyarrow is unavailable; the meta has date columns to rejoin; or any
    missing-value sentinel is present (those need mixed-type object columns).
    """
    try:
        import pyarrow as pa
    except ImportError:
        return None
    if meta.date_info:
        return None
    data = np.asarray(data)
    cat_names = meta.categorical_columns
    assert len(cat_names) == len(encoders), (len(cat_names), len(encoders))
    enc_by_name = dict(zip(cat_names, encoders))
    cont_names = set(meta.continuous_columns)
    nonneg = set(meta.non_negative_columns)

    arrays: dict = {}
    for i, name in enumerate(meta.column_names):
        x = data[:, i]
        if name in enc_by_name:
            enc = enc_by_name[name]
            classes = enc.classes_
            codes = enc.validate_codes(x).astype(np.int32)
            # the missing token decodes to ' ' (decode_matrix's mapping) —
            # applied on the small dictionary, never on the 40k rows
            cats = [" " if c == MISSING_TOKEN else str(c) for c in classes]
            arrays[name] = pa.DictionaryArray.from_arrays(
                pa.array(codes), pa.array(cats, type=pa.string())
            )
        elif name in nonneg:
            y = np.exp(x.astype(float)) - 1.0
            y = np.where(y < 0, np.ceil(y), y)
            if (y == -1).any():
                return None  # missing values -> mixed-type column
            arrays[name] = pa.array(y)
        elif name in cont_names:
            y = x.astype(float)
            if (y == MISSING_CONTINUOUS).any():
                return None
            arrays[name] = pa.array(y)
        else:
            arrays[name] = pa.array(x)
    return pa.table(arrays)


def table_to_frame(table) -> pd.DataFrame:
    """``decode_to_table`` output -> the DataFrame ``decode_matrix`` would
    have produced (dictionary columns densified to plain object-dtype
    strings).  Used once at drain time, not per snapshot."""
    import pyarrow as pa

    cols = {}
    for name in table.column_names:
        col = table.column(name)
        if pa.types.is_dictionary(col.type):
            col = col.cast(pa.string())
        try:
            vals = col.to_numpy(zero_copy_only=False)
        except TypeError:  # pyarrow < 13: ChunkedArray.to_numpy lacks the kwarg
            vals = col.to_numpy()
        if vals.dtype.kind in ("U", "S"):
            vals = vals.astype(object)
        cols[name] = vals
    return pd.DataFrame(cols, columns=list(table.column_names))


def decode_and_write_csv(
    data: np.ndarray,
    meta: TableMeta,
    encoders: Sequence[CategoryEncoder],
    path: str,
):
    """Decode one synthesized matrix and write its snapshot CSV.

    The single entry point both snapshot writers (train.snapshots
    SnapshotWriter and the multihost receiver) share: arrow-direct fast
    path when eligible, exact pandas path otherwise.  Returns the decoded
    representation (``pyarrow.Table`` or ``DataFrame`` — normalize with
    ``table_to_frame`` when a frame is required).
    """
    from fed_tgan_tpu.data.csvio import write_csv, write_table_csv

    table = decode_to_table(data, meta, encoders)
    if table is None:
        raw = decode_matrix(data, meta, encoders)
        write_csv(raw, path)
        return raw
    write_table_csv(table, path)
    return table


def decode_matrix(
    data: np.ndarray,
    meta: TableMeta,
    encoders: Sequence[CategoryEncoder],
    round_integers: bool = False,
) -> pd.DataFrame:
    """Decode a synthesized (or encoded-real) matrix to raw values.

    ``round_integers=False`` reproduces the reference's federated sampling
    path (Transform.inverse leaves integer continuous columns as floats);
    ``True`` additionally casts integer columns like decode_train_data does.
    """
    data = np.asarray(data)
    cat_names = meta.categorical_columns
    assert len(cat_names) == len(encoders), (len(cat_names), len(encoders))
    enc_by_name = dict(zip(cat_names, encoders))
    cont_names = set(meta.continuous_columns)
    nonneg = set(meta.non_negative_columns)

    # build every column first, then construct the frame ONCE — incremental
    # df[name] = ... assignments dominate decode wall-clock (pandas
    # sanitizes/re-blocks per column)
    date_parts: set = set()
    if meta.date_info:
        from fed_tgan_tpu.data.dates import part_columns

        for column, fmt in meta.date_info.items():
            date_parts.update(part_columns(column, fmt))

    cols: dict[str, np.ndarray] = {}
    for i, name in enumerate(meta.column_names):
        x = data[:, i]
        if name in enc_by_name:
            vals = enc_by_name[name].inverse_transform(x.astype(int))
            # decoded categories may hold the missing token -> ' '; date
            # part columns keep it — join_date_columns detects missing rows
            # by the token, and the post-join replace maps the leftovers
            if name not in date_parts and (vals == MISSING_TOKEN).any():
                vals = vals.copy()
                vals[vals == MISSING_TOKEN] = " "
            cols[name] = vals
        elif name in nonneg:
            y = np.exp(x.astype(float)) - 1.0
            y = np.where(y < 0, np.ceil(y), y)
            if (y == -1).any():
                vals = y.astype(object)
                vals[y == -1] = " "
                cols[name] = vals
            else:
                # keep the numeric dtype: identical CSV output, and the
                # frame stays on the fast (pyarrow) snapshot-writer path
                cols[name] = y
        elif name in cont_names:
            y = x.astype(float)
            if (y == MISSING_CONTINUOUS).any():
                vals = y.astype(object)
                vals[y == MISSING_CONTINUOUS] = " "
                cols[name] = vals
            else:
                cols[name] = y
        else:
            cols[name] = x

    df = pd.DataFrame(cols, columns=meta.column_names)

    if meta.date_info:
        df = join_date_columns(df, meta.date_info)
        # date rejoin may surface the missing token for empty part rows
        df = df.replace(MISSING_TOKEN, " ")

    if round_integers:
        for name in meta.integer_columns:
            if name in df.columns:
                df[name] = df[name].apply(
                    lambda x: int(float(x)) if x != " " else " "
                )
    return df
