"""Categorical label encoding.

Functional equivalent of the sklearn ``LabelEncoder`` objects the reference
passes around over RPC (reference Server/dtds/distributed.py:622-624,
Server/dtds/data/utils/file_generator.py:166): classes are the *sorted*
unique values, codes are positions in that sorted order.  Implemented on
numpy directly so encoders are cheap to serialize and need no sklearn at
decode time.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class CategoryEncoder:
    """Maps category values <-> integer codes, sklearn-LabelEncoder-compatible."""

    classes_: np.ndarray = field(default_factory=lambda: np.empty(0, dtype=object))

    @classmethod
    def fit(cls, values) -> "CategoryEncoder":
        arr = np.asarray(list(values), dtype=object)
        # np.unique on object arrays matches sklearn's sorted-class semantics.
        return cls(classes_=np.unique(arr))

    def transform(self, values) -> np.ndarray:
        arr = np.asarray(list(values), dtype=object)
        codes = np.searchsorted(self.classes_, arr)
        codes = np.clip(codes, 0, len(self.classes_) - 1)
        if not np.array_equal(self.classes_[codes], arr):
            unknown = sorted({v for v in arr.tolist() if v not in set(self.classes_.tolist())})
            raise ValueError(f"unknown categories: {unknown[:10]}")
        return codes.astype(np.int64)

    def validate_codes(self, codes) -> np.ndarray:
        """Range-checked int64 codes, without materializing the category
        values — the shared gate for every decode path (an int32 cast before
        the check could wrap an out-of-range float into the valid range)."""
        codes = np.asarray(codes, dtype=np.int64)
        if codes.size and (codes.min() < 0 or codes.max() >= len(self.classes_)):
            raise ValueError("category code out of range")
        return codes

    def inverse_transform(self, codes) -> np.ndarray:
        return self.classes_[self.validate_codes(codes)]

    def __len__(self) -> int:
        return len(self.classes_)

    def to_dict(self) -> dict:
        return {"classes": self.classes_.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "CategoryEncoder":
        return cls(classes_=np.asarray(d["classes"], dtype=object))


def encoder_artifact(column_names, encoders) -> list[dict]:
    """The on-disk label-encoder layout every writer shares:
    ``[{"column_name": c, "label_encoder": e}, ...]`` (the reference pickles
    the same shape, Server/dtds/distributed.py:679-681)."""
    return [
        {"column_name": c, "label_encoder": e}
        for c, e in zip(column_names, encoders)
    ]
