"""CSV ingestion and per-client preprocessing.

Behavioral equivalent of the reference ``FileGenerator``
(reference Server/dtds/data/utils/file_generator.py:65-188) and the
``prepare_data`` / ``encode_data_with_meta_labelencoder`` wrappers
(reference Server/dtds/data/load.py:51-90), without the npz/json round-trip
through disk: preprocessing produces the local meta dict and, once global
encoders exist, a dense numpy matrix ready for the feature transformer.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np
import pandas as pd

from fed_tgan_tpu.data.constants import (
    MISSING_CONTINUOUS,
    MISSING_TOKEN,
)
from fed_tgan_tpu.data.dates import split_date_columns
from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.schema import TableMeta


def infer_integer_columns(df: pd.DataFrame) -> list[str]:
    """Columns whose non-null values are all integral.

    Mirrors reference file_generator.py:104-110 (int dtype, or float dtype
    whose non-null values equal their int cast).
    """
    out = []
    for name in df.columns:
        col = df[name].dropna()
        dtype = str(col.dtype)
        if "int" in dtype:
            out.append(name)
        elif "float" in dtype and np.array_equal(col.to_numpy(), col.to_numpy().astype(int)):
            out.append(name)
    return out


@dataclass
class TablePreprocessor:
    """Holds one participant's preprocessed dataframe.

    Preprocessing pipeline (same order as reference file_generator.py:103-133):
    1. integer-column inference on the raw frame;
    2. blank cells -> NaN -> the ``'empty'`` token;
    3. ``log(x+1)`` on non-negative continuous columns;
    4. date columns split into categorical part-columns.
    """

    frame: pd.DataFrame
    name: str = "table"
    categorical_columns: list = field(default_factory=list)
    non_negative_columns: list = field(default_factory=list)
    date_formats: dict = field(default_factory=dict)
    target_column: str = ""
    problem_type: str = ""
    selected_columns: Optional[Sequence[str]] = None

    def __post_init__(self):
        df = self.frame
        if self.selected_columns is not None:
            df = df[list(self.selected_columns)]
        df = df.copy()

        self.categorical_columns = list(self.categorical_columns)
        self.integer_columns = infer_integer_columns(df)

        df = df.replace(r" ", np.nan).fillna(MISSING_TOKEN)

        exempt = set(self.categorical_columns) | set(self.date_formats.keys())
        for col in df.columns:
            if col in exempt:
                continue
            missing = df[col].astype(str).eq(MISSING_TOKEN)
            if not missing.any() and col not in self.non_negative_columns:
                continue
            # errors="raise": only genuinely-missing cells may become the
            # sentinel; stray tokens like '?' must fail loudly.
            vals = pd.to_numeric(df[col].where(~missing), errors="raise")
            if col in self.non_negative_columns:
                vals = np.log(vals + 1.0)
            vals = vals.fillna(MISSING_CONTINUOUS)
            df[col] = vals.astype(float)

        if self.date_formats:
            self.categorical_columns.extend(self.date_formats.keys())
            df = split_date_columns(df, self.date_formats, self.categorical_columns)

        self.df = df

    @classmethod
    def from_csv(cls, path: str, **kwargs) -> "TablePreprocessor":
        name = str(path).rsplit("/", 1)[-1].rsplit(".", 1)[0]
        return cls(frame=pd.read_csv(path), name=kwargs.pop("name", name), **kwargs)

    @property
    def n_rows(self) -> int:
        return len(self.df)

    def local_meta(self) -> dict:
        """Per-client meta with categorical frequency dicts.

        Equivalent of reference ``FileGenerator.generate_meta_data``
        (file_generator.py:191-231); the frequency dicts are what the server
        merges during category harmonization.
        """
        columns = []
        for idx, col in enumerate(self.df.columns):
            entry: dict = {"column_name": col, "column no": idx}
            if col in self.categorical_columns:
                counts = self.df[col].astype(str).value_counts()
                entry["type"] = "categorical"
                entry["size"] = len(counts)
                entry["i2s"] = {str(k): int(v) for k, v in counts.items()}
            else:
                entry["type"] = "continous"  # reference spelling
                vals = self.df[col].to_numpy(dtype=float)
                present = vals[vals != MISSING_CONTINUOUS]
                if present.size == 0:
                    present = vals
                entry["min"] = float(np.min(present))
                entry["max"] = float(np.max(present))
            columns.append(entry)
        meta = {
            "columns": columns,
            "problem_type": self.problem_type,
            "name": self.name,
            "date_info": dict(self.date_formats),
            "integer_info": list(self.integer_columns),
            "non_negative_cols": list(self.non_negative_columns),
        }
        if self.target_column:
            meta["target"] = self.target_column
        return meta

    def encode(
        self, encoders: Sequence[CategoryEncoder]
    ) -> tuple[np.ndarray, list[int], list[int]]:
        """Label-encode categorical columns with the *global* encoders.

        Equivalent of reference ``FileGenerator.generate_data`` +
        ``load_datapath`` (file_generator.py:156-188, load.py:38-48) minus the
        disk round-trip.  Returns (matrix, categorical_idx, ordinal_idx).
        """
        df = self.df.copy()
        cursor = 0
        cat_idx = []
        for idx, col in enumerate(df.columns):
            if col in self.categorical_columns:
                df[col] = encoders[cursor].transform(df[col].astype(str))
                cursor += 1
                cat_idx.append(idx)
        matrix = df.to_numpy(dtype=np.float64)
        return matrix, cat_idx, []

    def global_table_meta(self, harmonized_meta: dict) -> TableMeta:
        """Wrap a server-harmonized meta dict into a ``TableMeta``."""
        return TableMeta.from_json_dict(harmonized_meta)

    def write_artifacts(
        self,
        encoders: Sequence[CategoryEncoder],
        meta: dict,
        out_dir: str,
        timestamp: Optional[str] = None,
    ) -> str:
        """Persist the encoded-dataset artifact trio to disk.

        Equivalent of reference ``FileGenerator.generate_data`` +
        ``save_synthesizer_model_and_label_encoders``
        (file_generator.py:156-189, :249-265): one directory
        ``<out_dir>/<name>-<timestamp>/`` holding the meta JSON, the encoded
        matrix as ``.npz`` (key ``train``; empty ``test``, matching the
        ratio=1 reference behavior) and ``.csv``, plus the fitted label
        encoders pickled next to them.  Returns the directory path.
        """
        import json
        import pickle
        import time as _time

        if timestamp is None:
            timestamp = str(_time.time()).replace(".", "")
        run = f"{self.name}-{timestamp}"
        path = os.path.join(out_dir, run)
        os.makedirs(path, exist_ok=True)

        with open(os.path.join(path, f"{run}.json"), "w") as f:
            json.dump(meta, f, sort_keys=True, indent=4, separators=(",", ": "))

        matrix, _, _ = self.encode(encoders)
        np.savez(
            os.path.join(path, f"{run}.npz"),
            train=matrix,
            test=matrix[:0],
        )
        pd.DataFrame(matrix, columns=self.df.columns.tolist()).to_csv(
            os.path.join(path, f"{run}.csv"), index=False
        )
        from fed_tgan_tpu.data.encoders import encoder_artifact

        cat_cols = [c for c in self.df.columns if c in self.categorical_columns]
        with open(os.path.join(path, f"label_encoders_{self.name}.pickle"), "wb") as f:
            pickle.dump(encoder_artifact(cat_cols, encoders), f)
        return path
