"""Splitting one table into per-client shards.

The reference distributes data physically (each participant owns a private
CSV; reference README.md:15).  In the SPMD design each mesh position along the
``clients`` axis holds one shard, so shard construction is an explicit,
testable step.  Supports IID and non-IID (label-skewed) partitions — the
latter is what makes similarity-weighted aggregation matter.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def shard_indices(
    n_rows: int,
    n_clients: int,
    strategy: str = "iid",
    labels: np.ndarray | None = None,
    alpha: float = 0.5,
    seed: int = 0,
) -> list[np.ndarray]:
    """Partition ``range(n_rows)`` into ``n_clients`` disjoint index sets.

    strategies:
    - ``iid``: shuffled equal split.
    - ``contiguous``: consecutive row blocks (matches manually splitting a CSV).
    - ``label_sorted``: rows sorted by label then block-split — extreme
      label skew.
    - ``dirichlet``: per-label Dirichlet(alpha) allocation across clients —
      tunable non-IID (smaller alpha = more skew).
    """
    rng = np.random.default_rng(seed)
    if strategy == "iid":
        perm = rng.permutation(n_rows)
        return [np.sort(part) for part in np.array_split(perm, n_clients)]
    if strategy == "contiguous":
        return list(np.array_split(np.arange(n_rows), n_clients))
    if labels is None:
        raise ValueError(f"strategy {strategy!r} requires labels")
    labels = np.asarray(labels)
    if strategy == "label_sorted":
        order = np.argsort(labels, kind="stable")
        return [np.sort(part) for part in np.array_split(order, n_clients)]
    if strategy == "dirichlet":
        shards: list[list[int]] = [[] for _ in range(n_clients)]
        for value in np.unique(labels):
            rows = np.flatnonzero(labels == value)
            rng.shuffle(rows)
            probs = rng.dirichlet(np.full(n_clients, alpha))
            cuts = (np.cumsum(probs)[:-1] * len(rows)).astype(int)
            for client, part in enumerate(np.split(rows, cuts)):
                shards[client].extend(part.tolist())
        return [np.sort(np.asarray(s, dtype=np.int64)) for s in shards]
    raise ValueError(f"unknown strategy {strategy!r}")


def shard_dataframe(
    df: pd.DataFrame,
    n_clients: int,
    strategy: str = "iid",
    label_column: str | None = None,
    alpha: float = 0.5,
    seed: int = 0,
) -> list[pd.DataFrame]:
    labels = df[label_column].to_numpy() if label_column else None
    parts = shard_indices(len(df), n_clients, strategy, labels, alpha, seed)
    empty = [i for i, idx in enumerate(parts) if len(idx) == 0]
    if empty:
        # a 0-ROW client can't even fit its feature transformers — fail
        # here with guidance instead of deep inside sklearn (0-step
        # clients with >=1 row are a separate, supported case:
        # TrainConfig.allow_zero_step_clients)
        raise ValueError(
            f"clients {empty} received 0 rows under strategy={strategy!r} "
            f"(alpha={alpha}, seed={seed}); raise alpha, reduce n_clients, "
            "or change the shard seed"
        )
    return [df.iloc[idx].reset_index(drop=True) for idx in parts]
