"""Column-kind constants.

Mirrors the reference's vocabulary (reference Server/dtds/data/constants.py:1-3
and the client-side extra BIMODAL at
Client/distributed_GAN_MDGAN_Client0/dtds/data/constants.py:4).

Note the reference's meta JSON spells the continuous kind "continous" (sic,
reference Server/dtds/data/utils/file_generator.py:212); we accept both
spellings on input and emit the misspelled one for byte-compatibility with
reference tooling.
"""

CATEGORICAL = "categorical"
CONTINUOUS = "continuous"
ORDINAL = "ordinal"
BIMODAL = "bimodal"

# The misspelled kind tag used inside reference meta JSON files.
CONTINUOUS_JSON = "continous"

MISSING_TOKEN = "empty"

# Sentinel for missing values in continuous columns.  The reference's decode
# path documents this convention (Server/dtds/features/transformers.py:671:
# "for -999999 taking np.exp(-999999)-1 gives -1", which maps back to 'empty').
MISSING_CONTINUOUS = -999999.0


def is_continuous_kind(kind: str) -> bool:
    return kind in (CONTINUOUS, CONTINUOUS_JSON)
