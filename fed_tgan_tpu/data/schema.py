"""Table metadata model.

Two flavors exist in the reference and both are supported here:

- *local* (client-side) meta: per-column dicts where categorical ``i2s`` is a
  {category -> count} frequency dict (reference
  Server/dtds/data/utils/file_generator.py:191-231).  Frequency dicts are what
  the server merges during category harmonization.
- *global* (server-side) meta: categorical ``i2s`` is an ordered list (the
  harmonized category order; after label-encoding it is a list of ints) —
  the format of reference Server/models/Intrusion_train.json and of the JSON
  the server writes at Server/dtds/distributed.py:683-684.

``TableMeta`` round-trips the reference JSON byte-compatibly (including the
"continous" spelling).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from fed_tgan_tpu.data.constants import (
    CATEGORICAL,
    CONTINUOUS,
    CONTINUOUS_JSON,
    ORDINAL,
    is_continuous_kind,
)


def _jsonable(obj: Any) -> Any:
    """Convert numpy scalars/arrays to plain Python for json.dump.

    Equivalent in effect to the reference's NumpyEncoder
    (Server/dtds/data/utils/file_generator.py:18-56).
    """
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.bool_):
        return bool(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


@dataclass
class ColumnMeta:
    name: str
    kind: str  # CATEGORICAL / CONTINUOUS / ORDINAL
    index: int
    # categorical: either a frequency dict (local meta) or an ordered list
    # (global meta).  Continuous: None.
    i2s: Optional[Any] = None
    min: Optional[float] = None
    max: Optional[float] = None

    @property
    def size(self) -> Optional[int]:
        if self.i2s is None:
            return None
        return len(self.i2s)

    @property
    def is_continuous(self) -> bool:
        return is_continuous_kind(self.kind)

    def to_json_dict(self) -> dict:
        d: dict = {"column_name": self.name, "column no": self.index}
        if self.kind == CATEGORICAL or self.kind == ORDINAL:
            d["type"] = self.kind
            d["size"] = self.size
            d["i2s"] = _jsonable(self.i2s)
        else:
            d["type"] = CONTINUOUS_JSON  # reference spelling
            d["min"] = _jsonable(self.min)
            d["max"] = _jsonable(self.max)
        return d

    @classmethod
    def from_json_dict(cls, d: dict, index: int) -> "ColumnMeta":
        kind = d["type"]
        if is_continuous_kind(kind):
            return cls(
                name=d["column_name"],
                kind=CONTINUOUS,
                index=d.get("column no", index),
                min=d.get("min"),
                max=d.get("max"),
            )
        return cls(
            name=d["column_name"],
            kind=kind,
            index=d.get("column no", index),
            i2s=d.get("i2s"),
        )


@dataclass
class TableMeta:
    """Full dataset meta (the reference's meta JSON top level)."""

    columns: list[ColumnMeta]
    name: str = ""
    problem_type: str = ""
    target: Optional[str] = None
    date_info: dict = field(default_factory=dict)
    integer_columns: list = field(default_factory=list)
    non_negative_columns: list = field(default_factory=list)

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    @property
    def categorical_columns(self) -> list[str]:
        return [c.name for c in self.columns if c.kind == CATEGORICAL]

    @property
    def continuous_columns(self) -> list[str]:
        return [c.name for c in self.columns if c.is_continuous]

    def categorical_indices(self) -> list[int]:
        return [i for i, c in enumerate(self.columns) if c.kind == CATEGORICAL]

    def ordinal_indices(self) -> list[int]:
        return [i for i, c in enumerate(self.columns) if c.kind == ORDINAL]

    def column(self, name: str) -> ColumnMeta:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)

    def to_json_dict(self) -> dict:
        d = {
            "columns": [c.to_json_dict() for c in self.columns],
            "problem_type": self.problem_type,
            "name": self.name,
            "date_info": _jsonable(self.date_info),
            "integer_info": _jsonable(list(self.integer_columns)),
            "non_negative_cols": _jsonable(list(self.non_negative_columns)),
        }
        if self.target:
            d["target"] = self.target
        return d

    @classmethod
    def from_json_dict(cls, d: dict) -> "TableMeta":
        return cls(
            columns=[ColumnMeta.from_json_dict(c, i) for i, c in enumerate(d["columns"])],
            name=d.get("name", ""),
            problem_type=d.get("problem_type", ""),
            target=d.get("target"),
            date_info=d.get("date_info", {}),
            integer_columns=d.get("integer_info", []),
            non_negative_columns=d.get("non_negative_cols", []),
        )

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            # Same formatting as the reference's json.dump calls
            # (Server/dtds/distributed.py:683-684).
            json.dump(
                self.to_json_dict(),
                f,
                sort_keys=True,
                indent=4,
                separators=(",", ": "),
            )

    @classmethod
    def load_json(cls, path: str) -> "TableMeta":
        with open(path) as f:
            return cls.from_json_dict(json.load(f))
