from fed_tgan_tpu.data.constants import BIMODAL, CATEGORICAL, CONTINUOUS, ORDINAL
from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.ingest import TablePreprocessor
from fed_tgan_tpu.data.schema import ColumnMeta, TableMeta

__all__ = [
    "BIMODAL",
    "CATEGORICAL",
    "CONTINUOUS",
    "ORDINAL",
    "CategoryEncoder",
    "ColumnMeta",
    "TableMeta",
    "TablePreprocessor",
]
