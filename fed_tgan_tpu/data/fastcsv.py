"""Quantization-aware snapshot formatting.

The reference writes its 40k-row snapshot CSV through pandas every epoch
(reference Server/dtds/distributed.py:589-590).  On this framework's packed8
wire layout the host never needs to format 40k floats at all: a continuous
column's decoded value is a pure function of (mode index k, quantized u), so
it takes at most ``n_modes * (2*u_scale+1)`` distinct values (~2,550 under
packed8).  ``PackedSnapshotFormatter`` formats every distinct value ONCE per
run — through pyarrow's own CSV writer, so each value's repr is identical to
what the plain float column would have produced — and each snapshot becomes
integer index arithmetic plus an arrow dictionary ``take``: no float
formatting, no 40k-row string materialization, no pandas frame.  Measured on
the 1-core dev host at the reference's 40k x 42 snapshot: 413 -> 158 ms
per snapshot vs the assemble+decode_to_table path (the residual is pyarrow
densify + 21 MB of IO).  The only byte-level difference is quoting (pyarrow
quotes string-typed columns, so continuous values ship quoted);
``pd.read_csv`` — what the eval suite and the reference's offline scripts
use — parses both outputs to identical values.

Categorical columns reuse the dictionary trick the arrow-direct decode
introduced (data/decode.decode_to_table); here the continuous columns join
them, which is what removes the writer's remaining CPU floor (VERDICT r04:
~340 ms/round of decode+frame+CSV on the 1-core host).

Eligible when: pyarrow supports ``quoting_style="needed"`` (needed for float
byte-parity), the wire layout is quantized with a small level count
(packed8; packed16's 65k levels would make the LUT larger than the data),
every non-continuous column is categorical with an encoder, and the meta has
no date columns.  Anything else falls back to the existing paths.
"""

from __future__ import annotations

import io
from typing import Sequence

import numpy as np

from fed_tgan_tpu.data.constants import MISSING_TOKEN
from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.schema import TableMeta

# largest (2*u_scale+1) level count the per-column string LUT accepts: at
# packed8 (255 levels x <=10 modes) the LUT is ~2.5k strings per column;
# packed16 would be 65k x modes — bigger than the snapshot itself
_MAX_LEVELS = 1024


def _csv_formatted(values: np.ndarray) -> list[str]:
    """Format a float array exactly as ``pyarrow.csv.write_csv`` would
    render the equivalent float64 column — by running it through that very
    writer once and splitting the lines."""
    import pyarrow as pa
    import pyarrow.csv as pacsv

    buf = io.BytesIO()
    pacsv.write_csv(pa.table({"v": pa.array(values, type=pa.float64())}), buf)
    lines = buf.getvalue().decode().splitlines()
    return lines[1:]  # drop the header row


class PackedSnapshotFormatter:
    """parts {u:int8, k:int8, disc:int} -> ``pyarrow.Table`` of
    dictionary<string> columns, value-identical under ``pd.read_csv`` to
    the assemble+decode_to_table path it replaces."""

    def __init__(self, dictionaries, index_plan, names):
        self._dictionaries = dictionaries  # per column: pa.array of strings
        self._plan = index_plan  # per column: ("cont", j, L) | ("disc", j, enc)
        self._names = names

    @classmethod
    def build(
        cls,
        tables: dict | None,
        meta: TableMeta,
        encoders: Sequence[CategoryEncoder],
    ) -> "PackedSnapshotFormatter | None":
        """None when the fast path is not applicable (caller falls back)."""
        if tables is None or meta.date_info:
            return None
        try:
            import pyarrow as pa
            import pyarrow.csv as pacsv
        except ImportError:
            return None
        try:
            # float byte-parity depends on "needed" quoting (csvio's writer
            # silently falls back to quote-everything on old pyarrow, which
            # would wrap every continuous value in quotes) — so the fast
            # path is only eligible when the option exists
            pacsv.WriteOptions(quoting_style="needed")
        except (TypeError, ValueError):
            return None
        u_scale = int(tables["u_scale"])
        levels = 2 * u_scale + 1
        if levels > _MAX_LEVELS:
            return None
        cat_names = meta.categorical_columns
        if set(meta.column_names) - set(cat_names) - set(meta.continuous_columns):
            return None  # ordinal / unknown column kinds: exact path
        enc_by_name = dict(zip(cat_names, encoders))
        cont_idx = {int(i): j for j, i in enumerate(np.asarray(tables["cont_idx"]))}
        disc_idx = {int(i): j for j, i in enumerate(np.asarray(tables["disc_idx"]))}
        mu = np.asarray(tables["mu"], dtype=np.float64)
        sg = np.asarray(tables["sg"], dtype=np.float64)
        from fed_tgan_tpu.ops.decode import SCALE

        u_grid = np.arange(-u_scale, u_scale + 1, dtype=np.float64) / u_scale
        nonneg = set(meta.non_negative_columns)
        from fed_tgan_tpu.data.constants import MISSING_CONTINUOUS

        dictionaries, plan = [], []
        for i, name in enumerate(meta.column_names):
            if i in cont_idx:
                j = cont_idx[i]
                # (modes, levels) value grid — the only floats ever formatted
                vals = u_grid[None, :] * SCALE * sg[j][:, None] + mu[j][:, None]
                if (vals == MISSING_CONTINUOUS).any():
                    return None  # a mode can emit the missing sentinel
                if name in nonneg:
                    y = np.exp(vals) - 1.0
                    vals = np.where(y < 0, np.ceil(y), y)
                    if (vals == -1).any():
                        # exp(sentinel)-1 == -1 decodes to the blank missing
                        # token on the exact paths (data/decode.py) — punt
                        # rather than write -1 as a number
                        return None
                dictionaries.append(pa.array(_csv_formatted(vals.ravel())))
                plan.append(("cont", j, levels))
            else:
                enc = enc_by_name[name]
                cats = [" " if c == MISSING_TOKEN else str(c)
                        for c in enc.classes_]
                dictionaries.append(pa.array(cats, type=pa.string()))
                plan.append(("disc", disc_idx[i], enc))
        return cls(dictionaries, plan, list(meta.column_names))

    def table(self, parts: dict):
        import pyarrow as pa

        u = np.asarray(parts["u"], dtype=np.int32)
        k = np.asarray(parts["k"], dtype=np.int32)
        disc = np.asarray(parts["disc"])
        arrays = {}
        for name, dictionary, step in zip(self._names, self._dictionaries, self._plan):
            kind, j, extra = step
            if kind == "cont":
                levels = extra
                idx = k[:, j] * levels + (u[:, j] + (levels - 1) // 2)
            else:
                idx = extra.validate_codes(disc[:, j]).astype(np.int32)
            arrays[name] = pa.DictionaryArray.from_arrays(
                pa.array(idx, type=pa.int32()), dictionary
            )
        return pa.table(arrays)
