"""Device-mesh construction for the ``clients`` axis.

The reference's world is rank 0 (server) + N client processes over TCP
(reference Server/dtds/distributed.py:838-891).  Here the world is a 1-D
``jax.sharding.Mesh`` with a ``clients`` axis: each mesh position simulates
one (or, when n_clients > n_devices, several) federated participants, and
there is no separate server rank — aggregation is a collective.

Multi-host: initialize ``jax.distributed`` before building the mesh and the
same code spans hosts, with XLA routing the FedAvg psum over ICI within a
slice and DCN across slices.

Platform selection, provisioning, probing and mesh/topology construction
now live in ``runtime/backend.py`` (the portable backend seam); the
historical entry points below are re-exports kept so existing imports —
and the test monkeypatch seams on this module — keep working.  What stays
native here is the shard_map-adjacent collective surface.
"""

from __future__ import annotations

import jax

from fed_tgan_tpu.runtime.backend import (  # noqa: F401  (re-exported shims)
    CLIENTS_AXIS,
    _probe_stamp_path,
    arm_watchdog,
    backend_initialized,
    client_mesh,
    cpu_pinned,
    host_axis_groups,
    probe_backend_responsive,
    provision_virtual_cpu,
)
from fed_tgan_tpu.runtime.backend import (
    touch_backend_with_watchdog as _touch_backend_impl,
)
from jax.sharding import Mesh


def touch_backend_with_watchdog(
    timeout_s: float = 180.0,
    who: str = "",
    _touch=None,
    _abort=None,
) -> tuple[bool, str]:
    """Shim over ``runtime.backend.touch_backend_with_watchdog`` that reads
    the already-initialized early exit through THIS module's
    ``backend_initialized`` global, so tests (and callers) that patch the
    historical ``parallel.mesh`` seam keep governing the real behavior."""
    return _touch_backend_impl(
        timeout_s=timeout_s, who=who, _touch=_touch, _abort=_abort,
        _initialized=lambda: backend_initialized(),
    )


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it at the top level with a ``check_vma`` knob; older
    releases (<= 0.4.x) only have ``jax.experimental.shard_map.shard_map``
    where the same knob is spelled ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def pcast_varying(x, axes):
    """Mark ``x`` as device-varying over ``axes`` where the jax version
    tracks varying-ness (``jax.lax.pcast``); identity on older releases,
    which have no vma type system to satisfy."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return x


def clients_per_device(n_clients: int, mesh: Mesh) -> int:
    """How many simulated participants each device hosts.

    n_clients must be a multiple of the mesh size so every device runs the
    same program shape (SPMD)."""
    n_dev = mesh.devices.size
    if n_clients % n_dev != 0:
        raise ValueError(
            f"n_clients={n_clients} must be a multiple of mesh size {n_dev}; "
            "pad the client list or shrink the mesh"
        )
    return n_clients // n_dev
