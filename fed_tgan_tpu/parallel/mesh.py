"""Device-mesh construction for the ``clients`` axis.

The reference's world is rank 0 (server) + N client processes over TCP
(reference Server/dtds/distributed.py:838-891).  Here the world is a 1-D
``jax.sharding.Mesh`` with a ``clients`` axis: each mesh position simulates
one (or, when n_clients > n_devices, several) federated participants, and
there is no separate server rank — aggregation is a collective.

Multi-host: initialize ``jax.distributed`` before building the mesh and the
same code spans hosts, with XLA routing the FedAvg psum over ICI within a
slice and DCN across slices.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

CLIENTS_AXIS = "clients"


def backend_initialized() -> bool:
    """True once any JAX backend client exists in this process."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False  # private API moved: assume uninitialized


def probe_backend_responsive(timeout_s: int = 120) -> tuple[bool, str]:
    """Whether ``jax.devices()`` completes in a fresh interpreter.

    A wedged accelerator tunnel hangs ``jax.devices()`` indefinitely (seen
    on the tunneled TPU transport under sustained load); probing in a
    SUBPROCESS with a timeout lets callers fall back to a CPU mesh instead
    of hanging with it.  Only meaningful before this process initializes a
    backend.

    Returns ``(ok, reason)`` — ``reason`` distinguishes a hang from a fast
    crash and carries the child's stderr tail so misconfigurations (e.g. a
    plugin version mismatch) aren't misreported as "unresponsive".

    A successful probe is cached on disk for ``cache_s`` seconds (keyed by
    platform selection) so bursts of CLI runs on a healthy machine don't pay
    the backend double-initialization.  The cache is a liveness tradeoff —
    a wedge arriving inside the window hangs the NEXT run like an unprobed
    one would (the probe is inherently a point-in-time check: even an
    uncached probe races a wedge arriving right after it).  The window is
    kept short for that reason; failures are never cached.
    """
    import hashlib
    import os
    import subprocess
    import sys
    import tempfile
    import time

    cache_s = 300
    key = hashlib.sha256(
        (os.environ.get("JAX_PLATFORMS", "") + sys.executable).encode()
    ).hexdigest()[:16]
    stamp = os.path.join(tempfile.gettempdir(), f".fed_tgan_backend_ok_{key}")
    try:
        if time.time() - os.path.getmtime(stamp) < cache_s:
            return True, "cached"
    except OSError:
        pass

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"jax.devices() did not return within {timeout_s}s (hung backend)"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return False, "backend probe crashed: " + (" | ".join(tail) or f"rc={proc.returncode}")
    try:
        with open(stamp, "w"):
            pass
    except OSError:
        pass
    return True, ""


def provision_virtual_cpu(n_devices: int) -> None:
    """Force an ``n_devices`` virtual CPU platform (the tests/CI recipe).

    Must run before any JAX backend initializes.  Sets
    ``--xla_force_host_platform_device_count`` in XLA_FLAGS — replacing any
    existing (possibly smaller) value — then overrides the platform through
    the config API, because this environment pre-imports jax with
    JAX_PLATFORMS=axon via a site hook, making the env-var route too late.
    Raises RuntimeError if the devices don't materialize (i.e. a backend was
    already initialized in this process).
    """
    import os
    import re

    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"could not provision {n_devices} virtual CPU devices "
            f"(got {len(jax.devices())}); was a backend already initialized?"
        )


def client_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all) with axis 'clients'."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (CLIENTS_AXIS,))


def clients_per_device(n_clients: int, mesh: Mesh) -> int:
    """How many simulated participants each device hosts.

    n_clients must be a multiple of the mesh size so every device runs the
    same program shape (SPMD)."""
    n_dev = mesh.devices.size
    if n_clients % n_dev != 0:
        raise ValueError(
            f"n_clients={n_clients} must be a multiple of mesh size {n_dev}; "
            "pad the client list or shrink the mesh"
        )
    return n_clients // n_dev
