"""Device-mesh construction for the ``clients`` axis.

The reference's world is rank 0 (server) + N client processes over TCP
(reference Server/dtds/distributed.py:838-891).  Here the world is a 1-D
``jax.sharding.Mesh`` with a ``clients`` axis: each mesh position simulates
one (or, when n_clients > n_devices, several) federated participants, and
there is no separate server rank — aggregation is a collective.

Multi-host: initialize ``jax.distributed`` before building the mesh and the
same code spans hosts, with XLA routing the FedAvg psum over ICI within a
slice and DCN across slices.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from fed_tgan_tpu.obs.journal import emit as _emit_event

CLIENTS_AXIS = "clients"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it at the top level with a ``check_vma`` knob; older
    releases (<= 0.4.x) only have ``jax.experimental.shard_map.shard_map``
    where the same knob is spelled ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def pcast_varying(x, axes):
    """Mark ``x`` as device-varying over ``axes`` where the jax version
    tracks varying-ness (``jax.lax.pcast``); identity on older releases,
    which have no vma type system to satisfy."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return x


def cpu_pinned() -> bool:
    """Whether this process can only ever see the cpu platform.  The config
    value only reflects ``config.update``; an env-var pin is read by jax at
    backend-init time, so consult both.  NOTE: on hosts whose site hook
    pre-imports jax against an accelerator plugin, a fresh subprocess may
    ignore an env-var cpu pin — in-process ``jax.config.update`` is the
    reliable route (provision_virtual_cpu does this)."""
    import os

    platforms = getattr(jax.config, "jax_platforms", None) or os.environ.get(
        "JAX_PLATFORMS"
    )
    return bool(platforms) and set(str(platforms).split(",")) <= {"cpu"}


def backend_initialized() -> bool:
    """True once any JAX backend client exists in this process."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False  # private API moved: assume uninitialized


def probe_backend_responsive(
    timeout_s: int = 15,
    attempts: int = 1,
    backoff_s: float = 60.0,
    log=None,
    ignore_cache: bool = False,
) -> tuple[bool, str]:
    """Whether ``jax.devices()`` completes in a fresh interpreter.

    A wedged accelerator tunnel hangs ``jax.devices()`` indefinitely (seen
    on the tunneled TPU transport under sustained load); probing in a
    SUBPROCESS with a timeout lets callers fall back to a CPU mesh instead
    of hanging with it.  Only meaningful before this process initializes a
    backend.

    The deadline is a hard ~15 s by default: a healthy backend answers in
    low single-digit seconds, and BENCH_r05 measured a wedged tunnel
    holding the old 120–300 s deadlines for their full duration on every
    attempt — CPU failover should cost seconds, not minutes.

    Returns ``(ok, reason)`` — ``reason`` distinguishes a hang from a fast
    crash and carries the child's stderr tail so misconfigurations (e.g. a
    plugin version mismatch) aren't misreported as "unresponsive".

    ``attempts`` > 1 retries a failed probe after ``backoff_s`` seconds —
    for callers (the benchmark) whose entire purpose is the accelerator
    number, one transient wedge or a probe racing another process holding
    the chip should not flip the run to CPU permanently.  ``log`` (callable
    taking a string) narrates each failed attempt so a fallback is
    self-explaining.

    A successful probe is cached on disk for ``cache_s`` seconds (keyed by
    platform selection and uid) so bursts of CLI runs on a healthy machine
    don't pay the backend double-initialization.  The cache is a liveness
    tradeoff — a wedge arriving inside the window hangs the NEXT run like
    an unprobed one would (the probe is inherently a point-in-time check:
    even an uncached probe races a wedge arriving right after it); callers
    close that hole with ``touch_backend_with_watchdog``.  The window is
    kept short for that reason; failures are never cached.
    """
    import os
    import subprocess
    import sys
    import time

    cache_s = 300
    stamp = _probe_stamp_path()
    if not ignore_cache:
        # ``ignore_cache``: callers whose whole point is CURRENT liveness
        # (doctor --wait-healthy gating a relaunch) must not be vouched for
        # by a stamp that may predate a fresh wedge
        try:
            st = os.lstat(stamp)  # lstat: never trust a symlinked stamp
            import stat as _stat

            if (_stat.S_ISREG(st.st_mode) and st.st_uid == os.getuid()
                    and time.time() - st.st_mtime < cache_s):
                return True, "cached"
        except OSError:
            pass

    reason = ""
    for attempt in range(1, max(1, attempts) + 1):
        if attempt > 1:
            if log is not None:
                log(f"backend probe attempt {attempt - 1}/{attempts} failed "
                    f"({reason}); retrying in {backoff_s:.0f}s")
            time.sleep(backoff_s)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            reason = (f"jax.devices() did not return within {timeout_s}s "
                      "(hung backend)")
            continue
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-3:]
            reason = ("backend probe crashed: "
                      + (" | ".join(tail) or f"rc={proc.returncode}"))
            continue
        try:
            fd = os.open(stamp, os.O_WRONLY | os.O_CREAT | os.O_NOFOLLOW,
                         0o600)
            os.utime(fd)
            os.close(fd)
        except OSError:
            pass
        _emit_event("backend_probe", ok=True, attempts=attempt,
                    timeout_s=timeout_s)
        return True, "" if attempt == 1 else f"ok after {attempt} attempts"
    if attempts > 1:
        reason += f" (after {attempts} attempts over ~" \
                  f"{attempts * timeout_s + (attempts - 1) * backoff_s:.0f}s)"
    _emit_event("backend_probe", ok=False, reason=reason,
                timeout_s=timeout_s)
    return False, reason


def _probe_stamp_path() -> str:
    """Path of the positive-probe cache stamp.

    uid in the key + O_NOFOLLOW on create (see caller): on a shared box
    another user's stale stamp must not vouch for this user's tunnel, nor
    may a planted symlink at the predictable path redirect the create.
    """
    import hashlib
    import os
    import sys
    import tempfile

    key = hashlib.sha256(
        (os.environ.get("JAX_PLATFORMS", "") + sys.executable
         + str(os.getuid())).encode()
    ).hexdigest()[:16]
    return os.path.join(tempfile.gettempdir(), f".fed_tgan_backend_ok_{key}")


def arm_watchdog(timeout_s: float, on_fire, name: str = "watchdog"):
    """Daemon thread that calls ``on_fire()`` unless cancelled within
    ``timeout_s``; returns the cancel callable.  Shared core of the
    backend-touch watchdog and the bench run deadline, so the
    Event/daemon-thread/force-exit shape cannot drift between them."""
    import threading

    done = threading.Event()

    def _watch() -> None:
        if not done.wait(timeout_s):
            on_fire()

    threading.Thread(target=_watch, daemon=True, name=name).start()
    return done.set


def touch_backend_with_watchdog(
    timeout_s: float = 180.0,
    who: str = "",
    _touch=None,
    _abort=None,
) -> tuple[bool, str]:
    """Initialize the accelerator backend NOW, guarded by a watchdog.

    The probe cache means a run can start inside the positive-cache window
    of a probe that predates a fresh wedge; that run's first real
    ``jax.devices()`` then hangs exactly like an unprobed one.  Calling
    this right after platform selection closes the hole: the touch happens
    immediately, and a watchdog thread aborts the process with the same
    diagnosis the probe produces if it doesn't complete in ``timeout_s``.

    A touch that CRASHES instead of hanging (e.g. another process grabbed
    the chip between probe and touch) returns ``(False, reason)`` — the
    probe-style contract — so callers route it through their normal
    fallback/abort policy instead of dying on a raw traceback.  A hang
    cannot return: the watchdog ``os._exit``\\ s (not ``sys.exit``) because
    the main thread is stuck inside an uninterruptible C extension call —
    no Python exception can reach it.  Both failure modes invalidate the
    positive stamp so the next run re-probes for real.
    ``_touch``/``_abort`` are test seams.
    """
    if backend_initialized():
        return True, ""
    import os
    import sys

    def _drop_stamp() -> None:
        # invalidate the (now-stale) positive stamp so the NEXT run
        # re-probes for real and can fall back to CPU gracefully
        # instead of repeating this failure for the cache window
        try:
            os.unlink(_probe_stamp_path())
        except OSError:
            pass

    def _fire() -> None:
        _drop_stamp()
        print(
            f"{who}accelerator backend unusable (jax.devices() did not "
            f"return within {timeout_s:.0f}s after a positive probe — "
            "the tunnel likely wedged inside the probe-cache window); "
            "aborting — retry later or use --backend cpu",
            file=sys.stderr,
            flush=True,
        )
        (_abort or os._exit)(3)

    cancel = arm_watchdog(timeout_s, _fire, name="backend-touch-watchdog")
    try:
        (jax.devices if _touch is None else _touch)()
    except Exception as exc:
        _drop_stamp()
        return False, f"backend init crashed after a positive probe: {exc}"
    finally:
        cancel()
    return True, ""


def provision_virtual_cpu(n_devices: int) -> None:
    """Force an ``n_devices`` virtual CPU platform (the tests/CI recipe).

    Must run before any JAX backend initializes.  Sets
    ``--xla_force_host_platform_device_count`` in XLA_FLAGS — replacing any
    existing (possibly smaller) value — then overrides the platform through
    the config API, because this environment pre-imports jax with
    JAX_PLATFORMS=axon via a site hook, making the env-var route too late.
    Raises RuntimeError if the devices don't materialize (i.e. a backend was
    already initialized in this process).
    """
    import os
    import re

    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"could not provision {n_devices} virtual CPU devices "
            f"(got {len(jax.devices())}); was a backend already initialized?"
        )


def client_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all) with axis 'clients'."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (CLIENTS_AXIS,))


def host_axis_groups(mesh: Mesh):
    """``axis_index_groups`` pair for a two-tier (intra-host, cross-host)
    psum over the clients axis, or ``None`` when tiering buys nothing.

    Tier 1 groups the mesh positions living on one host process (reduced
    over fast intra-host interconnect); tier 2 groups one representative
    column across hosts, so the cross-host hop moves one partial per host
    instead of one per device.  Returns ``None`` — callers then emit the
    plain flat psum, byte-identical to pre-tier programs — when the mesh
    spans fewer than two processes, hosts hold unequal device counts
    (grouped psums need rectangular groups), or each host has a single
    device (tier 1 would be a no-op).
    """
    by_proc: dict[int, list[int]] = {}
    for idx, d in enumerate(mesh.devices.flat):
        by_proc.setdefault(d.process_index, []).append(idx)
    groups = [by_proc[p] for p in sorted(by_proc)]
    if len(groups) < 2:
        return None
    width = len(groups[0])
    if width < 2 or any(len(g) != width for g in groups):
        return None
    inter = [[g[j] for g in groups] for j in range(width)]
    return groups, inter


def clients_per_device(n_clients: int, mesh: Mesh) -> int:
    """How many simulated participants each device hosts.

    n_clients must be a multiple of the mesh size so every device runs the
    same program shape (SPMD)."""
    n_dev = mesh.devices.size
    if n_clients % n_dev != 0:
        raise ValueError(
            f"n_clients={n_clients} must be a multiple of mesh size {n_dev}; "
            "pad the client list or shrink the mesh"
        )
    return n_clients // n_dev
