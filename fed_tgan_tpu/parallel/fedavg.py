"""In-graph weighted federated averaging.

The reference's aggregation is a Python loop over state_dict layers on the
server after shipping every client's weights over RPC (reference
Server/dtds/distributed.py:86-132, :799-823) — the dominant per-epoch
communication cost.  Here it is one ``lax.psum`` of weight-scaled parameter
pytrees over the ``clients`` mesh axis: the result lands replicated on every
device, so the reference's separate "distribute averaged weights back"
round-trip (distributed.py:821-823) costs nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fed_tgan_tpu.parallel.mesh import CLIENTS_AXIS


def weighted_average(tree, weights: jax.Array, axis_name: str = CLIENTS_AXIS):
    """sum_i w_i * leaf_i over the mesh axis, for every leaf.

    Call inside shard_map.  ``tree`` leaves carry a leading local-clients
    axis of size k (>=1); ``weights`` is the local (k,) slice of the global
    weight vector.  Returns leaves WITHOUT the leading axis: the global
    weighted sum, identical on every device (psum replicates it).
    """

    def avg(leaf):
        local = jnp.tensordot(weights, leaf.astype(jnp.float32), axes=1)
        return jax.lax.psum(local, axis_name).astype(leaf.dtype)

    return jax.tree.map(avg, tree)


def replicate_local(tree, k: int):
    """Broadcast averaged leaves back to the per-local-client layout."""
    return jax.tree.map(lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), tree)
