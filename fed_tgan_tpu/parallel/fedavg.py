"""In-graph weighted federated averaging.

The reference's aggregation is a Python loop over state_dict layers on the
server after shipping every client's weights over RPC (reference
Server/dtds/distributed.py:86-132, :799-823) — the dominant per-epoch
communication cost.  Here it is one ``lax.psum`` of weight-scaled parameter
pytrees over the ``clients`` mesh axis: the result lands replicated on every
device, so the reference's separate "distribute averaged weights back"
round-trip (distributed.py:821-823) costs nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fed_tgan_tpu.parallel.mesh import CLIENTS_AXIS


def hierarchical_psum(x, axis_name: str = CLIENTS_AXIS, groups=None):
    """Two-tier psum: intra-host reduce, then cross-host reduce.

    ``groups`` is ``None`` (plain single psum — byte-identical programs to
    pre-tier builds) or a pair ``(intra, inter)`` of
    ``axis_index_groups`` lists: tier 1 reduces within each host's group
    (every device holds its host's partial sum), tier 2 reduces one
    representative column across hosts (every device ends with the global
    sum, replicated — the same contract as a flat psum).  On multi-host
    meshes the cross-host tier then moves one partial per host over ICI/DCN
    instead of one per device.  See :func:`..mesh.host_axis_groups`.
    """
    if groups is None:
        return jax.lax.psum(x, axis_name)
    intra, inter = groups
    x = jax.lax.psum(x, axis_name, axis_index_groups=intra)
    return jax.lax.psum(x, axis_name, axis_index_groups=inter)


def weighted_average(tree, weights: jax.Array, axis_name: str = CLIENTS_AXIS,
                     groups=None):
    """sum_i w_i * leaf_i over the mesh axis, for every leaf.

    Call inside shard_map.  ``tree`` leaves carry a leading local-clients
    axis of size k (>=1); ``weights`` is the local (k,) slice of the global
    weight vector.  Returns leaves WITHOUT the leading axis: the global
    weighted sum, identical on every device (psum replicates it).  The
    intra-device ``tensordot`` over k is tier 0; ``groups`` (see
    :func:`hierarchical_psum`) splits the cross-device reduce into
    intra-host + cross-host tiers on multi-host meshes.
    """

    def avg(leaf):
        local = jnp.tensordot(weights, leaf.astype(jnp.float32), axes=1)
        return hierarchical_psum(local, axis_name, groups).astype(leaf.dtype)

    return jax.tree.map(avg, tree)


def weighted_delta_average(
    prev,
    new,
    weights: jax.Array,
    axis_name: str = CLIENTS_AXIS,
    payload_dtype=jnp.bfloat16,
    renormalize: bool = False,
    groups=None,
):
    """:func:`weighted_average` with the COLLECTIVE payload re-encoded to
    ``payload_dtype`` — the bf16 half of the mixed-precision mode.

    Only the weighted per-round DELTA crosses the wire at reduced
    precision: the local weighted accumulation runs in f32, the psum moves
    ``payload_dtype`` bytes (~half of f32), and the result is re-anchored
    on the replicated global prev in f32.  Quantization error is therefore
    confined to each round's step, never compounding in the master params.

    Requires what the fused epoch already guarantees: ``prev`` replicated
    (``leaf[0]`` is the global state) and the global ``weights`` summing
    to 1 (so sum_i w_i * (n_i - p) == sum_i w_i * n_i - p).  That second
    precondition used to be docstring-only; ``renormalize=True`` enforces
    it in-graph by dividing the reduced step by the global weight sum (one
    extra scalar psum) — callers whose weights may have drifted off 1
    after cohort masking or quarantine renormalization must pass it, so
    the delta path cannot silently re-anchor off the true average.
    ``renormalize=False`` keeps pre-fix programs byte-identical.
    """

    def avg(p, n, wsum):
        d = n.astype(jnp.float32) - p.astype(jnp.float32)
        local = jnp.tensordot(weights, d, axes=1)
        step = hierarchical_psum(local.astype(payload_dtype), axis_name,
                                 groups)
        step = step.astype(jnp.float32)
        if wsum is not None:
            step = step / wsum
        return (p[0].astype(jnp.float32) + step).astype(n.dtype)

    wsum = None
    if renormalize:
        wsum = jnp.maximum(
            hierarchical_psum(weights.astype(jnp.float32).sum(), axis_name,
                              groups),
            _EPS,
        )
    return jax.tree.map(lambda p, n: avg(p, n, wsum), prev, new)


def replicate_local(tree, k: int):
    """Broadcast averaged leaves back to the per-local-client layout."""
    return jax.tree.map(lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), tree)


# --------------------------------------------------------------------------
# Byzantine-robust aggregation.
#
# The reference trusts every client state_dict blindly; here a validation
# gate screens each delta for NaN/Inf and a median-based norm outlier test
# (two-sided: the high side catches scaled/poisoned updates, the low side
# stuck clients replaying stale params), renormalizes the similarity
# weights over the survivors, and feeds one of four aggregators.  All of it
# runs in-graph over the clients mesh axis so the gate costs one extra
# all_gather of scalars per round; host-side numpy twins below serve the
# socket path, doctor checks, and parity tests.
# --------------------------------------------------------------------------

_EPS = 1e-12


def _delta_norms(prev, new, k: int):
    """Per-local-client finite flags and delta L2 norms, over all leaves.

    Returns ``(finite, norm)``, both shape (k,).  Non-finite entries are
    masked to 0 before the sum-of-squares so a single NaN cannot poison the
    norm of an otherwise-informative delta (the finite flag already damns
    that client).
    """
    finite = jnp.ones((k,), dtype=bool)
    sumsq = jnp.zeros((k,), dtype=jnp.float32)
    for p, n in zip(jax.tree.leaves(prev), jax.tree.leaves(new)):
        if not jnp.issubdtype(n.dtype, jnp.floating):
            continue
        d = n.astype(jnp.float32) - p.astype(jnp.float32)
        d = d.reshape(k, -1)
        ok = jnp.isfinite(d)
        finite = finite & ok.all(axis=1)
        sumsq = sumsq + jnp.sum(jnp.where(ok, d, 0.0) ** 2, axis=1)
    return finite, jnp.sqrt(sumsq)


def robust_aggregate(
    prev,
    new,
    weights: jax.Array,
    steps: jax.Array,
    k: int,
    aggregator: str = "weighted",
    update_gate: bool = True,
    gate_norm_factor: float = 10.0,
    update_clip: float = 3.0,
    trim_ratio: float = 0.2,
    axis_name: str = CLIENTS_AXIS,
    payload_dtype=None,
    groups=None,
):
    """Gate + aggregate client parameter trees inside shard_map.

    ``prev``/``new`` leaves carry a leading local-clients axis of size
    ``k``; ``prev`` is the replicated pre-round state (every client's slice
    holds the same global values, so ``leaf[0]`` IS the global prev).
    ``weights``/``steps`` are the local (k,) slices.  Returns
    ``(agg_tree, quarantined)``: leaves WITHOUT the leading axis (replicated
    global result) and a local (k,) float mask of clients the gate rejected
    this round.

    When every alive client passes the gate the effective weights are the
    ORIGINAL weights (scalar select, not a renormalized copy), so the
    ``weighted`` aggregator reproduces :func:`weighted_average`
    bit-identically on clean rounds.

    ``payload_dtype`` (bf16 mode) re-encodes the cross-device payload of
    every aggregator to that dtype, composing with the gate: the norm
    screen's ``_delta_norms``/all_gather scalars stay f32 (a poisoned
    update must not hide behind quantization), only the bulk parameter
    traffic shrinks.  ``None`` keeps the f32 programs byte-identical.

    ``groups`` (see :func:`hierarchical_psum`) two-tiers the bulk psum of
    the weighted/clipped aggregators on multi-host meshes; the gate's
    scalar all_gathers and the gather-based trimmed/median aggregators
    (which need every survivor's full value, not a sum) stay flat.
    """
    gather = lambda x: jax.lax.all_gather(x, axis_name, axis=0, tiled=True)
    rank = jax.lax.axis_index(axis_name)

    finite_l, norm_l = _delta_norms(prev, new, k)
    finite_g = gather(finite_l)
    norm_g = gather(norm_l)
    w_g = gather(weights.astype(jnp.float32))
    steps_g = gather(steps.astype(jnp.int32))

    alive = w_g > 0
    trained = steps_g > 0
    if update_gate:
        # median-based two-sided norm outlier test over clients that are
        # alive, finite, and actually trained this round (zero-step clients
        # legitimately ship zero deltas)
        consider = alive & finite_g & trained
        med = jnp.nanmedian(jnp.where(consider, norm_g, jnp.nan))
        med_ok = jnp.isfinite(med) & (med > 0)
        bad_norm = (
            med_ok
            & trained
            & ((norm_g > gate_norm_factor * med)
               | (norm_g * gate_norm_factor < med))
        )
        valid = alive & finite_g & ~bad_norm
    else:
        med = jnp.nanmedian(jnp.where(alive & finite_g, norm_g, jnp.nan))
        valid = alive & finite_g

    all_valid = (valid == alive).all()
    wz = jnp.where(valid, w_g, 0.0)
    s = wz.sum()
    any_valid = s > 0
    # bit-exact passthrough: a clean round uses the original weights, an
    # attacked round the survivor-renormalized ones
    w_eff_g = jnp.where(all_valid, w_g, wz / jnp.maximum(s, _EPS))

    sl = lambda x: jax.lax.dynamic_slice_in_dim(x, rank * k, k, axis=0)
    valid_l = sl(valid)
    w_eff_l = sl(w_eff_g)

    def expand(mask, leaf):
        return mask.reshape((k,) + (1,) * (leaf.ndim - 1))

    # sanitize BEFORE any weighted arithmetic: NaN * 0 is NaN, so invalid
    # clients' leaves are replaced by their (replicated, finite) prev values
    san = jax.tree.map(
        lambda p, n: jnp.where(expand(valid_l, n), n, p), prev, new
    )

    if aggregator == "weighted":
        if payload_dtype is not None:
            agg = weighted_delta_average(
                prev, san, w_eff_l, axis_name, payload_dtype, groups=groups)
        else:
            agg = weighted_average(san, w_eff_l, axis_name, groups=groups)
    elif aggregator == "clipped":
        # norm-clipped weighted mean of deltas around the global prev:
        # scale_i = min(1, update_clip * median_norm / norm_i)
        safe_med = jnp.where(jnp.isfinite(med) & (med > 0), med, 1.0)
        scale_g = jnp.minimum(
            1.0, update_clip * safe_med / jnp.maximum(norm_g, _EPS)
        )
        cw_l = w_eff_l * sl(scale_g)

        def clip_avg(p, n):
            d = n.astype(jnp.float32) - p.astype(jnp.float32)
            local = jnp.tensordot(cw_l, d, axes=1)
            if payload_dtype is not None:
                local = local.astype(payload_dtype)
            step = hierarchical_psum(local, axis_name,
                                     groups).astype(jnp.float32)
            return (p[0].astype(jnp.float32) + step).astype(n.dtype)

        agg = jax.tree.map(clip_avg, prev, san)
    elif aggregator == "trimmed":
        m = valid.sum()
        t = jnp.minimum(
            jnp.floor(trim_ratio * m).astype(jnp.int32),
            jnp.maximum((m - 1) // 2, 0),
        )

        def trim_mean(leaf):
            src = (leaf.astype(payload_dtype) if payload_dtype is not None
                   else leaf.astype(jnp.float32))
            g = gather(src).astype(jnp.float32)           # (n, ...)
            n_total = g.shape[0]
            mask = valid.reshape((n_total,) + (1,) * (g.ndim - 1))
            g = jnp.where(mask, g, jnp.inf)               # invalid sort last
            g = jnp.sort(g, axis=0)
            idx = jnp.arange(n_total).reshape(
                (n_total,) + (1,) * (g.ndim - 1)
            )
            keep = (idx >= t) & (idx < m - t)
            total = jnp.sum(jnp.where(keep, g, 0.0), axis=0)
            return (total / jnp.maximum(m - 2 * t, 1)).astype(leaf.dtype)

        agg = jax.tree.map(trim_mean, san)
    elif aggregator == "median":

        def coord_median(leaf):
            src = (leaf.astype(payload_dtype) if payload_dtype is not None
                   else leaf.astype(jnp.float32))
            g = gather(src).astype(jnp.float32)
            mask = valid.reshape((g.shape[0],) + (1,) * (g.ndim - 1))
            g = jnp.where(mask, g, jnp.nan)
            return jnp.nanmedian(g, axis=0).astype(leaf.dtype)

        agg = jax.tree.map(coord_median, san)
    else:
        raise ValueError(
            f"unknown aggregator {aggregator!r}; "
            "expected weighted|clipped|trimmed|median"
        )

    # if the gate rejected EVERYONE, keep the previous global state rather
    # than publishing garbage
    agg = jax.tree.map(
        lambda a, p: jnp.where(any_valid, a, p[0].astype(a.dtype)), agg, prev
    )
    quarantined = (sl(alive) & ~valid_l).astype(jnp.float32)
    return agg, quarantined


# -- host-side (numpy) twins for the socket path, doctor, and parity tests --


def host_weighted_average(trees: list, weights):
    """sum_i w_i * leaf_i over a list of client pytrees (numpy/host)."""
    import numpy as np

    w = np.asarray(weights, dtype=np.float64)
    leaves = [jax.tree.leaves(t) for t in trees]
    treedef = jax.tree.structure(trees[0])
    out = []
    for li in zip(*leaves):
        stack = np.stack([np.asarray(x, dtype=np.float64) for x in li])
        out.append(np.tensordot(w, stack, axes=1).astype(np.asarray(li[0]).dtype))
    return jax.tree.unflatten(treedef, out)


def host_robust_aggregate(
    prev,
    new_trees: list,
    weights,
    steps=None,
    aggregator: str = "weighted",
    update_gate: bool = True,
    gate_norm_factor: float = 10.0,
    update_clip: float = 3.0,
    trim_ratio: float = 0.2,
):
    """Host-side mirror of :func:`robust_aggregate`.

    ``prev`` is the single global pytree; ``new_trees`` is one updated
    pytree per client.  Returns ``(agg_tree, quarantined)`` with
    ``quarantined`` a (n,) bool array.  Same gate math as the in-graph
    version, without the mesh.
    """
    import numpy as np

    n = len(new_trees)
    w = np.asarray(weights, dtype=np.float64)
    steps_arr = (np.asarray(steps, dtype=np.int64) if steps is not None
                 else np.ones(n, dtype=np.int64))
    prev_leaves = jax.tree.leaves(prev)
    treedef = jax.tree.structure(prev)
    client_leaves = [jax.tree.leaves(t) for t in new_trees]

    finite = np.ones(n, dtype=bool)
    sumsq = np.zeros(n, dtype=np.float64)
    for j, p in enumerate(prev_leaves):
        p64 = np.asarray(p, dtype=np.float64)
        if not np.issubdtype(np.asarray(p).dtype, np.floating):
            continue
        for i in range(n):
            d = np.asarray(client_leaves[i][j], dtype=np.float64) - p64
            ok = np.isfinite(d)
            finite[i] &= bool(ok.all())
            sumsq[i] += float(np.sum(np.where(ok, d, 0.0) ** 2))
    norm = np.sqrt(sumsq)

    alive = w > 0
    trained = steps_arr > 0
    if update_gate:
        consider = alive & finite & trained
        med = np.median(norm[consider]) if consider.any() else np.nan
        med_ok = np.isfinite(med) and med > 0
        bad_norm = (
            med_ok
            & trained
            & ((norm > gate_norm_factor * med)
               | (norm * gate_norm_factor < med))
        )
        valid = alive & finite & ~bad_norm
    else:
        valid = alive & finite

    s = w[valid].sum()
    any_valid = s > 0
    if (valid == alive).all():
        w_eff = w.copy()
    else:
        w_eff = np.where(valid, w, 0.0) / max(s, _EPS)

    med_for_clip = (np.median(norm[valid & trained])
                    if (valid & trained).any() else np.nan)
    safe_med = med_for_clip if np.isfinite(med_for_clip) and med_for_clip > 0 else 1.0

    out = []
    for j, p in enumerate(prev_leaves):
        p64 = np.asarray(p, dtype=np.float64)
        dtype = np.asarray(p).dtype
        # sanitized stack: invalid clients contribute prev (finite) values
        stack = np.stack([
            np.asarray(client_leaves[i][j], dtype=np.float64)
            if valid[i] else p64
            for i in range(n)
        ])
        if not any_valid:
            out.append(p64.astype(dtype))
            continue
        if aggregator == "weighted":
            out.append(np.tensordot(w_eff, stack, axes=1).astype(dtype))
        elif aggregator == "clipped":
            scale = np.minimum(1.0, update_clip * safe_med
                               / np.maximum(norm, _EPS))
            cw = w_eff * scale
            out.append((p64 + np.tensordot(cw, stack - p64, axes=1))
                       .astype(dtype))
        elif aggregator == "trimmed":
            m = int(valid.sum())
            t = min(int(np.floor(trim_ratio * m)), max((m - 1) // 2, 0))
            g = np.where(valid.reshape((n,) + (1,) * (stack.ndim - 1)),
                         stack, np.inf)
            g = np.sort(g, axis=0)
            sub = g[t:m - t]
            out.append((sub.sum(axis=0) / max(m - 2 * t, 1)).astype(dtype))
        elif aggregator == "median":
            g = np.where(valid.reshape((n,) + (1,) * (stack.ndim - 1)),
                         stack, np.nan)
            out.append(np.nanmedian(g, axis=0).astype(dtype))
        else:
            raise ValueError(
                f"unknown aggregator {aggregator!r}; "
                "expected weighted|clipped|trimmed|median"
            )
    quarantined = alive & ~valid
    return jax.tree.unflatten(treedef, out), quarantined
