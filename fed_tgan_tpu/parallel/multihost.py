"""Multi-host runtime: ``jax.distributed`` bootstrap + the cross-process
participant mesh.

The reference spans hosts with PyTorch RPC worker processes (reference
Server/dtds/distributed.py:838-891): rank 0 drives, ranks 1..N hold data and
train.  Here the same world maps onto a multi-controller JAX program:

- rank 0 = init-protocol server AND ``jax.distributed`` coordinator; its
  devices exist in the global view but are excluded from the training mesh,
  so it never launches the SPMD program (it services snapshots over the
  native transport instead);
- ranks 1..N = participants; each contributes one local device as one
  position of the global ``clients`` mesh, and the per-round weighted-psum
  FedAvg rides XLA collectives across hosts (gloo on CPU, ICI/DCN on TPU)
  instead of RPC state_dict round-trips.

The ``jax.distributed`` coordinator listens on ``port + 1`` — one above the
native transport's rendezvous port, so one ``-ip``/``-port`` pair configures
both planes.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fed_tgan_tpu.parallel.mesh import CLIENTS_AXIS

JAX_PORT_OFFSET = 1


def initialize_multihost(
    ip: str,
    port: int,
    world_size: int,
    rank: int,
    backend: str | None = None,
    n_local_devices: int = 1,
) -> None:
    """Join the multi-controller world (all ranks, including the server).

    ``backend="cpu"`` provisions ``n_local_devices`` virtual CPU devices and
    selects gloo cross-process collectives — the localhost test path and the
    CI story (SURVEY §4).  On TPU each host's real chips are used as-is.
    Must run before any JAX backend initializes in this process.
    """
    if backend == "cpu":
        import os
        import re

        # same flag surgery as provision_virtual_cpu, but the device-count
        # check must wait until after jax.distributed.initialize (jax.devices
        # would initialize the backend pre-handshake and hang the rendezvous)
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_local_devices}"
        ).strip()
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"{ip}:{port + JAX_PORT_OFFSET}",
        num_processes=world_size,
        process_id=rank,
    )
    # the global topology exchange needs EVERY process to bring its backend
    # up (each publishes its local devices); rank 0 otherwise never would —
    # it only services the transport — and the others would time out waiting
    jax.devices()


def participant_mesh() -> Mesh:
    """1-D ``clients`` mesh over one device per participant process.

    Mesh positions are ordered by process index, so mesh position c belongs
    to transport rank c+1 — the same client numbering as the init protocol.
    """
    by_proc: dict[int, jax.Device] = {}
    for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
        if d.process_index != 0:
            by_proc.setdefault(d.process_index, d)
    if not by_proc:
        raise RuntimeError(
            "no participant devices: the world has a single process (rank 0); "
            "multi-host training needs world_size >= 2"
        )
    devices = [by_proc[p] for p in sorted(by_proc)]
    return Mesh(np.asarray(devices), (CLIENTS_AXIS,))


def from_local_chunk(mesh: Mesh, tree):
    """Assemble global arrays sharded over 'clients' from each process's
    local leading-axis chunk (participants call this; rank 0 owns no shard)."""
    sharding = NamedSharding(mesh, P(CLIENTS_AXIS))
    return jax.tree.map(
        lambda leaf: jax.make_array_from_process_local_data(
            sharding, np.asarray(leaf)
        ),
        tree,
    )


def local_shard(tree):
    """Each leaf's process-local shard with the clients axis squeezed —
    the participant's own view of a mesh-sharded result (post-psum model
    state is replicated, so any participant's shard is the global value).
    Materializes to numpy (blocks until the value is ready)."""
    return jax.tree.map(
        lambda leaf: np.asarray(leaf.addressable_shards[0].data)[0], tree
    )


def local_shard_device(tree):
    """``local_shard`` without leaving the device: the slice is dispatched
    asynchronously on the shard's device, so it composes with still-in-
    flight producers (the pre-sync snapshot dispatch) instead of forcing a
    sync + device-to-host copy + re-upload."""
    return jax.tree.map(
        lambda leaf: leaf.addressable_shards[0].data[0], tree
    )
