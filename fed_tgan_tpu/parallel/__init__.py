from fed_tgan_tpu.parallel.fedavg import weighted_average
from fed_tgan_tpu.parallel.mesh import client_mesh

__all__ = ["client_mesh", "weighted_average"]
