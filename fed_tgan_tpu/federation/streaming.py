"""Streaming client registration: admit newcomers into a resident init.

``federated_initialize`` prices the whole population at once; a production
federation doesn't get that luxury — clients show up while a cohort is
already resident (ROADMAP item 3's churn workload).  An
:class:`OnboardingSession` wraps a finished :class:`FederatedInit` and
admits newcomers in cohort-sized batches at O(batch) cost:

- the **global artifacts stay frozen**: harmonized vocabulary, global
  GMMs, transformer layout (``output_dim`` is a compiled-program shape —
  changing it would force a retrace mid-training), and the pooled
  similarity references.  A newcomer whose categories fall outside the
  frozen vocabulary is rejected (or dropped with ``on_invalid="drop"``) —
  re-harmonizing is a full re-init by design;
- newcomers pass the PR 2 init-payload screen (``_all_finite`` over meta,
  encoded matrix, and fitted GMMs) exactly like remote ranks in
  ``federation/distributed.py`` — a diverged or hostile shard must not
  poison the resident weights;
- their local fits go through the same cohort-batched device path
  (``fit_shards_jax``) and the same content-hashed cache as cold init;
- similarity scores are computed against the FROZEN references (global
  category counts, resident mixture CDF) and appended to the stored *raw*
  score matrices; per-column normalization and the softmax re-run over
  the extended population — bit-equal to the reference math over raw
  distances, so resident scores never need recomputing.

The per-client aggregation weights of residents DO shift when newcomers
join (the softmax renormalizes — that is the paper's semantics, not an
artifact); their encoded matrices and transformers are untouched.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np
from scipy.spatial import distance as _sdistance

from fed_tgan_tpu.data.ingest import TablePreprocessor
from fed_tgan_tpu.features.bgm import N_CLUSTERS, WEIGHT_EPS
from fed_tgan_tpu.features.transformer import ModeNormalizer
from fed_tgan_tpu.federation.distributed import _all_finite
from fed_tgan_tpu.federation.init import (
    FederatedInit,
    _normalize_per_column,
    aggregation_weights,
)
from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.obs.trace import span as _span


class OnboardingSession:
    """Incremental registration over a resident :class:`FederatedInit`.

    ``session.init`` always points at the latest snapshot; every
    :meth:`register_clients` call returns the new one.  The session object
    itself is cheap — all state lives in ``init.onboarding``.
    """

    def __init__(self, init: FederatedInit, cache=None):
        if init.onboarding is None:
            raise ValueError(
                "this FederatedInit predates streaming registration "
                "(no onboarding state); re-run federated_initialize"
            )
        from fed_tgan_tpu.federation.init_cache import InitCache

        self.init = init
        self.cache = InitCache.resolve(cache)

    @property
    def n_clients(self) -> int:
        return len(self.init.rows_per_client)

    def register_clients(
        self,
        newcomers: Sequence[TablePreprocessor],
        on_invalid: str = "raise",
    ) -> FederatedInit:
        """Admit a batch of newcomers; returns the extended snapshot.

        ``on_invalid="drop"`` silently skips shards that fail the screen
        (schema mismatch, unseen categories, non-finite payloads) instead
        of raising; the returned snapshot covers survivors only.
        """
        if on_invalid not in ("raise", "drop"):
            raise ValueError(f"unknown on_invalid policy {on_invalid!r}")
        init, ob = self.init, self.init.onboarding
        params = ob["params"]
        seed, backend = params["seed"], params["backend"]
        cont_idx, cat_idx = ob["cont_idx"], ob["cat_idx"]
        n_res = len(init.rows_per_client)
        t0 = time.perf_counter()

        with _span("init.register_clients", newcomers=len(newcomers)):
            admitted, matrices, metas = self._screen(
                newcomers, cat_idx, on_invalid
            )
            if not admitted:
                return init

            gmms_list = self._fit_locals(admitted, matrices, metas,
                                         cont_idx, seed, backend)
            jsd_new = self._jsd_raw(metas, cat_idx)
            wd_new, stacks_new = self._wd_raw(gmms_list, cont_idx)

            # extended raw scores -> per-column renormalization + softmax
            # over the WHOLE population (reference math over raw distances)
            jsd_raw = np.vstack([ob["jsd_raw"], jsd_new])
            wd_raw = np.vstack([ob["wd_raw"], wd_new])
            rows = list(init.rows_per_client) + [len(m) for m in matrices]
            n_all = len(rows)
            jsd = _normalize_per_column(jsd_raw, n_all)
            wd = _normalize_per_column(wd_raw, n_all)
            weights = (
                aggregation_weights(jsd, wd, rows)
                if params["weighted"] else np.full(n_all, 1.0 / n_all)
            )

            # frozen global layout: newcomers get their own transformer
            # instances and deterministic per-client transform streams
            # (seed + global index), exactly like cold init
            transformers = list(init.transformers)
            client_matrices = list(init.client_matrices)
            global_gmms = transformers[0].column_gmms
            for k, m in enumerate(matrices):
                tf = ModeNormalizer(
                    backend=backend, seed=seed
                ).refit_with_global(init.global_meta, init.encoders,
                                    global_gmms)
                transformers.append(tf)
                if init.client_matrices:
                    client_matrices.append(
                        tf.transform(
                            m, rng=np.random.default_rng(seed + n_res + k)
                        )
                    )

            onboarding = dict(
                ob,
                jsd_raw=jsd_raw,
                wd_raw=wd_raw,
                mix_means=np.concatenate([ob["mix_means"], stacks_new[0]]),
                mix_stds=np.concatenate([ob["mix_stds"], stacks_new[1]]),
                mix_weights=np.concatenate(
                    [ob["mix_weights"], stacks_new[2]]
                ),
            )
            self.init = FederatedInit(
                global_meta=init.global_meta,
                encoders=init.encoders,
                transformers=transformers,
                client_matrices=client_matrices,
                weights=weights,
                jsd=jsd,
                wd=wd,
                rows_per_client=rows,
                jsd_raw=jsd_raw,
                wd_raw=wd_raw,
                onboarding=onboarding,
            )
        _emit_event("init_phase", phase="register_clients",
                    seconds=round(time.perf_counter() - t0, 6),
                    clients=n_all, rows=int(np.sum(rows)))
        return self.init

    def score_clients(
        self, shards: Sequence[TablePreprocessor],
        alive: Optional[np.ndarray] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Score shards against the frozen references WITHOUT mutation.

        Runs the full admission screen (schema, vocabulary, finiteness —
        ``on_invalid="raise"``), the cache-aware local GMM fits, and the
        raw JSD / sketch-WD scoring, but touches no session state.  This
        is the per-window drift probe: re-score a resident's CURRENT
        shard and compare the rows against the stored baseline in
        ``init.onboarding["jsd_raw"]/["wd_raw"]``.  Unchanged shards are
        content-hash cache hits, so a window's cost is dominated by the
        clients that actually drifted.

        Returns ``(jsd_raw_rows, wd_raw_rows)``, one row per shard.
        """
        ob = self.init.onboarding
        params = ob["params"]
        cont_idx, cat_idx = ob["cont_idx"], ob["cat_idx"]
        admitted, matrices, metas = self._screen(shards, cat_idx, "raise")
        gmms_list = self._fit_locals(admitted, matrices, metas, cont_idx,
                                     params["seed"], params["backend"])
        jsd_rows = self._jsd_raw(metas, cat_idx)
        wd_rows, _ = self._wd_raw(gmms_list, cont_idx, alive=alive)
        return jsd_rows, wd_rows

    def rescore_client(
        self, idx: int, shard: TablePreprocessor
    ) -> FederatedInit:
        """Online refit for a DRIFTED resident; returns the new snapshot.

        The frozen global layout survives (vocabulary, global GMMs,
        ``output_dim`` — compiled-program shapes never move); what refits
        is everything local to client ``idx``: its encoded matrix is
        re-transformed through a fresh frozen-layout ``ModeNormalizer``
        (each drifted row re-normalized by its newly-assigned mode — the
        online refit of mode-specific normalization), its local GMMs are
        re-fitted for similarity scoring, its rows in the raw score
        matrices and the resident mixture stacks are REPLACED (not
        appended), and the per-column normalization + softmax re-run over
        the population — so every client's weight reflects the drifted
        distribution within the same window that detected it.
        """
        init, ob = self.init, self.init.onboarding
        if not 0 <= idx < len(init.rows_per_client):
            raise IndexError(f"client index {idx} out of range")
        params = ob["params"]
        seed, backend = params["seed"], params["backend"]
        cont_idx, cat_idx = ob["cont_idx"], ob["cat_idx"]
        t0 = time.perf_counter()
        with _span("init.rescore_client", client=idx):
            admitted, matrices, metas = self._screen([shard], cat_idx,
                                                     "raise")
            gmms_list = self._fit_locals(admitted, matrices, metas,
                                         cont_idx, seed, backend)
            jsd_row = self._jsd_raw(metas, cat_idx)
            wd_row, stacks_new = self._wd_raw(gmms_list, cont_idx)

            jsd_raw = np.array(ob["jsd_raw"], copy=True)
            wd_raw = np.array(ob["wd_raw"], copy=True)
            jsd_raw[idx] = jsd_row[0]
            wd_raw[idx] = wd_row[0]
            rows = list(init.rows_per_client)
            rows[idx] = len(matrices[0])
            n_all = len(rows)
            jsd = _normalize_per_column(jsd_raw, n_all)
            wd = _normalize_per_column(wd_raw, n_all)
            weights = (
                aggregation_weights(jsd, wd, rows)
                if params["weighted"] else np.full(n_all, 1.0 / n_all)
            )

            transformers = list(init.transformers)
            client_matrices = list(init.client_matrices)
            tf = ModeNormalizer(
                backend=backend, seed=seed
            ).refit_with_global(init.global_meta, init.encoders,
                                transformers[0].column_gmms)
            transformers[idx] = tf
            if client_matrices:
                client_matrices[idx] = tf.transform(
                    matrices[0], rng=np.random.default_rng(seed + idx)
                )

            mix = [np.array(ob[k], copy=True)
                   for k in ("mix_means", "mix_stds", "mix_weights")]
            for stack, new in zip(mix, stacks_new):
                stack[idx] = new[0]
            onboarding = dict(
                ob, jsd_raw=jsd_raw, wd_raw=wd_raw,
                mix_means=mix[0], mix_stds=mix[1], mix_weights=mix[2],
            )
            self.init = FederatedInit(
                global_meta=init.global_meta,
                encoders=init.encoders,
                transformers=transformers,
                client_matrices=client_matrices,
                weights=weights,
                jsd=jsd,
                wd=wd,
                rows_per_client=rows,
                jsd_raw=jsd_raw,
                wd_raw=wd_raw,
                onboarding=onboarding,
            )
        _emit_event("init_phase", phase="rescore_client",
                    seconds=round(time.perf_counter() - t0, 6),
                    clients=n_all, rows=int(np.sum(rows)))
        return self.init

    # ------------------------------------------------------------ internals

    def _reject(self, why: str, on_invalid: str) -> bool:
        """True = drop silently, False never returned on raise."""
        if on_invalid == "raise":
            raise ValueError(why)
        _emit_event("client_dropped", reason=why, where="register_clients")
        return True

    def _screen(self, newcomers, cat_idx, on_invalid):
        """Schema + vocabulary + finiteness screen (the PR 2 payload
        screen, applied at admission instead of at transport gather)."""
        init = self.init
        gsig = [
            (c.name, "continous" if c.is_continuous else "categorical")
            for c in init.global_meta.columns
        ]
        vocabs = [
            {str(v) for v in c.i2s}
            for c in init.global_meta.columns if not c.is_continuous
        ]
        admitted, matrices, metas = [], [], []
        for c in newcomers:
            meta = c.local_meta()
            sig = [(col.get("column_name", ""), col["type"])
                   for col in meta["columns"]]
            if sig != gsig:
                if self._reject(
                    f"newcomer {meta.get('name', '?')!r}: schema mismatch "
                    f"with the frozen global meta", on_invalid,
                ):
                    continue
            unseen = []
            cursor = 0
            for col in meta["columns"]:
                if col["type"] != "categorical":
                    continue
                extra = set(col["i2s"]) - vocabs[cursor]
                if extra:
                    unseen.append((col["column_name"], sorted(extra)[:5]))
                cursor += 1
            if unseen:
                if self._reject(
                    f"newcomer {meta.get('name', '?')!r}: categories outside "
                    f"the frozen global vocabulary {unseen}; re-run full "
                    f"init to re-harmonize", on_invalid,
                ):
                    continue
            matrix, this_cat_idx, _ = c.encode(init.encoders)
            if this_cat_idx != list(cat_idx):
                if self._reject(
                    f"newcomer {meta.get('name', '?')!r}: categorical "
                    f"column positions {this_cat_idx} != frozen {cat_idx}",
                    on_invalid,
                ):
                    continue
            if not (_all_finite(meta) and _all_finite(matrix)):
                if self._reject(
                    f"newcomer {meta.get('name', '?')!r}: non-finite init "
                    f"payload", on_invalid,
                ):
                    continue
            admitted.append(c)
            matrices.append(matrix)
            metas.append(meta)
        return admitted, matrices, metas

    def _fit_locals(self, admitted, matrices, metas, cont_idx, seed,
                    backend):
        """Cohort-batched (and cache-aware) local fits for the newcomers."""
        from fed_tgan_tpu.features.bgm import fit_column_gmms
        from fed_tgan_tpu.federation.init_cache import shard_fingerprint

        gmms_list: list[Optional[dict]] = [None] * len(admitted)
        fps = []
        if self.cache is not None:
            for k, c in enumerate(admitted):
                fp = shard_fingerprint(c, n_components=N_CLUSTERS,
                                       backend=backend, seed=seed)
                fps.append(fp)
                hit = self.cache.load_client(fp)
                if hit is not None:
                    gmms_list[k] = hit["gmms"]
        need = [k for k in range(len(admitted)) if gmms_list[k] is None]
        if need:
            if backend == "jax":
                from fed_tgan_tpu.features.bgm_jax import fit_shards_jax

                fitted = fit_shards_jax(
                    [[matrices[k][:, j] for j in cont_idx] for k in need],
                    n_components=N_CLUSTERS, eps=WEIGHT_EPS,
                )
            else:
                fitted = [
                    fit_column_gmms(
                        [matrices[k][:, j] for j in cont_idx],
                        N_CLUSTERS, WEIGHT_EPS, backend, seed,
                    )
                    for k in need
                ]
            for k, gl in zip(need, fitted):
                gmms_list[k] = dict(zip(cont_idx, gl))
                # a diverged fit is screened exactly like a bad payload
                if not _all_finite({j: g.to_dict()
                                    for j, g in gmms_list[k].items()}):
                    raise ValueError(
                        f"newcomer {k}: non-finite local GMM fit"
                    )
                if self.cache is not None:
                    self.cache.store_client(fps[k], metas[k], gmms_list[k])
        if self.cache is not None:
            self.cache.flush_events()
        return gmms_list

    def _jsd_raw(self, metas, cat_idx) -> np.ndarray:
        """Raw JSD of each newcomer against the FROZEN global counts."""
        init, ob = self.init, self.init.onboarding
        cat_cols_meta = [
            (cursor, j) for cursor, j in enumerate(ob["cat_idx"])
        ]
        out = np.zeros((len(metas), len(cat_cols_meta)))
        for r, meta in enumerate(metas):
            for cursor, j in cat_cols_meta:
                counts = ob["cat_counts"][cursor]
                enc = init.encoders[cursor]
                vec = np.zeros_like(counts)
                for key, count in meta["columns"][j]["i2s"].items():
                    vec[int(enc.transform([str(key)])[0])] = count
                out[r, cursor] = _sdistance.jensenshannon(counts, vec)
        return np.nan_to_num(out, nan=0.0)

    def _wd_raw(self, gmms_list, cont_idx, alive=None):
        """Raw WD of each newcomer against the FROZEN resident pool: one
        sketch program where residents carry the pool weights and every
        newcomer carries omega 0 (scored, but not reshaping the pool).
        ``alive`` (elastic churn) masks departed residents out of the
        pooled reference while keeping the stacks index-stable."""
        from fed_tgan_tpu.federation import sketch as _sketch

        ob = self.init.onboarding
        client_gmms = [
            [g.get(j) if isinstance(g, dict) else None for j in range(
                max(cont_idx, default=-1) + 1)]
            for g in gmms_list
        ]
        stacks_new = _sketch.stack_client_gmms(
            client_gmms, cont_idx, n_components=N_CLUSTERS
        )
        means = np.concatenate([ob["mix_means"], stacks_new[0]])
        stds = np.concatenate([ob["mix_stds"], stacks_new[1]])
        weights = np.concatenate([ob["mix_weights"], stacks_new[2]])
        n_res = ob["mix_means"].shape[0]
        omega = np.concatenate(
            [_sketch.live_omega(self.init.rows_per_client, alive),
             np.zeros(len(gmms_list))]
        )
        wd_all = _sketch.wd_sketch(
            None, None, cont_idx, omega=omega,
            stacks=(means, stds, weights),
        )
        return wd_all[n_res:], stacks_new
