"""Multi-host federated init over the native transport.

Runs the same protocol as ``federated_initialize`` but with real process/host
separation, mirroring the reference's RPC choreography (reference
Server/dtds/distributed.py:866-874):

  server                          clients (rank 1..N)
  ------                          -------------------
  gather local metas         <--  send local_meta()
  harmonize categories
  broadcast meta+encoders    -->  encode data, fit local GMMs
  gather (gmms, n_rows)      <--  send transformer information
  harmonize continuous
  broadcast global GMMs      -->  refit transformer, transform data
  compute weights
  broadcast weights          -->  ready to join the device mesh

After init, every client holds its encoded shard + transformer + the global
aggregation weights; training then happens on the JAX mesh (each host runs
its mesh slice; across hosts XLA collectives ride ICI/DCN via
``jax.distributed``), NOT over this transport.
"""

from __future__ import annotations

import numpy as np

from fed_tgan_tpu.data.ingest import TablePreprocessor
from fed_tgan_tpu.data.schema import TableMeta
from fed_tgan_tpu.features.transformer import ModeNormalizer
from fed_tgan_tpu.federation.init import (
    aggregation_weights,
    harmonize_categories,
    harmonize_continuous,
)
from fed_tgan_tpu.runtime.transport import ClientTransport, ServerTransport


def server_initialize(
    transport: ServerTransport,
    seed: int = 0,
    weighted: bool = True,
    backend: str = "sklearn",
    run_name: str | None = None,
) -> dict:
    """Drive the init protocol from rank 0; returns the global artifacts.

    ``run_name`` rides along with the harmonized meta so every client labels
    its artifacts consistently with the server's (clients may be launched
    with differently-named shard CSVs)."""
    local_metas = transport.gather()

    global_meta_dict, encoders, jsd = harmonize_categories(local_metas)
    transport.broadcast(
        {"meta": global_meta_dict, "encoders": encoders, "run_name": run_name}
    )

    infos = transport.gather()  # [{"gmms": [...], "rows": int}]
    client_gmms = [i["gmms"] for i in infos]
    rows = [i["rows"] for i in infos]

    global_gmms, wd = harmonize_continuous(client_gmms, rows, seed=seed, backend=backend)
    transport.broadcast({"gmms": global_gmms})

    # pooled conditional-sampling counts: the reference server rebuilds its
    # Cond on the FULL training table (distributed.py:565-580); here the
    # clients exchange additive one-hot counts instead of rows, so the
    # pooled distribution is identical without centralizing any data
    cond_counts = sum(transport.gather())

    if weighted:
        weights = aggregation_weights(jsd, wd, rows)
    else:
        weights = np.full(len(rows), 1.0 / len(rows))
    transport.broadcast(
        {"weights": weights, "rows_per_client": rows, "cond_counts": cond_counts}
    )

    return {
        "global_meta": TableMeta.from_json_dict(global_meta_dict),
        "encoders": encoders,
        "global_gmms": global_gmms,
        "weights": weights,
        "jsd": jsd,
        "wd": wd,
        "rows_per_client": rows,
        "cond_counts": cond_counts,
    }


def client_initialize(
    transport: ClientTransport,
    preprocessor: TablePreprocessor,
    seed: int = 0,
    backend: str = "sklearn",
) -> dict:
    """Participate in the init protocol; returns this shard's artifacts."""
    transport.send_obj(preprocessor.local_meta())

    msg = transport.recv_obj()
    global_meta = TableMeta.from_json_dict(msg["meta"])
    encoders = msg["encoders"]
    run_name = msg.get("run_name")

    matrix, cat_idx, _ = preprocessor.encode(encoders)
    local_tf = ModeNormalizer(backend=backend, seed=seed).fit(matrix, cat_idx)
    transport.send_obj({"gmms": local_tf.column_gmms, "rows": len(matrix)})

    global_gmms = transport.recv_obj()["gmms"]
    transformer = ModeNormalizer(backend=backend, seed=seed).refit_with_global(
        global_meta, encoders, global_gmms
    )
    # rank r holds client index r-1: the SAME rng stream the in-process
    # federated_initialize gives that client, so a multihost world encodes
    # (and therefore trains) bit-identically to the single-process path
    encoded = transformer.transform(
        matrix, rng=np.random.default_rng(seed + transport.rank - 1)
    )

    from fed_tgan_tpu.ops.segments import SegmentSpec
    from fed_tgan_tpu.train.sampler import CondSampler

    spec = SegmentSpec.from_output_info(transformer.output_info)
    transport.send_obj(CondSampler.count_matrix(encoded, spec))

    final = transport.recv_obj()

    return {
        "global_meta": global_meta,
        "encoders": encoders,
        "transformer": transformer,
        "matrix": encoded,
        "weights": final["weights"],
        "rows_per_client": final["rows_per_client"],
        "cond_counts": final["cond_counts"],
        "run_name": run_name,
    }
