"""Multi-host federated init over the native transport.

Runs the same protocol as ``federated_initialize`` but with real process/host
separation, mirroring the reference's RPC choreography (reference
Server/dtds/distributed.py:866-874):

  server                          clients (rank 1..N)
  ------                          -------------------
  gather local metas         <--  send local_meta()
  harmonize categories
  broadcast meta+encoders    -->  encode data, fit local GMMs
  gather (gmms, n_rows)      <--  send transformer information
  harmonize continuous
  broadcast global GMMs      -->  refit transformer, transform data
  compute weights
  broadcast weights          -->  ready to join the device mesh

After init, every client holds its encoded shard + transformer + the global
aggregation weights; training then happens on the JAX mesh (each host runs
its mesh slice; across hosts XLA collectives ride ICI/DCN via
``jax.distributed``), NOT over this transport.
"""

from __future__ import annotations

import numpy as np

from fed_tgan_tpu.data.ingest import TablePreprocessor
from fed_tgan_tpu.data.schema import TableMeta
from fed_tgan_tpu.features.transformer import ModeNormalizer
from fed_tgan_tpu.federation.init import (
    aggregation_weights,
    harmonize_categories,
    harmonize_continuous,
)
from fed_tgan_tpu.runtime.transport import ClientTransport, ServerTransport


def _check_floor(
    transport: ServerTransport, phase: str, min_clients: int | None,
    newly_dropped: list[int],
) -> None:
    import logging

    if newly_dropped:
        logging.getLogger("fed_tgan_tpu.federation").warning(
            "init %s: dropped client rank(s) %s; continuing with %d survivors",
            phase, newly_dropped, len(transport.live_ranks()),
        )
    floor = transport.n_clients if min_clients is None else min_clients
    live = len(transport.live_ranks())
    if live < floor or live == 0:
        raise RuntimeError(
            f"aborting during init ({phase}): {live} live clients is below "
            f"min_clients={floor} (dropped: {sorted(transport.dropped)})"
        )


def _gather_phase(
    transport: ServerTransport, phase: str, min_clients: int | None
) -> dict[int, object]:
    """One fault-tolerant gather: returns ``{rank: payload}`` over the
    ranks that answered.  With ``min_clients`` set, a missing client is
    dropped (logged, weights later renormalized over survivors); without
    it, ANY drop aborts cleanly — the reference's all-or-nothing contract,
    minus the hang."""
    results, newly_dropped = transport.gather_surviving()
    _check_floor(transport, phase, min_clients, newly_dropped)
    return results


def _all_finite(obj) -> bool:
    """Every float reachable in ``obj`` (containers, arrays, plain objects)
    is finite.  Non-numeric leaves pass vacuously."""
    if isinstance(obj, (bool, int, str, bytes)) or obj is None:
        return True
    if isinstance(obj, float):
        return np.isfinite(obj)
    if isinstance(obj, np.ndarray):
        return not np.issubdtype(obj.dtype, np.floating) or bool(
            np.isfinite(obj).all()
        )
    if isinstance(obj, np.generic):
        return not np.issubdtype(obj.dtype, np.floating) or bool(
            np.isfinite(obj)
        )
    if isinstance(obj, dict):
        return all(_all_finite(v) for v in obj.values())
    if isinstance(obj, (list, tuple, set)):
        return all(_all_finite(v) for v in obj)
    d = getattr(obj, "__dict__", None)
    return _all_finite(d) if d is not None else True


def _validate_gathered(
    transport: ServerTransport, phase: str, min_clients: int | None,
    results: dict[int, object],
) -> dict[int, object]:
    """Screen gathered init payloads for NaN/Inf — a client whose local
    GMM fit diverged (or that is hostile) must not poison the harmonized
    global artifacts.  Offenders are dropped exactly like a dead socket:
    logged, excluded, weights renormalized over survivors, subject to the
    same ``min_clients`` floor."""
    bad = [r for r in sorted(results) if not _all_finite(results[r])]
    for r in bad:
        transport.mark_dropped(r, f"non-finite payload in init {phase}")
        del results[r]
    _check_floor(transport, phase + "-validate", min_clients, bad)
    return results


def _broadcast_phase(
    transport: ServerTransport, obj: object, phase: str,
    min_clients: int | None,
) -> None:
    """Fault-tolerant counterpart of :func:`_gather_phase` for the
    server->clients direction: an unreachable rank is dropped instead of
    aborting the broadcast, subject to the same survivor floor."""
    newly_dropped = transport.broadcast_surviving(obj)
    _check_floor(transport, phase, min_clients, newly_dropped)


def server_initialize(
    transport: ServerTransport,
    seed: int = 0,
    weighted: bool = True,
    backend: str = "sklearn",
    run_name: str | None = None,
    min_clients: int | None = None,
) -> dict:
    """Drive the init protocol from rank 0; returns the global artifacts.

    ``run_name`` rides along with the harmonized meta so every client labels
    its artifacts consistently with the server's (clients may be launched
    with differently-named shard CSVs).

    ``min_clients`` enables graceful degradation: a client that misses its
    deadline or dies mid-protocol is dropped and the similarity weights are
    computed over the survivors (the paper's weighting restricted to live
    ranks); the run aborts cleanly if survivors fall below the floor.  With
    ``min_clients=None`` (default) every client is required — a dropout
    aborts with a clear error instead of hanging."""
    metas = _gather_phase(transport, "gather-metas", min_clients)
    meta_ranks = sorted(metas)

    global_meta_dict, encoders, jsd = harmonize_categories(
        [metas[r] for r in meta_ranks]
    )
    jsd_by_rank = dict(zip(meta_ranks, np.asarray(jsd)))
    _broadcast_phase(
        transport,
        {"meta": global_meta_dict, "encoders": encoders, "run_name": run_name},
        "broadcast-meta", min_clients,
    )

    infos = _validate_gathered(
        transport, "gather-gmms", min_clients,
        _gather_phase(transport, "gather-gmms", min_clients),
    )
    info_ranks = sorted(infos)  # [{"gmms": [...], "rows": int}] by rank
    client_gmms = [infos[r]["gmms"] for r in info_ranks]
    rows_by_rank = {r: infos[r]["rows"] for r in info_ranks}

    global_gmms, wd = harmonize_continuous(
        client_gmms, [rows_by_rank[r] for r in info_ranks], seed=seed,
        backend=backend,
    )
    wd_by_rank = dict(zip(info_ranks, np.asarray(wd)))
    _broadcast_phase(transport, {"gmms": global_gmms}, "broadcast-gmms",
                     min_clients)

    # pooled conditional-sampling counts: the reference server rebuilds its
    # Cond on the FULL training table (distributed.py:565-580); here the
    # clients exchange additive one-hot counts instead of rows, so the
    # pooled distribution is identical without centralizing any data
    counts = _validate_gathered(
        transport, "gather-cond-counts", min_clients,
        _gather_phase(transport, "gather-cond-counts", min_clients),
    )
    cond_counts = sum(counts[r] for r in sorted(counts))

    # the weighting runs over the ranks that survived EVERY phase; a rank
    # that contributed metas/GMMs but died later is excluded and the
    # similarity-derived weights renormalize over the survivors
    final_ranks = [r for r in transport.live_ranks() if r in wd_by_rank]
    jsd_live = np.asarray([jsd_by_rank[r] for r in final_ranks])
    wd_live = np.asarray([wd_by_rank[r] for r in final_ranks])
    rows = [rows_by_rank[r] for r in final_ranks]
    if weighted:
        weights = aggregation_weights(jsd_live, wd_live, rows)
    else:
        weights = np.full(len(rows), 1.0 / len(rows))
    _broadcast_phase(
        transport,
        {"weights": weights, "rows_per_client": rows, "cond_counts": cond_counts,
         "live_ranks": final_ranks},
        "broadcast-weights", min_clients,
    )

    return {
        "global_meta": TableMeta.from_json_dict(global_meta_dict),
        "encoders": encoders,
        "global_gmms": global_gmms,
        "weights": weights,
        "jsd": jsd_live,
        "wd": wd_live,
        "rows_per_client": rows,
        "cond_counts": cond_counts,
        "live_ranks": final_ranks,
        "dropped": sorted(transport.dropped),
    }


def client_initialize(
    transport: ClientTransport,
    preprocessor: TablePreprocessor,
    seed: int = 0,
    backend: str = "sklearn",
) -> dict:
    """Participate in the init protocol; returns this shard's artifacts."""
    transport.send_obj(preprocessor.local_meta())

    msg = transport.recv_obj()
    global_meta = TableMeta.from_json_dict(msg["meta"])
    encoders = msg["encoders"]
    run_name = msg.get("run_name")

    matrix, cat_idx, _ = preprocessor.encode(encoders)
    local_tf = ModeNormalizer(backend=backend, seed=seed).fit(matrix, cat_idx)
    transport.send_obj({"gmms": local_tf.column_gmms, "rows": len(matrix)})

    global_gmms = transport.recv_obj()["gmms"]
    transformer = ModeNormalizer(backend=backend, seed=seed).refit_with_global(
        global_meta, encoders, global_gmms
    )
    # rank r holds client index r-1: the SAME rng stream the in-process
    # federated_initialize gives that client, so a multihost world encodes
    # (and therefore trains) bit-identically to the single-process path
    encoded = transformer.transform(
        matrix, rng=np.random.default_rng(seed + transport.rank - 1)
    )

    from fed_tgan_tpu.ops.segments import SegmentSpec
    from fed_tgan_tpu.train.sampler import CondSampler

    spec = SegmentSpec.from_output_info(transformer.output_info)
    transport.send_obj(CondSampler.count_matrix(encoded, spec))

    final = transport.recv_obj()

    return {
        "global_meta": global_meta,
        "encoders": encoders,
        "transformer": transformer,
        "matrix": encoded,
        "weights": final["weights"],
        "rows_per_client": final["rows_per_client"],
        "cond_counts": final["cond_counts"],
        "run_name": run_name,
    }
