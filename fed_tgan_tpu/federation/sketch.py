"""Device-computed similarity sketches for O(cohort) onboarding.

The exact init path scores every client by Wasserstein distance between a
rows-proportional Monte-Carlo sample of its column GMM and the pooled
sample over all clients (reference Server/dtds/distributed.py:689-765) —
N host passes over O(total rows) draws per column, the second superlinear
term of the onboarding wall.

The sketch uses what the fit already gives us analytically: client i's
fitted column GMM has CDF ``F_i(x) = sum_k w_ik Phi((x - mu_ik)/s_ik)``,
the pooled reference is the rows-weighted mixture ``F_bar = sum_i w_i F_i``,
and ``W1(F_i, F_bar) = integral |F_i(x) - F_bar(x)| dx`` — evaluated on a
shared per-column grid in ONE jitted device program over (clients x
columns x grid).  The exact path's sampled WD is the Monte-Carlo estimate
of this same integral, so sketch scores agree in expectation and the
downstream softmax weights agree to sampling noise (gated in
tests/test_onboard.py and the BENCH_r13 parity record).

The pooled global refit keeps a matching budget trick: the pool IS a known
mixture (N x K components with weights ``omega_i * w_ik``), so one
fixed-budget vectorized draw from it replaces the per-client sampling
loop, making the global refit cost independent of N.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

from fed_tgan_tpu.obs.trace import span as _span

GRID_POINTS = 512
POOL_BUDGET = 65536
_TAIL_SIGMAS = 4.5


def stack_client_gmms(
    client_gmms: Sequence[Sequence[object]],
    cont_cols: Sequence[int],
    n_components: Optional[int] = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stack per-client per-column GMMs into (N, C, K) arrays.

    Degenerate clients (component clamp on tiny shards) pad with zero-weight
    components (std 1 so the CDF term stays finite); zero weight keeps them
    out of both the sketch and the pooled draw.
    """
    n_clients = len(client_gmms)
    if n_components is None:
        n_components = max(
            client_gmms[i][j].n_components
            for i in range(n_clients)
            for j in cont_cols
        )
    shape = (n_clients, len(cont_cols), n_components)
    means = np.zeros(shape, dtype=np.float64)
    stds = np.ones(shape, dtype=np.float64)
    weights = np.zeros(shape, dtype=np.float64)
    for i in range(n_clients):
        for cursor, j in enumerate(cont_cols):
            g = client_gmms[i][j]
            k = g.n_components
            means[i, cursor, :k] = g.means
            stds[i, cursor, :k] = np.maximum(g.stds, 1e-9)
            w = np.maximum(g.weights, 0.0)
            weights[i, cursor, :k] = w / max(w.sum(), 1e-300)
    return means, stds, weights


def live_omega(
    rows_per_client: Sequence[int],
    alive: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Rows-proportional pool weights restricted to the LIVE residents.

    The elastic-federation drift probe scores clients against the resident
    mixture pool every window; once members depart, their fitted mixtures
    remain in the stacks (indices stay stable) but must stop shaping the
    pooled reference CDF — a mask here is cheaper and steadier than
    re-stacking the survivor subset.  ``alive=None`` keeps everyone.
    """
    omega = np.asarray(rows_per_client, dtype=np.float64)
    if alive is not None:
        omega = omega * np.asarray(alive, dtype=bool)
    total = omega.sum()
    if total <= 0.0:
        raise ValueError("no live residents: pooled reference is empty")
    return omega / total


def _wd_impl(means, stds, weights, omega, grid):
    """(N, C, K) mixtures + (N,) pool weights + (C, G) grid -> (N, C) W1."""
    import jax.numpy as jnp
    from jax import lax
    from jax.scipy.stats import norm

    n, c, k = means.shape
    g = grid.shape[1]

    def accumulate(acc, i):
        z = (grid[None, :, :] - means[:, :, i, None]) / stds[:, :, i, None]
        return acc + weights[:, :, i, None] * norm.cdf(z), None

    cdf, _ = lax.scan(
        accumulate, jnp.zeros((n, c, g), means.dtype), jnp.arange(k)
    )
    pooled = jnp.einsum("ncg,n->cg", cdf, omega)
    dx = (grid[:, -1] - grid[:, 0]) / (g - 1)
    return jnp.abs(cdf - pooled[None, :, :]).sum(axis=-1) * dx[None, :]


@functools.lru_cache(maxsize=None)
def _wd_fn():
    import jax

    return jax.jit(_wd_impl)


def column_grids(
    means: np.ndarray,
    stds: np.ndarray,
    weights: np.ndarray,
    grid_points: int = GRID_POINTS,
) -> np.ndarray:
    """Shared (C, G) integration grid spanning every active component's
    mean +- 4.5 sigma (host-side — bounds are data-dependent shapes)."""
    valid = weights > 0.0
    lo_all = np.where(valid, means - _TAIL_SIGMAS * stds, np.inf)
    hi_all = np.where(valid, means + _TAIL_SIGMAS * stds, -np.inf)
    lo = lo_all.min(axis=(0, 2))
    hi = hi_all.max(axis=(0, 2))
    bad = ~np.isfinite(lo) | ~np.isfinite(hi) | (hi <= lo)
    lo = np.where(bad, np.where(np.isfinite(lo), lo, 0.0) - 0.5, lo)
    hi = np.where(bad, lo + 1.0, hi)
    steps = np.arange(grid_points, dtype=np.float64) / (grid_points - 1)
    return lo[:, None] + (hi - lo)[:, None] * steps[None, :]


def wd_sketch(
    client_gmms: Sequence[Sequence[object]],
    rows_per_client: Sequence[int],
    cont_cols: Sequence[int],
    grid_points: int = GRID_POINTS,
    omega: Optional[np.ndarray] = None,
    stacks: Optional[tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> np.ndarray:
    """Raw (unnormalized) per-client per-column W1 against the pooled
    reference, one batched device program.

    ``omega`` overrides the pool weights (streaming registration passes 0
    for newcomers so they score against the frozen resident reference).
    """
    import jax
    import jax.numpy as jnp

    means, stds, weights = (
        stacks if stacks is not None
        else stack_client_gmms(client_gmms, cont_cols)
    )
    n_clients = means.shape[0]
    if not len(cont_cols):
        return np.zeros((n_clients, 0), dtype=np.float64)
    if omega is None:
        omega = np.asarray(rows_per_client, dtype=np.float64)
        omega = omega / omega.sum()
    grid = column_grids(means, stds, weights, grid_points)
    with _span("init.wd_sketch", clients=n_clients, columns=len(cont_cols)):
        wd = np.asarray(
            jax.device_get(
                _wd_fn()(
                    jnp.asarray(means, jnp.float32),
                    jnp.asarray(stds, jnp.float32),
                    jnp.asarray(weights, jnp.float32),
                    jnp.asarray(omega, jnp.float32),
                    jnp.asarray(grid, jnp.float32),
                )
            ),
            dtype=np.float64,
        )
    return wd


def pooled_mixture_sample(
    client_gmms: Sequence[Sequence[object]],
    rows_per_client: Sequence[int],
    cont_cols: Sequence[int],
    budget: int = POOL_BUDGET,
    seed: int = 0,
    stacks: Optional[tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> list[np.ndarray]:
    """One budgeted vectorized draw per column from the pooled mixture
    (components ``omega_i * w_ik``) — the global-refit input whose size no
    longer grows with the population."""
    means, stds, weights = (
        stacks if stacks is not None
        else stack_client_gmms(client_gmms, cont_cols)
    )
    omega = np.asarray(rows_per_client, dtype=np.float64)
    omega = omega / omega.sum()
    rng = np.random.default_rng(seed)
    out = []
    for cursor in range(len(cont_cols)):
        flat_w = (omega[:, None] * weights[:, cursor, :]).reshape(-1)
        flat_w = flat_w / flat_w.sum()
        comp = rng.choice(flat_w.size, size=budget, p=flat_w)
        out.append(
            rng.normal(
                means[:, cursor, :].reshape(-1)[comp],
                stds[:, cursor, :].reshape(-1)[comp],
            )
        )
    return out
