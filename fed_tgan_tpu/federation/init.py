"""Host-side federated initialization.

The one-time init phase of Fed-TGAN, exactly the reference's math:

1. **Category harmonization** (reference Server/dtds/distributed.py:592-684
   ``uniform_meta_category``): merge per-client category frequency dicts,
   order the global vocabulary by total frequency, fit one label encoder per
   categorical column, and score every client by per-column Jensen-Shannon
   distance between its frequency vector and the global one.
2. **Continuous harmonization** (reference :689-765
   ``uniform_continuous_gmm``): per continuous column, draw a
   rows-proportional sample from every client's local GMM, pool them, refit
   a global Bayesian GMM on the pool, and score every client by Wasserstein
   distance between its sample and the pool.
3. **Aggregation weights** (reference :767-783
   ``calculate_final_weights_for_aggregation``):
   ``softmax((1 - d_i/sum(d)) * n_i/N)`` where ``d_i`` sums the client's
   normalized JSD and WD scores.

This phase is object-valued, one-time and cold, so it stays on host
(numpy + sklearn) exchanged over the runtime transport; only its *outputs*
(encoded shards, sampler tables, weights) move to the device mesh.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np
from scipy.spatial import distance as _sdistance
from scipy.stats import wasserstein_distance

from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.ingest import TablePreprocessor
from fed_tgan_tpu.data.schema import TableMeta
from fed_tgan_tpu.features.bgm import (
    N_CLUSTERS,
    WEIGHT_EPS,
    ColumnGMM,
    fit_column_gmms,
    resolved_init_workers,
)
from fed_tgan_tpu.features.transformer import ModeNormalizer
from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.obs.trace import span as _span


def _normalize_per_column(dist: np.ndarray, n_clients: int) -> np.ndarray:
    """Reference's per-column normalization incl. the zero-sum fallback
    (distributed.py:642-657): each column's distances are divided by their
    sum across clients; all-zero columns (single participant) become
    1/n_clients for everyone."""
    dist = dist.astype(np.float64).copy()
    col_sum = dist.sum(axis=0)
    nonzero = col_sum != 0
    dist[:, nonzero] = dist[:, nonzero] / col_sum[nonzero]
    dist[:, ~nonzero] = 1.0 / n_clients
    return dist


def harmonize_categories(
    local_metas: Sequence[dict],
) -> tuple[dict, list[CategoryEncoder], np.ndarray]:
    """Merge per-client local metas into the harmonized global meta.

    Returns (global_meta_dict, encoders, jsd):
    - global_meta_dict: first client's meta with each categorical ``i2s``
      replaced by the globally-frequency-ordered category list;
    - encoders: one per categorical column, fitted on the global vocabulary;
    - jsd: (n_clients, n_categorical) per-column normalized JSD scores.
    """
    n_clients = len(local_metas)
    base = copy.deepcopy(local_metas[0])

    # The merge below walks columns positionally (like the reference,
    # Server/dtds/distributed.py:596-639, which assumes it silently); a
    # client whose columns are named or ordered differently would get its
    # frequency dicts credited to the wrong columns, so check explicitly.
    def _signature(meta: dict) -> list[tuple[str, str]]:
        return [(c.get("column_name", ""), c["type"]) for c in meta["columns"]]

    base_sig = _signature(base)
    for ci, meta in enumerate(local_metas[1:], start=1):
        sig = _signature(meta)
        if sig != base_sig:
            mismatches = [
                f"position {k}: client0 has {a!r}, client{ci} has {b!r}"
                for k, (a, b) in enumerate(zip(base_sig, sig))
                if a != b
            ] or [f"column count {len(base_sig)} vs {len(sig)}"]
            raise ValueError(
                "client metas disagree on column names/types/order; category "
                "harmonization merges positionally, so all clients must "
                "present the same schema in the same order. "
                + "; ".join(mismatches[:5])
            )

    cat_cols = [i for i, c in enumerate(base["columns"]) if c["type"] == "categorical"]

    encoders: list[CategoryEncoder] = []
    jsd = np.zeros((n_clients, len(cat_cols)))

    for cursor, col_idx in enumerate(cat_cols):
        merged: dict[str, int] = {}
        for meta in local_metas:
            for key, count in meta["columns"][col_idx]["i2s"].items():
                merged[key] = merged.get(key, 0) + int(count)

        ordered = [k for k, _ in sorted(merged.items(), key=lambda kv: kv[1], reverse=True)]
        base["columns"][col_idx]["i2s"] = ordered
        base["columns"][col_idx]["size"] = len(ordered)

        enc = CategoryEncoder.fit(ordered)
        encoders.append(enc)

        vocab = len(ordered)
        vec_global = np.zeros(vocab)
        codes = {k: int(enc.transform([k])[0]) for k in ordered}
        for key, count in merged.items():
            vec_global[codes[key]] = count

        for ci, meta in enumerate(local_metas):
            vec = np.zeros(vocab)
            for key, count in meta["columns"][col_idx]["i2s"].items():
                vec[codes[key]] = count
            jsd[ci, cursor] = _sdistance.jensenshannon(vec_global, vec)

    jsd = np.nan_to_num(jsd, nan=0.0)
    return base, encoders, _normalize_per_column(jsd, n_clients)


def harmonize_continuous(
    client_gmms: Sequence[Sequence[Optional[ColumnGMM]]],
    rows_per_client: Sequence[int],
    seed: int = 0,
    n_components: int = N_CLUSTERS,
    eps: float = WEIGHT_EPS,
    backend: str = "sklearn",
) -> tuple[list[Optional[ColumnGMM]], np.ndarray]:
    """Pool rows-proportional samples of the per-client column GMMs, refit
    global GMMs, and score clients by Wasserstein distance to the pool.

    ``client_gmms[i][j]`` is client i's GMM for column j (None when
    discrete).  Returns (global_gmms_per_column, wd) where wd is
    (n_clients, n_continuous) normalized.
    """
    n_clients = len(client_gmms)
    n_cols = len(client_gmms[0])
    n_sample = int(np.sum(rows_per_client))
    by_number = [float(r) / n_sample for r in rows_per_client]
    rng = np.random.default_rng(seed)

    cont_cols = [j for j in range(n_cols) if client_gmms[0][j] is not None]
    wd = np.zeros((n_clients, len(cont_cols)))
    global_gmms: list[Optional[ColumnGMM]] = [None] * n_cols

    # sampling + WD stay serial (they share one rng stream and are cheap).
    # Pooled refits go to a process pool only when workers are opted in —
    # batching every column's pooled sample first would otherwise raise peak
    # memory from O(rows) to O(cols x rows) for nothing.  The jax backend
    # always batches: the whole refit is one vmapped device program.
    batch = resolved_init_workers() > 1 or backend == "jax"
    pooled_cols = []
    for cursor, j in enumerate(cont_cols):
        samples = [
            client_gmms[i][j].sample(int(n_sample * by_number[i]), rng)
            for i in range(n_clients)
        ]
        pooled = np.concatenate(samples)
        for i in range(n_clients):
            wd[i, cursor] = wasserstein_distance(pooled, samples[i])
        if batch:
            pooled_cols.append(pooled)
        else:
            global_gmms[j] = fit_column_gmms(
                [pooled], n_components=n_components, eps=eps, backend=backend,
                seed=seed,
            )[0]

    if batch:
        refits = fit_column_gmms(
            pooled_cols, n_components=n_components, eps=eps, backend=backend,
            seed=seed,
        )
        for j, gmm in zip(cont_cols, refits):
            global_gmms[j] = gmm

    return global_gmms, _normalize_per_column(wd, n_clients)


def aggregation_weights(
    jsd: np.ndarray, wd: np.ndarray, rows_per_client: Sequence[int]
) -> np.ndarray:
    """``softmax((1 - d_i/sum(d)) * n_i/N)`` — reference distributed.py:767-783."""
    combo = jsd.sum(axis=1) + wd.sum(axis=1)
    total = combo.sum()
    by_number = np.asarray(rows_per_client, dtype=np.float64)
    by_number = by_number / by_number.sum()
    raw = (1.0 - combo / total) * by_number
    e = np.exp(raw)
    return e / e.sum()


def renormalize_weights(weights: np.ndarray, alive: np.ndarray) -> np.ndarray:
    """Restrict aggregation weights to the surviving clients and rescale to
    sum 1 — the paper's similarity weighting over live ranks only.  ``alive``
    is a boolean mask aligned with ``weights``; dropped clients get exactly
    0 so their (stale) models contribute nothing to the psum."""
    w = np.asarray(weights, dtype=np.float64) * np.asarray(alive, dtype=bool)
    total = w.sum()
    if total <= 0.0:
        raise ValueError("no surviving clients: all aggregation weight lost")
    return (w / total).astype(np.float32)


@dataclass
class FederatedInit:
    """Everything the device-mesh trainer needs after init."""

    global_meta: TableMeta
    encoders: list[CategoryEncoder]
    transformers: list[ModeNormalizer]
    client_matrices: list[np.ndarray]  # transformed (encoded) per-client data
    weights: np.ndarray  # (n_clients,) aggregation weights
    jsd: np.ndarray
    wd: np.ndarray
    rows_per_client: list[int] = field(default_factory=list)

    @property
    def output_info(self):
        return self.transformers[0].output_info


def federated_initialize(
    clients: Sequence[TablePreprocessor],
    seed: int = 0,
    backend: str = "sklearn",
    weighted: bool = True,
) -> FederatedInit:
    """Run the full init protocol over in-process client shards.

    Mirrors the server's startup sequence (reference distributed.py:866-874):
    uniform_meta_category -> uniform_continuous_gmm -> refit_local_transformer
    -> calculate_final_weights_for_aggregation.  ``weighted=False`` yields
    uniform FedAvg weights (the reference's ``average_model_ordinary``).
    """
    n_clients = len(clients)

    # each protocol phase is spanned + journaled (`init_phase`) so
    # `obs report` can decompose the onboarding wall at scale -- the
    # clocks are host-side (this whole path is numpy/sklearn)
    def _phase_done(phase: str, t0: float) -> None:
        _emit_event("init_phase", phase=phase,
                    seconds=round(time.perf_counter() - t0, 6),
                    clients=n_clients)

    t0 = time.perf_counter()
    with _span("init.category_harmonize", clients=n_clients):
        local_metas = [c.local_meta() for c in clients]
        global_meta_dict, encoders, jsd = harmonize_categories(local_metas)
    _phase_done("category_harmonize", t0)

    t0 = time.perf_counter()
    with _span("init.encode", clients=n_clients):
        encoded = [c.encode(encoders) for c in clients]
        matrices = [m for m, _, _ in encoded]
        cat_idx = encoded[0][1]
        rows_per_client = [len(m) for m in matrices]
    _phase_done("encode", t0)

    # local per-column GMM fits (client-side in the reference) -- the
    # dominant init cost at scale (one BGM fit per client per column)
    t0 = time.perf_counter()
    with _span("init.local_bgm_fit", clients=n_clients):
        local_tfs = [
            ModeNormalizer(backend=backend, seed=seed).fit(m, cat_idx)
            for m in matrices
        ]
        client_gmms = [tf.column_gmms for tf in local_tfs]
    _phase_done("local_bgm_fit", t0)

    t0 = time.perf_counter()
    with _span("init.continuous_harmonize", clients=n_clients):
        global_gmms, wd = harmonize_continuous(
            client_gmms, rows_per_client, seed=seed, backend=backend
        )
    _phase_done("continuous_harmonize", t0)

    t0 = time.perf_counter()
    with _span("init.refit_transform", clients=n_clients):
        global_meta = TableMeta.from_json_dict(global_meta_dict)
        transformers = []
        client_matrices = []
        for i in range(n_clients):
            tf = ModeNormalizer(backend=backend, seed=seed).refit_with_global(
                global_meta, encoders, global_gmms
            )
            transformers.append(tf)
            client_matrices.append(
                tf.transform(matrices[i], rng=np.random.default_rng(seed + i))
            )
    _phase_done("refit_transform", t0)

    t0 = time.perf_counter()
    with _span("init.aggregation_weights", clients=n_clients):
        if weighted:
            weights = aggregation_weights(jsd, wd, rows_per_client)
        else:
            weights = np.full(n_clients, 1.0 / n_clients)
    _phase_done("aggregation_weights", t0)

    return FederatedInit(
        global_meta=global_meta,
        encoders=encoders,
        transformers=transformers,
        client_matrices=client_matrices,
        weights=weights,
        jsd=jsd,
        wd=wd,
        rows_per_client=rows_per_client,
    )
