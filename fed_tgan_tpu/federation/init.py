"""Host-side federated initialization.

The one-time init phase of Fed-TGAN, exactly the reference's math:

1. **Category harmonization** (reference Server/dtds/distributed.py:592-684
   ``uniform_meta_category``): merge per-client category frequency dicts,
   order the global vocabulary by total frequency, fit one label encoder per
   categorical column, and score every client by per-column Jensen-Shannon
   distance between its frequency vector and the global one.
2. **Continuous harmonization** (reference :689-765
   ``uniform_continuous_gmm``): per continuous column, draw a
   rows-proportional sample from every client's local GMM, pool them, refit
   a global Bayesian GMM on the pool, and score every client by Wasserstein
   distance between its sample and the pool.
3. **Aggregation weights** (reference :767-783
   ``calculate_final_weights_for_aggregation``):
   ``softmax((1 - d_i/sum(d)) * n_i/N)`` where ``d_i`` sums the client's
   normalized JSD and WD scores.

This phase is object-valued, one-time and cold, so it stays on host
(numpy + sklearn) exchanged over the runtime transport; only its *outputs*
(encoded shards, sampler tables, weights) move to the device mesh.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np
from scipy.spatial import distance as _sdistance
from scipy.stats import wasserstein_distance

from fed_tgan_tpu.data.encoders import CategoryEncoder
from fed_tgan_tpu.data.ingest import TablePreprocessor
from fed_tgan_tpu.data.schema import TableMeta
from fed_tgan_tpu.features.bgm import (
    N_CLUSTERS,
    WEIGHT_EPS,
    ColumnGMM,
    fit_column_gmms,
    resolved_init_workers,
)
from fed_tgan_tpu.features.transformer import ModeNormalizer
from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.obs.trace import span as _span


def _normalize_per_column(dist: np.ndarray, n_clients: int) -> np.ndarray:
    """Reference's per-column normalization incl. the zero-sum fallback
    (distributed.py:642-657): each column's distances are divided by their
    sum across clients; all-zero columns (single participant) become
    1/n_clients for everyone."""
    dist = dist.astype(np.float64).copy()
    col_sum = dist.sum(axis=0)
    nonzero = col_sum != 0
    dist[:, nonzero] = dist[:, nonzero] / col_sum[nonzero]
    dist[:, ~nonzero] = 1.0 / n_clients
    return dist


def harmonize_categories(
    local_metas: Sequence[dict],
    raw: bool = False,
):
    """Merge per-client local metas into the harmonized global meta.

    Returns (global_meta_dict, encoders, jsd):
    - global_meta_dict: first client's meta with each categorical ``i2s``
      replaced by the globally-frequency-ordered category list;
    - encoders: one per categorical column, fitted on the global vocabulary;
    - jsd: (n_clients, n_categorical) per-column normalized JSD scores.

    With ``raw=True`` two extras follow: the unnormalized JSD matrix and
    the per-column global count vectors (indexed by encoder code) — the
    frozen reference streaming registration scores newcomers against.
    """
    n_clients = len(local_metas)
    base = copy.deepcopy(local_metas[0])

    # The merge below walks columns positionally (like the reference,
    # Server/dtds/distributed.py:596-639, which assumes it silently); a
    # client whose columns are named or ordered differently would get its
    # frequency dicts credited to the wrong columns, so check explicitly.
    def _signature(meta: dict) -> list[tuple[str, str]]:
        return [(c.get("column_name", ""), c["type"]) for c in meta["columns"]]

    base_sig = _signature(base)
    for ci, meta in enumerate(local_metas[1:], start=1):
        sig = _signature(meta)
        if sig != base_sig:
            mismatches = [
                f"position {k}: client0 has {a!r}, client{ci} has {b!r}"
                for k, (a, b) in enumerate(zip(base_sig, sig))
                if a != b
            ] or [f"column count {len(base_sig)} vs {len(sig)}"]
            raise ValueError(
                "client metas disagree on column names/types/order; category "
                "harmonization merges positionally, so all clients must "
                "present the same schema in the same order. "
                + "; ".join(mismatches[:5])
            )

    cat_cols = [i for i, c in enumerate(base["columns"]) if c["type"] == "categorical"]

    encoders: list[CategoryEncoder] = []
    jsd = np.zeros((n_clients, len(cat_cols)))
    global_counts: list[np.ndarray] = []

    for cursor, col_idx in enumerate(cat_cols):
        merged: dict[str, int] = {}
        for meta in local_metas:
            for key, count in meta["columns"][col_idx]["i2s"].items():
                merged[key] = merged.get(key, 0) + int(count)

        ordered = [k for k, _ in sorted(merged.items(), key=lambda kv: kv[1], reverse=True)]
        base["columns"][col_idx]["i2s"] = ordered
        base["columns"][col_idx]["size"] = len(ordered)

        enc = CategoryEncoder.fit(ordered)
        encoders.append(enc)

        vocab = len(ordered)
        vec_global = np.zeros(vocab)
        codes = {k: int(enc.transform([k])[0]) for k in ordered}
        for key, count in merged.items():
            vec_global[codes[key]] = count
        global_counts.append(vec_global)

        for ci, meta in enumerate(local_metas):
            vec = np.zeros(vocab)
            for key, count in meta["columns"][col_idx]["i2s"].items():
                vec[codes[key]] = count
            jsd[ci, cursor] = _sdistance.jensenshannon(vec_global, vec)

    jsd = np.nan_to_num(jsd, nan=0.0)
    if raw:
        return (base, encoders, _normalize_per_column(jsd, n_clients),
                jsd, global_counts)
    return base, encoders, _normalize_per_column(jsd, n_clients)


def harmonize_continuous(
    client_gmms: Sequence[Sequence[Optional[ColumnGMM]]],
    rows_per_client: Sequence[int],
    seed: int = 0,
    n_components: int = N_CLUSTERS,
    eps: float = WEIGHT_EPS,
    backend: str = "sklearn",
    method: str = "exact",
    pool_budget: int = 0,
    grid_points: int = 0,
    raw: bool = False,
):
    """Score clients by Wasserstein distance to the pooled reference and
    refit global GMMs on it.

    ``client_gmms[i][j]`` is client i's GMM for column j (None when
    discrete).  Returns (global_gmms_per_column, wd) where wd is
    (n_clients, n_continuous) normalized; ``raw=True`` appends the
    unnormalized matrix.

    ``method="exact"`` is the reference protocol: draw a rows-proportional
    Monte-Carlo sample from every client, pool, empirical WD per client,
    refit on the full pool — O(N) host passes over O(total rows) draws.
    ``method="sketch"`` computes the same scores from the *analytic*
    mixture CDFs in one batched device program and refits on a
    fixed-budget draw from the pooled mixture (see federation/sketch.py),
    making this phase O(cohort-batch) instead of O(N).
    """
    n_clients = len(client_gmms)
    n_cols = len(client_gmms[0])
    n_sample = int(np.sum(rows_per_client))

    cont_cols = [j for j in range(n_cols) if client_gmms[0][j] is not None]
    wd = np.zeros((n_clients, len(cont_cols)))
    global_gmms: list[Optional[ColumnGMM]] = [None] * n_cols

    if method == "sketch":
        from fed_tgan_tpu.federation import sketch as _sketch

        stacks = _sketch.stack_client_gmms(client_gmms, cont_cols)
        wd = _sketch.wd_sketch(
            client_gmms, rows_per_client, cont_cols,
            grid_points=grid_points or _sketch.GRID_POINTS, stacks=stacks,
        )
        budget = min(pool_budget or _sketch.POOL_BUDGET, n_sample)
        pooled_cols = _sketch.pooled_mixture_sample(
            client_gmms, rows_per_client, cont_cols, budget=budget,
            seed=seed, stacks=stacks,
        )
        refits = fit_column_gmms(
            pooled_cols, n_components=n_components, eps=eps, backend=backend,
            seed=seed,
        )
        for j, gmm in zip(cont_cols, refits):
            global_gmms[j] = gmm
        if raw:
            return global_gmms, _normalize_per_column(wd, n_clients), wd
        return global_gmms, _normalize_per_column(wd, n_clients)
    if method != "exact":
        raise ValueError(f"unknown similarity method {method!r}")

    by_number = [float(r) / n_sample for r in rows_per_client]
    rng = np.random.default_rng(seed)

    # sampling + WD stay serial (they share one rng stream and are cheap).
    # Pooled refits go to a process pool only when workers are opted in —
    # batching every column's pooled sample first would otherwise raise peak
    # memory from O(rows) to O(cols x rows) for nothing.  The jax backend
    # always batches: the whole refit is one vmapped device program.
    batch = resolved_init_workers() > 1 or backend == "jax"
    pooled_cols = []
    for cursor, j in enumerate(cont_cols):
        samples = [
            client_gmms[i][j].sample(int(n_sample * by_number[i]), rng)
            for i in range(n_clients)
        ]
        pooled = np.concatenate(samples)
        for i in range(n_clients):
            wd[i, cursor] = wasserstein_distance(pooled, samples[i])
        if batch:
            pooled_cols.append(pooled)
        else:
            global_gmms[j] = fit_column_gmms(
                [pooled], n_components=n_components, eps=eps, backend=backend,
                seed=seed,
            )[0]

    if batch:
        refits = fit_column_gmms(
            pooled_cols, n_components=n_components, eps=eps, backend=backend,
            seed=seed,
        )
        for j, gmm in zip(cont_cols, refits):
            global_gmms[j] = gmm

    if raw:
        return global_gmms, _normalize_per_column(wd, n_clients), wd
    return global_gmms, _normalize_per_column(wd, n_clients)


def aggregation_weights(
    jsd: np.ndarray, wd: np.ndarray, rows_per_client: Sequence[int]
) -> np.ndarray:
    """``softmax((1 - d_i/sum(d)) * n_i/N)`` — reference distributed.py:767-783."""
    combo = jsd.sum(axis=1) + wd.sum(axis=1)
    total = combo.sum()
    by_number = np.asarray(rows_per_client, dtype=np.float64)
    by_number = by_number / by_number.sum()
    raw = (1.0 - combo / total) * by_number
    e = np.exp(raw)
    return e / e.sum()


def recompute_weights(
    jsd_raw: np.ndarray,
    wd_raw: np.ndarray,
    rows_per_client: Sequence[int],
    alive: Optional[np.ndarray] = None,
    weighted: bool = True,
) -> np.ndarray:
    """Similarity weights from RAW per-column distances, restricted to the
    live population.

    The drift detector re-scores clients per window (fresh ``wd_raw`` rows
    from the sketch scorer, fresh ``jsd_raw`` from category counts) and
    needs the paper's full pipeline — per-column normalization over the
    CURRENT population, then the softmax combine — rather than the frozen
    init-time weights.  ``alive=None`` means everyone; a departed client
    keeps its raw score rows (the matrices stay packed) but exits both the
    normalization and the final renormalization, so survivors see exactly
    the weights a from-scratch init over the survivor set would produce.
    ``weighted=False`` (uniform FedAvg runs) skips similarity and splits
    mass evenly over the live clients.
    """
    n = len(rows_per_client)
    if alive is None:
        alive = np.ones(n, dtype=bool)
    alive = np.asarray(alive, dtype=bool)
    if not weighted:
        return renormalize_weights(np.full(n, 1.0 / n), alive)
    idx = np.nonzero(alive)[0]
    if idx.size == 0:
        raise ValueError("no surviving clients: all aggregation weight lost")
    live_jsd = _normalize_per_column(
        np.asarray(jsd_raw, dtype=np.float64)[idx], idx.size)
    live_wd = _normalize_per_column(
        np.asarray(wd_raw, dtype=np.float64)[idx], idx.size)
    live_rows = [rows_per_client[i] for i in idx]
    w = np.zeros(n, dtype=np.float32)
    w[idx] = aggregation_weights(live_jsd, live_wd, live_rows)
    return w


def renormalize_weights(weights: np.ndarray, alive: np.ndarray) -> np.ndarray:
    """Restrict aggregation weights to the surviving clients and rescale to
    sum 1 — the paper's similarity weighting over live ranks only.  ``alive``
    is a boolean mask aligned with ``weights``; dropped clients get exactly
    0 so their (stale) models contribute nothing to the psum."""
    w = np.asarray(weights, dtype=np.float64) * np.asarray(alive, dtype=bool)
    total = w.sum()
    if total <= 0.0:
        raise ValueError("no surviving clients: all aggregation weight lost")
    return (w / total).astype(np.float32)


@dataclass
class FederatedInit:
    """Everything the device-mesh trainer needs after init."""

    global_meta: TableMeta
    encoders: list[CategoryEncoder]
    transformers: list[ModeNormalizer]
    client_matrices: list[np.ndarray]  # transformed (encoded) per-client data
    weights: np.ndarray  # (n_clients,) aggregation weights
    jsd: np.ndarray
    wd: np.ndarray
    rows_per_client: list[int] = field(default_factory=list)
    # raw (pre-normalization) similarity scores + the frozen global
    # references — what streaming registration (federation/streaming.py)
    # needs to admit newcomers without recomputing the resident population
    jsd_raw: Optional[np.ndarray] = None
    wd_raw: Optional[np.ndarray] = None
    onboarding: Optional[dict] = None

    @property
    def output_info(self):
        return self.transformers[0].output_info


def _onboarding_state(client_gmms, cont_idx, cat_idx, jsd_raw, wd_raw,
                      cat_counts, seed, backend, weighted, similarity):
    """Frozen references streaming registration scores newcomers against."""
    from fed_tgan_tpu.federation import sketch as _sketch

    mix_means, mix_stds, mix_weights = _sketch.stack_client_gmms(
        client_gmms, cont_idx, n_components=N_CLUSTERS
    )
    return {
        "jsd_raw": np.asarray(jsd_raw, dtype=np.float64),
        "wd_raw": np.asarray(wd_raw, dtype=np.float64),
        "cat_counts": [np.asarray(c, dtype=np.float64) for c in cat_counts],
        "mix_means": mix_means,
        "mix_stds": mix_stds,
        "mix_weights": mix_weights,
        "cont_idx": list(cont_idx),
        "cat_idx": list(cat_idx),
        "params": {"seed": seed, "backend": backend, "weighted": weighted,
                   "similarity": similarity},
    }


def _restore_from_cache(entry: dict, backend: str, seed: int,
                        transform_matrices: bool) -> FederatedInit:
    """Rebuild a FederatedInit from a global cache entry.

    Matrices come back byte-for-byte from the entry (never re-transformed),
    so a warm run is bit-identical to the cold run that stored it.
    """
    payload, arrays = entry["payload"], entry["arrays"]
    global_meta = TableMeta.from_json_dict(payload["global_meta"])
    encoders = [
        CategoryEncoder.fit([str(v) for v in cmeta.i2s])
        for cmeta in global_meta.columns
        if not cmeta.is_continuous
    ]
    n_cols = len(global_meta.columns)
    global_gmms: list[Optional[ColumnGMM]] = [None] * n_cols
    for j_str, d in payload["gmms"].items():
        global_gmms[int(j_str)] = ColumnGMM.from_dict(d)
    rows_per_client = [int(r) for r in payload["rows_per_client"]]
    n_clients = len(rows_per_client)
    transformers = [
        ModeNormalizer(backend=backend, seed=seed).refit_with_global(
            global_meta, encoders, global_gmms
        )
        for _ in range(n_clients)
    ]
    client_matrices = (
        [arrays[f"m{i}"] for i in range(n_clients)]
        if transform_matrices else []
    )
    onboarding = {
        "jsd_raw": arrays["jsd_raw"],
        "wd_raw": arrays["wd_raw"],
        "cat_counts": [
            arrays[f"cat_counts{c}"] for c in range(len(encoders))
        ],
        "mix_means": arrays["mix_means"],
        "mix_stds": arrays["mix_stds"],
        "mix_weights": arrays["mix_weights"],
        "cont_idx": [int(j) for j in payload["cont_idx"]],
        "cat_idx": [int(j) for j in payload["cat_idx"]],
        "params": payload["params"],
    }
    return FederatedInit(
        global_meta=global_meta,
        encoders=encoders,
        transformers=transformers,
        client_matrices=client_matrices,
        weights=arrays["weights"],
        jsd=arrays["jsd"],
        wd=arrays["wd"],
        rows_per_client=rows_per_client,
        jsd_raw=arrays["jsd_raw"],
        wd_raw=arrays["wd_raw"],
        onboarding=onboarding,
    )


def federated_initialize(
    clients: Sequence[TablePreprocessor],
    seed: int = 0,
    backend: str = "sklearn",
    weighted: bool = True,
    similarity: str = "exact",
    batch_fit: Optional[bool] = None,
    cache=None,
    transform_matrices: bool = True,
) -> FederatedInit:
    """Run the full init protocol over in-process client shards.

    Mirrors the server's startup sequence (reference distributed.py:866-874):
    uniform_meta_category -> uniform_continuous_gmm -> refit_local_transformer
    -> calculate_final_weights_for_aggregation.  ``weighted=False`` yields
    uniform FedAvg weights (the reference's ``average_model_ordinary``).

    Onboarding-at-scale knobs (all default to the reference behavior):

    - ``similarity="sketch"`` scores WD from the analytic mixture CDFs in
      one batched device program instead of N Monte-Carlo host passes
      (federation/sketch.py) — same scores in expectation, O(cohort) cost;
    - ``batch_fit`` (default: on for the jax backend) fits every client's
      continuous columns in a handful of batched device dispatches
      (``fit_shards_jax``) instead of one jit round-trip per client;
    - ``cache`` (a directory path or :class:`InitCache`) persists
      content-hashed client fits and the finished global state; warm hits
      restore bit-identical encoded matrices without refitting;
    - ``transform_matrices=False`` skips materializing the per-client
      encoded matrices (registration-only / encoded-only onboarding, e.g.
      scoring a huge population before deciding which cohort trains).
    """
    from fed_tgan_tpu.federation.init_cache import (
        InitCache,
        global_key,
        shard_fingerprint,
    )

    n_clients = len(clients)
    total_rows = int(sum(c.n_rows for c in clients))
    cache = InitCache.resolve(cache)
    use_batch = (batch_fit if batch_fit is not None
                 else backend == "jax") and backend == "jax"
    if similarity not in ("exact", "sketch"):
        raise ValueError(f"unknown similarity {similarity!r}")

    # each protocol phase is spanned + journaled (`init_phase`) so
    # `obs report` can decompose the onboarding wall at scale -- the
    # clocks are host-side (this whole path is numpy/sklearn)
    def _phase_done(phase: str, t0: float) -> None:
        _emit_event("init_phase", phase=phase,
                    seconds=round(time.perf_counter() - t0, 6),
                    clients=n_clients, rows=total_rows)

    fps: list[str] = []
    gkey = None
    cached_clients: dict[int, dict] = {}
    if cache is not None:
        t0 = time.perf_counter()
        with _span("init.cache_lookup", clients=n_clients):
            fps = [
                shard_fingerprint(c, n_components=N_CLUSTERS,
                                  backend=backend, seed=seed)
                for c in clients
            ]
            gkey = global_key(
                fps, seed=seed, backend=backend, weighted=weighted,
                similarity=similarity, matrices=transform_matrices,
            )
            entry = cache.load_global(gkey)
            if entry is None:
                for i, fp in enumerate(fps):
                    hit = cache.load_client(fp)
                    if hit is not None:
                        cached_clients[i] = hit
        _phase_done("cache_lookup", t0)
        if entry is not None:
            t0 = time.perf_counter()
            with _span("init.cache_restore", clients=n_clients):
                init = _restore_from_cache(
                    entry, backend=backend, seed=seed,
                    transform_matrices=transform_matrices,
                )
            _phase_done("cache_restore", t0)
            cache.flush_events()
            return init

    t0 = time.perf_counter()
    with _span("init.category_harmonize", clients=n_clients):
        local_metas = [
            cached_clients[i]["local_meta"] if i in cached_clients
            else c.local_meta()
            for i, c in enumerate(clients)
        ]
        global_meta_dict, encoders, jsd, jsd_raw, cat_counts = (
            harmonize_categories(local_metas, raw=True)
        )
    _phase_done("category_harmonize", t0)

    t0 = time.perf_counter()
    with _span("init.encode", clients=n_clients):
        encoded = [c.encode(encoders) for c in clients]
        matrices = [m for m, _, _ in encoded]
        cat_idx = encoded[0][1]
        rows_per_client = [len(m) for m in matrices]
    _phase_done("encode", t0)

    # local per-column GMM fits (client-side in the reference) -- the
    # dominant init cost at scale.  Batched mode flattens the whole cohort
    # into shape-bucketed device dispatches; cached clients skip the fit
    # entirely and inject their stored GMMs into the transformer.
    t0 = time.perf_counter()
    with _span("init.local_bgm_fit", clients=n_clients):
        n_cols = matrices[0].shape[1]
        cont_idx = [j for j in range(n_cols) if j not in set(cat_idx)]
        gmms_by_client: dict[int, dict] = {
            i: hit["gmms"] for i, hit in cached_clients.items()
        }
        need = [i for i in range(n_clients) if i not in gmms_by_client]
        if use_batch and need:
            from fed_tgan_tpu.features.bgm_jax import fit_shards_jax

            fitted = fit_shards_jax(
                [[matrices[i][:, j] for j in cont_idx] for i in need],
                n_components=N_CLUSTERS, eps=WEIGHT_EPS,
            )
            for i, gl in zip(need, fitted):
                gmms_by_client[i] = dict(zip(cont_idx, gl))
        local_tfs = []
        for i in range(n_clients):
            pre = gmms_by_client.get(i)
            tf = ModeNormalizer(backend=backend, seed=seed).fit(
                matrices[i], cat_idx, column_gmms=pre
            )
            local_tfs.append(tf)
            if pre is None:
                all_gmms = tf.column_gmms
                gmms_by_client[i] = {j: all_gmms[j] for j in cont_idx}
        client_gmms = [tf.column_gmms for tf in local_tfs]
        if cache is not None:
            for i in range(n_clients):
                if i not in cached_clients:
                    cache.store_client(fps[i], local_metas[i],
                                       gmms_by_client[i])
    _phase_done("local_bgm_fit", t0)

    t0 = time.perf_counter()
    with _span("init.continuous_harmonize", clients=n_clients):
        global_gmms, wd, wd_raw = harmonize_continuous(
            client_gmms, rows_per_client, seed=seed, backend=backend,
            method=similarity, raw=True,
        )
    _phase_done("continuous_harmonize", t0)

    t0 = time.perf_counter()
    with _span("init.refit_transform", clients=n_clients):
        global_meta = TableMeta.from_json_dict(global_meta_dict)
        transformers = []
        client_matrices = []
        for i in range(n_clients):
            tf = ModeNormalizer(backend=backend, seed=seed).refit_with_global(
                global_meta, encoders, global_gmms
            )
            transformers.append(tf)
            if transform_matrices:
                client_matrices.append(
                    tf.transform(matrices[i], rng=np.random.default_rng(seed + i))
                )
    _phase_done("refit_transform", t0)

    t0 = time.perf_counter()
    with _span("init.aggregation_weights", clients=n_clients):
        if weighted:
            weights = aggregation_weights(jsd, wd, rows_per_client)
        else:
            weights = np.full(n_clients, 1.0 / n_clients)
        onboarding = _onboarding_state(
            client_gmms, cont_idx, cat_idx, jsd_raw, wd_raw, cat_counts,
            seed, backend, weighted, similarity,
        )
    _phase_done("aggregation_weights", t0)

    init = FederatedInit(
        global_meta=global_meta,
        encoders=encoders,
        transformers=transformers,
        client_matrices=client_matrices,
        weights=weights,
        jsd=jsd,
        wd=wd,
        rows_per_client=rows_per_client,
        jsd_raw=jsd_raw,
        wd_raw=wd_raw,
        onboarding=onboarding,
    )

    if cache is not None:
        t0 = time.perf_counter()
        with _span("init.cache_store", clients=n_clients):
            payload = {
                "global_meta": global_meta_dict,
                "gmms": {
                    str(j): g.to_dict()
                    for j, g in enumerate(global_gmms) if g is not None
                },
                "cont_idx": list(cont_idx),
                "cat_idx": list(cat_idx),
                "rows_per_client": list(map(int, rows_per_client)),
                "params": onboarding["params"],
            }
            arrays = {
                "jsd": jsd, "wd": wd, "jsd_raw": jsd_raw, "wd_raw": wd_raw,
                "weights": np.asarray(weights, dtype=np.float64),
                "mix_means": onboarding["mix_means"],
                "mix_stds": onboarding["mix_stds"],
                "mix_weights": onboarding["mix_weights"],
            }
            for c, vec in enumerate(cat_counts):
                arrays[f"cat_counts{c}"] = vec
            if transform_matrices:
                for i, m in enumerate(client_matrices):
                    arrays[f"m{i}"] = m
            cache.store_global(gkey, payload, arrays)
        _phase_done("cache_store", t0)
        cache.flush_events()
    return init
