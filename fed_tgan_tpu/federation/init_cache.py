"""Content-hashed encoded-shard cache for federated onboarding.

Re-running init over an unchanged population (restarts, resumed sweeps,
late joiners next to a resident cohort) repays the full fit cost for
nothing: the inputs are bit-identical.  This cache keys every piece of
init-time state on content fingerprints in the style of
``runtime/checkpoint.checkpoint_fingerprint`` — sha256 over the actual
bytes that determine the result — so a hit is *provably* the same
computation and the restored output is bit-identical (test-gated):

- **client entries** (``client-<fp>.json``): one per shard fingerprint,
  holding the local meta dict and the per-column local GMM fits.  The
  fingerprint covers the preprocessed shard bytes, the schema knobs, the
  fit hyperparameters and :data:`ENCODER_VERSION`, so a schema or encoder
  change invalidates by construction (no TTLs, no mtime races).  Local
  fits depend on nothing global (label encoding touches categorical
  columns only), which is what makes per-client reuse sound when the
  population around a client changes.
- **global entries** (``global-<gkey>.npz``): keyed over the *ordered*
  client fingerprint list plus the init parameters; holds the harmonized
  meta, global GMMs, similarity scores, aggregation weights and the
  transformed per-client matrices — a warm re-run restores the whole
  ``FederatedInit`` without touching a single shard fit.

Every payload publishes atomically (tmp + ``os.replace``) next to a
manifest recording the payload's sha256; a mismatch or unreadable file is
counted as ``corrupt`` and treated as a miss (the caller refits and the
store overwrites the rotten entry).  ``testing/faults.py`` can truncate
the n-th store (``corrupt_cache:nth=N``) to drill exactly that path.

Outcomes are journaled as aggregate ``init_cache`` events (op x scope
counts, never one line per client) summarized by ``obs report``.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Optional

import numpy as np

from fed_tgan_tpu.obs.journal import emit as _emit_event

# bump when the encoded representation or the fit pipeline changes shape:
# every fingerprint embeds it, so old entries all miss at once
ENCODER_VERSION = 1

_DIGEST_CHARS = 16


def shard_fingerprint(client, *, n_components: int, backend: str,
                      seed: int) -> str:
    """Content hash of one participant's preprocessed shard.

    Streams the post-``__post_init__`` dataframe (the actual fit input)
    plus every knob that shapes the local fit; raw-bytes identity of the
    source CSV is neither necessary nor sufficient — two CSVs that
    preprocess identically SHOULD share an entry.
    """
    h = hashlib.sha256()
    h.update(
        f"encoder-v{ENCODER_VERSION}|{backend}|{seed}|{n_components}".encode()
    )
    df = client.df
    h.update(repr(list(df.columns)).encode())
    h.update(repr(sorted(map(str, client.categorical_columns))).encode())
    h.update(repr(sorted(map(str, client.non_negative_columns))).encode())
    h.update(repr(sorted(client.date_formats.items())).encode())
    for name in df.columns:
        col = df[name]
        if col.dtype.kind in "ifbu":
            h.update(np.ascontiguousarray(col.to_numpy()).tobytes())
        else:
            h.update("\x1f".join(col.astype(str)).encode())
        h.update(b"\x1e")
    return h.hexdigest()[:_DIGEST_CHARS]


def global_key(fingerprints: list[str], **params) -> str:
    """Key over the ORDERED client fingerprints + init parameters (client
    order feeds per-client transform seeds and the weight vector layout,
    so a permuted population is a different computation)."""
    h = hashlib.sha256()
    h.update("|".join(fingerprints).encode())
    h.update(repr(sorted(params.items())).encode())
    return h.hexdigest()[:_DIGEST_CHARS]


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _maybe_corrupt(path: str) -> None:
    from fed_tgan_tpu.testing.faults import active_plan

    plan = active_plan()
    if plan is not None:
        plan.on_cache_store(path)


class InitCache:
    """One cache directory; counters aggregate until :meth:`flush_events`."""

    def __init__(self, root: str) -> None:
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.counts: dict[tuple[str, str], int] = {}

    @classmethod
    def resolve(cls, cache) -> Optional["InitCache"]:
        if cache is None or isinstance(cache, cls):
            return cache
        return cls(str(cache))

    def _note(self, op: str, scope: str, n: int = 1) -> None:
        if n:
            self.counts[(op, scope)] = self.counts.get((op, scope), 0) + n

    def flush_events(self) -> None:
        """Emit one aggregate ``init_cache`` journal event per (op, scope)."""
        for (op, scope), count in sorted(self.counts.items()):
            _emit_event("init_cache", op=op, scope=scope, count=count,
                        root=self.root)
        self.counts.clear()

    # ---------------------------------------------------------- client scope

    def _client_path(self, fp: str) -> str:
        return os.path.join(self.root, f"client-{fp}.json")

    def load_client(self, fp: str) -> Optional[dict]:
        """Returns ``{"local_meta": dict, "gmms": {int col: ColumnGMM}}`` or
        None (miss).  Digest mismatch / unparseable file counts ``corrupt``
        and is a miss."""
        from fed_tgan_tpu.features.bgm import ColumnGMM

        path = self._client_path(fp)
        if not os.path.exists(path):
            self._note("miss", "client")
            return None
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode())
            payload = doc["payload"]
            blob = json.dumps(payload, sort_keys=True).encode()
            if (doc.get("version") != ENCODER_VERSION
                    or doc.get("sha256") != hashlib.sha256(blob).hexdigest()):
                raise ValueError("digest or version mismatch")
            gmms = {
                int(j): ColumnGMM.from_dict(d)
                for j, d in payload["gmms"].items()
            }
        except (ValueError, KeyError, TypeError, OSError):
            self._note("corrupt", "client")
            return None
        self._note("hit", "client")
        return {"local_meta": payload["local_meta"], "gmms": gmms}

    def store_client(self, fp: str, local_meta: dict, gmms: dict) -> None:
        payload = {
            "local_meta": local_meta,
            "gmms": {str(j): g.to_dict() for j, g in gmms.items()},
        }
        blob = json.dumps(payload, sort_keys=True).encode()
        doc = {
            "version": ENCODER_VERSION,
            "sha256": hashlib.sha256(blob).hexdigest(),
            "payload": payload,
        }
        path = self._client_path(fp)
        _atomic_write(path, json.dumps(doc, sort_keys=True).encode())
        self._note("store", "client")
        _maybe_corrupt(path)

    # ---------------------------------------------------------- global scope

    def _global_paths(self, gkey: str) -> tuple[str, str]:
        base = os.path.join(self.root, f"global-{gkey}")
        return base + ".npz", base + ".json"

    def load_global(self, gkey: str) -> Optional[dict]:
        """Returns ``{"payload": dict, "arrays": {name: ndarray}}`` or None."""
        npz_path, man_path = self._global_paths(gkey)
        if not (os.path.exists(npz_path) and os.path.exists(man_path)):
            self._note("miss", "global")
            return None
        try:
            with open(man_path, "rb") as f:
                manifest = json.loads(f.read().decode())
            if (manifest.get("version") != ENCODER_VERSION
                    or manifest.get("sha256") != _sha256_file(npz_path)):
                raise ValueError("digest or version mismatch")
            with np.load(npz_path, allow_pickle=False) as z:
                arrays = {k: z[k] for k in z.files}
            payload = json.loads(str(arrays.pop("payload")[()]))
        except (ValueError, KeyError, TypeError, OSError,
                json.JSONDecodeError):
            self._note("corrupt", "global")
            return None
        self._note("hit", "global")
        return {"payload": payload, "arrays": arrays}

    def store_global(self, gkey: str, payload: dict, arrays: dict) -> None:
        import io

        npz_path, man_path = self._global_paths(gkey)
        buf = io.BytesIO()
        np.savez(
            buf,
            payload=np.asarray(json.dumps(payload, sort_keys=True)),
            **arrays,
        )
        _atomic_write(npz_path, buf.getvalue())
        manifest = {
            "version": ENCODER_VERSION,
            "sha256": _sha256_file(npz_path),
        }
        _atomic_write(man_path, json.dumps(manifest, sort_keys=True).encode())
        self._note("store", "global")
        _maybe_corrupt(npz_path)
