"""Elastic federation: live membership churn and drift as handled events.

The trainer's population was a cold-init constant: ``federated_initialize``
priced everyone at once, the SPMD epoch program baked the slot count into
its trace, and the only membership change was subtractive (PR 1 dropout).
This module composes the pieces that already exist into a LIVE federation:

- **joins** route through :class:`OnboardingSession.register_clients`
  (frozen global layout, cache-aware local fits, softmax re-run over the
  extended population) and land in the trainer via
  ``FederatedTrainer.admit_clients`` — pow2 population/row/step buckets
  mean a join inside capacity never recompiles the round program;
- **departures** route through the PR 1 dropout path
  (``drop_client`` -> survivor weight renormalization, steps zeroed,
  no reshape);
- **drift** is data, not corruption.  A scripted ``drift:`` fault swaps a
  client's shard silently (same schema, shifted distribution); the
  per-window detector re-scores residents' CURRENT shards against their
  stored onboarding baselines through the PR 13 sketch scorer (content-hash
  cache keeps unchanged shards free), refits the drifted clients' mode
  normalization online (``rescore_client``), recomputes similarity weights
  over the live population within the SAME window, and feeds sustained
  drift into the existing quarantine-strike/eviction machinery.  Rollback
  is never the remedy — restoring old model weights cannot undrift a
  shard.

Every transition is journaled (``client_joined`` / ``client_left`` /
``drift_alarm`` / ``drift_window``) so ``obs report`` can narrate the
membership history and ``obs slo`` can gate the drift trajectory.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Optional, Sequence

import numpy as np

from fed_tgan_tpu.data.ingest import TablePreprocessor
from fed_tgan_tpu.federation.init import recompute_weights
from fed_tgan_tpu.federation.streaming import OnboardingSession
from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.obs.trace import span as _span

log = logging.getLogger("fed_tgan_tpu.federation")


@dataclasses.dataclass
class DriftConfig:
    """Detection-window policy for the elastic federation.

    ``jsd_alarm`` is an ABSOLUTE rise threshold (raw JSD lives in [0, 1]
    and is scored against the FROZEN global category counts, so the
    baseline is pool-independent).  Raw sketch-WD is in data units AND
    scored against the live resident pool — a pool that moves whenever a
    member departs or refits — so a client's WD rise is measured in units
    of the population's MEDIAN baseline WD per column (a near-zero
    self-baseline on IID shards must not turn numerical noise into an
    alarm, and a pool shift that moves everyone equally must not cascade).
    ``detect_every=0`` disables the probe entirely.
    """

    detect_every: int = 5        # rounds between detection windows
    jsd_alarm: float = 0.05      # absolute per-column raw-JSD rise
    wd_alarm_rel: float = 3.0    # WD rise in population-median-WD units
    refit: bool = True           # online refit + weight recompute on alarm


def clone_with_frame(client: TablePreprocessor, frame) -> TablePreprocessor:
    """Rebuild a preprocessor around a new RAW frame, same knobs.

    ``__post_init__`` extends ``categorical_columns`` with date-derived
    part-columns, so the constructor args must be recovered from the
    post-init state: keep only user-named categoricals that exist in the
    raw frame and aren't date keys (those re-extend on construction).
    """
    cats = [
        c for c in client.categorical_columns
        if c in client.frame.columns and c not in client.date_formats
    ]
    return TablePreprocessor(
        frame=frame,
        name=client.name,
        categorical_columns=cats,
        non_negative_columns=list(client.non_negative_columns),
        date_formats=dict(client.date_formats),
        target_column=client.target_column,
        problem_type=client.problem_type,
        selected_columns=client.selected_columns,
    )


class ElasticFederation:
    """Membership + drift orchestrator over a live ``FederatedTrainer``.

    Host-side state machine between fused device chunks: the trainer owns
    the device arrays, the :class:`OnboardingSession` owns the similarity
    state, and this class keeps them in lockstep while clients join,
    leave, and drift.  ``self.clients[i]`` is the CURRENT raw shard of
    global client ``i`` (drift swaps it); indices align with the
    trainer/init population because joins only append.
    """

    def __init__(
        self,
        trainer,
        session: OnboardingSession,
        clients: Sequence[TablePreprocessor],
        watchdog=None,
        config: Optional[DriftConfig] = None,
    ):
        if len(clients) != trainer.n_clients:
            raise ValueError(
                f"{len(clients)} client shards for a {trainer.n_clients}-"
                f"client trainer; pass the same population both got"
            )
        self.trainer = trainer
        self.session = session
        self.clients: list[TablePreprocessor] = list(clients)
        self.watchdog = watchdog
        self.cfg = config or DriftConfig()
        self.windows: list[dict] = []   # drift trajectory (one row/window)
        self._applied_events: set[tuple] = set()
        # per-client (jsd_row, wd_row) from the LAST window (seeded from
        # onboarding); refreshed every window so drift is window-over-
        # window, not cumulative-vs-cold-init — the refit absorbs a shift
        # and the next window is quiet again
        self._baseline: dict[int, tuple] = {}
        # membership changed since the last window: the pooled WD
        # reference moved for EVERY survivor, so the (pool-relative) WD
        # criterion is meaningless until baselines re-anchor — the next
        # window alarms on the pool-independent JSD signal alone
        self._pool_changed = False
        # keep the trainer's init pointed at the session's latest snapshot
        self.trainer.init = self.session.init

    # ------------------------------------------------------------- membership

    @property
    def population(self) -> int:
        return self.trainer.n_clients

    def _alive_mask(self) -> np.ndarray:
        alive = np.ones(self.population, dtype=bool)
        if self.trainer.dropped_clients:
            alive[sorted(self.trainer.dropped_clients)] = False
        return alive

    def join(self, newcomers: Sequence[TablePreprocessor],
             reason: str = "join") -> None:
        """Admit newcomers between rounds: similarity onboarding through
        the streaming session, then population landing in the trainer
        (``client_joined`` events are emitted there, with the repack
        verdict)."""
        new_init = self.session.register_clients(newcomers)
        self.trainer.admit_clients(new_init, reason=reason)
        self.clients.extend(newcomers)
        self._pool_changed = True

    def leave(self, idx: int, reason: str = "scripted departure") -> None:
        """Departure through the PR 1 dropout path; survivors renormalize."""
        _emit_event(
            "client_left", client=int(idx),
            round=int(self.trainer.completed_epochs), reason=reason,
            survivors=self.population - len(self.trainer.dropped_clients) - 1,
        )
        self.trainer.drop_client(idx, reason)
        self._pool_changed = True

    def apply_drift(self, idx: int, shift: float, seed: int = 0) -> None:
        """SILENTLY swap client ``idx``'s shard for a distribution-shifted
        one (schema-stable, deterministic).  No similarity state moves
        here — the point is that the next detection window must CATCH it:
        the drifted matrix is encoded with the frozen global encoders and
        transformed with the client's EXISTING (pre-drift) transformer,
        exactly the staleness the online refit later repairs.
        """
        from fed_tgan_tpu.testing import faults as _faults

        if not 0 <= idx < self.population:
            raise IndexError(f"client index {idx} out of range")
        cur = self.clients[idx]
        drifted = clone_with_frame(
            cur, _faults.drift_frame(cur.frame, shift=shift, seed=seed)
        )
        matrix, _, _ = drifted.encode(self.session.init.encoders)
        encoded = self.session.init.transformers[idx].transform(
            matrix, rng=np.random.default_rng(seed + idx)
        )
        self.trainer.update_client_shard(idx, encoded)
        self.clients[idx] = drifted
        log.info("drift applied to client %d (shift=%s, seed=%d); "
                 "detector owns the discovery", idx, shift, seed)

    # ------------------------------------------------------------- detection

    def detect(self, round_idx: Optional[int] = None) -> dict:
        """One detection window: re-score every live resident's CURRENT
        shard against its stored onboarding baseline; alarm, refit, and
        recompute weights for the drifted; charge sustained drift into the
        quarantine strike machinery.  Returns the window record (also
        appended to ``self.windows`` — the drift trajectory artifact).
        """
        if round_idx is None:
            round_idx = int(self.trainer.completed_epochs)
        alive = self._alive_mask()
        live = np.nonzero(alive)[0]
        if live.size == 0:
            raise RuntimeError("no live clients to score")
        ob = self.session.init.onboarding
        with _span("elastic.detect", round=round_idx, clients=len(live)):
            jsd_rows, wd_rows = self.session.score_clients(
                [self.clients[i] for i in live], alive=alive
            )
            ob_jsd = np.asarray(ob["jsd_raw"], dtype=np.float64)
            ob_wd = np.asarray(ob["wd_raw"], dtype=np.float64)
            base_jsd = np.stack([
                self._baseline.get(int(c), (ob_jsd[c], ob_wd[c]))[0]
                for c in live
            ]) if len(live) else ob_jsd[:0]
            base_wd = np.stack([
                self._baseline.get(int(c), (ob_jsd[c], ob_wd[c]))[1]
                for c in live
            ]) if len(live) else ob_wd[:0]
            jsd_rise = (
                (jsd_rows - base_jsd).max(axis=1)
                if jsd_rows.shape[1] else np.zeros(len(live))
            )
            # per-column population scale: a pool shift that moves every
            # client's WD equally must not read as everyone drifting
            scale = (
                np.maximum(np.median(np.abs(base_wd), axis=0), 1e-6)
                if wd_rows.shape[1] else None
            )
            wd_rise = (
                ((wd_rows - base_wd) / scale).max(axis=1)
                if wd_rows.shape[1] else np.zeros(len(live))
            )
            # a join/leave since the last window moved the pooled WD
            # reference under every survivor at once; only the absolute
            # JSD criterion is trustworthy until baselines re-anchor
            # (they do below, unconditionally — one window of WD blind-
            # ness, never a false-alarm cascade)
            wd_suppressed = self._pool_changed
            hit = jsd_rise > self.cfg.jsd_alarm
            if not wd_suppressed:
                hit = hit | (wd_rise > self.cfg.wd_alarm_rel)
            self._pool_changed = False
            for k, c in enumerate(live):
                self._baseline[int(c)] = (jsd_rows[k], wd_rows[k])
            drifted = [int(live[k]) for k in np.nonzero(hit)[0]]
            for k in np.nonzero(hit)[0]:
                _emit_event(
                    "drift_alarm", client=int(live[k]), round=round_idx,
                    jsd_rise=round(float(jsd_rise[k]), 6),
                    wd_rise=round(float(wd_rise[k]), 6),
                )
            if drifted and self.cfg.refit:
                for c in drifted:
                    # online refit: local GMMs, mode-normalized matrix,
                    # raw score rows REPLACED at index c
                    new_init = self.session.rescore_client(
                        c, self.clients[c]
                    )
                    self.trainer.update_client_shard(
                        c, new_init.client_matrices[c]
                    )
                ob = self.session.init.onboarding
                weights = recompute_weights(
                    ob["jsd_raw"], ob["wd_raw"],
                    self.session.init.rows_per_client,
                    alive=alive, weighted=ob["params"]["weighted"],
                )
                self.trainer.update_weights(weights)
                self.trainer.init = self.session.init
                # the refit MOVED the pooled WD reference (the repaired
                # mixtures re-enter the pool), so every survivor's
                # baseline re-anchors against the post-refit pool: next
                # window's rises measure future drift, not this window's
                # repair — and unlike a blanket one-window WD blackout,
                # a re-drifted shard still reads as a fresh WD rise
                jsd2, wd2 = self.session.score_clients(
                    [self.clients[i] for i in live], alive=alive
                )
                for k, c in enumerate(live):
                    self._baseline[int(c)] = (jsd2[k], wd2[k])
            sustained = (
                self.watchdog.observe_drift(round_idx, drifted)
                if self.watchdog is not None else []
            )
            evicted = []
            for c in sustained:
                if c in self.trainer.dropped_clients:
                    continue
                self.trainer._strikes[c] += 1
                strikes = int(self.trainer._strikes[c])
                _emit_event(
                    "quarantine", client=int(c), rounds=1,
                    first=round_idx, last=round_idx,
                    strikes=strikes, test="drift",
                )
                if strikes >= self.trainer.quarantine_strikes:
                    self.leave(
                        c,
                        f"sustained drift across "
                        f"{self.watchdog.cfg.drift_patience}+ windows "
                        f"(strike limit {self.trainer.quarantine_strikes})",
                    )
                    evicted.append(int(c))
        record = {
            "round": round_idx,
            "population": int(self.population),
            "live": int(alive.sum() - len(evicted)),
            "scored": int(live.size),
            "alarms": len(drifted),
            "drifted": drifted,
            "sustained": [int(c) for c in sustained],
            "evicted": evicted,
            "max_jsd_rise": round(float(jsd_rise.max(initial=0.0)), 6),
            "max_wd_rise": round(float(wd_rise.max(initial=0.0)), 6),
            # refit + weight recompute happen inside this same window,
            # so detection-to-recompute lag is 0 rounds by construction;
            # recorded (not assumed) so the SLO gate measures, not trusts
            "recompute_lag_rounds": 0 if (drifted and self.cfg.refit)
            else None,
            # membership changed since the last window: WD criterion sat
            # out (pool-relative; the move was the pool's, not a shard's)
            "wd_suppressed": True if wd_suppressed else None,
        }
        self.windows.append(record)
        _emit_event("drift_window", **{
            k: v for k, v in record.items() if v is not None
        })
        return record

    # -------------------------------------------------------------- training

    def run(
        self,
        epochs: int,
        plan=None,
        fit_kwargs: Optional[dict] = None,
        ckpt_dir: Optional[str] = None,
        newcomer_factory: Optional[Callable[[int, int], list]] = None,
        on_rollback: Optional[Callable] = None,
    ):
        """Train ``epochs`` rounds, applying scripted churn between fused
        chunks and running the drift probe every ``detect_every`` rounds.

        ``plan`` defaults to the ambient :func:`testing.faults.active_plan`;
        its ``join:``/``leave:``/``drift:`` events fire at their scripted
        round boundaries.  ``newcomer_factory(count, round)`` must supply
        raw shards for ``join:`` events.  With a watchdog AND ``ckpt_dir``,
        each segment trains under :func:`fit_with_watchdog` (rollback
        re-syncs the session to the restored trainer); churn events are
        applied exactly once even when a rollback re-traverses their round.
        Checkpointing rides the usual ``fit_kwargs["sample_hook"]`` /
        ``hook_epochs`` channel — this loop adds no save cadence of its own.
        """
        from fed_tgan_tpu.testing.faults import active_plan

        if plan is None:
            plan = active_plan()
        fit_kwargs = dict(fit_kwargs or {})
        start = int(self.trainer.completed_epochs)
        target = start + int(epochs)
        de = int(self.cfg.detect_every)

        while self.trainer.completed_epochs < target:
            e = int(self.trainer.completed_epochs)
            if plan is not None and plan.has_churn():
                for ev in plan.churn_events(e):
                    key = (e,) + tuple(ev)
                    if key in self._applied_events:
                        continue   # rollback re-traversal: applied already
                    self._applied_events.add(key)
                    if ev[0] == "join":
                        if newcomer_factory is None:
                            raise ValueError(
                                f"fault plan schedules a join at round "
                                f"{e + 1} but no newcomer_factory was given"
                            )
                        self.join(newcomer_factory(int(ev[1]), e))
                    elif ev[0] == "leave":
                        self.leave(
                            int(ev[1]),
                            f"scripted departure at round {e + 1}",
                        )
                    else:  # drift
                        self.apply_drift(
                            int(ev[1]), float(ev[2]), seed=e,
                        )
            if de and e > start and (e - start) % de == 0 and \
                    ("window", e) not in self._applied_events:
                self._applied_events.add(("window", e))
                self.detect(e)

            # segment ends at the next churn round, the next detection
            # window, or the target — whichever comes first
            edges = [target]
            if plan is not None and plan.has_churn():
                nxt = plan.next_churn_round(e + 1)
                if nxt is not None:
                    edges.append(nxt)
            if de:
                edges.append(e + de - (e - start) % de)
            stop = max(e + 1, min(edges))
            seg = stop - e
            if self.watchdog is not None and ckpt_dir:
                from fed_tgan_tpu.train.watchdog import fit_with_watchdog

                def _rb(tr):
                    self._on_rollback(tr)
                    if on_rollback is not None:
                        on_rollback(tr)

                self.trainer = fit_with_watchdog(
                    self.trainer, seg, self.watchdog, ckpt_dir,
                    fit_kwargs=dict(fit_kwargs),
                    on_rollback=_rb,
                )
            else:
                self.trainer.fit(seg, **fit_kwargs)
        return self.trainer

    def _on_rollback(self, trainer) -> None:
        """Re-sync host-side state to the restored trainer: the session's
        similarity snapshot reverts with the checkpointed init (baselines
        included); raw shards stay current — if drift landed before the
        checkpoint, the next window simply re-detects and re-repairs."""
        self.trainer = trainer
        self.session.init = trainer.init
