from fed_tgan_tpu.federation.init import (
    FederatedInit,
    aggregation_weights,
    federated_initialize,
    harmonize_categories,
    harmonize_continuous,
)

__all__ = [
    "FederatedInit",
    "aggregation_weights",
    "federated_initialize",
    "harmonize_categories",
    "harmonize_continuous",
]
