from fed_tgan_tpu.federation.init import (
    FederatedInit,
    aggregation_weights,
    federated_initialize,
    harmonize_categories,
    harmonize_continuous,
)
from fed_tgan_tpu.federation.init_cache import InitCache, shard_fingerprint
from fed_tgan_tpu.federation.streaming import OnboardingSession

__all__ = [
    "FederatedInit",
    "InitCache",
    "OnboardingSession",
    "aggregation_weights",
    "federated_initialize",
    "harmonize_categories",
    "harmonize_continuous",
    "shard_fingerprint",
]
