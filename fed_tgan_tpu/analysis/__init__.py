"""Correctness tooling: static JAX lint (jaxlint) + runtime sanitizers.

Two prongs, one goal -- keep the hot paths provably clean:

* :mod:`fed_tgan_tpu.analysis.lint` -- stdlib-AST rules J01-J05 (host
  syncs in hot loops, PRNG key reuse, recompile hazards, numpy-in-jit,
  unguarded shared state) with a checked-in ratcheting baseline.
  Run ``python -m fed_tgan_tpu.analysis``.
* :mod:`fed_tgan_tpu.analysis.sanitizers` -- opt-in runtime guards:
  transfer guards around designated hot regions, a ``log_compiles``
  driven compile counter with per-program budgets, NaN debugging.
  Enabled by ``--sanitize`` on the train/serve CLIs.

This ``__init__`` stays import-light (no JAX, no numpy) so the lint
gate and the CLI start instantly.
"""

from fed_tgan_tpu.analysis.lint import (  # noqa: F401
    DEFAULT_BASELINE_PATH,
    Finding,
    LintError,
    apply_baseline,
    load_baseline,
    run_lint,
    save_baseline,
)

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Finding",
    "LintError",
    "apply_baseline",
    "load_baseline",
    "run_lint",
    "save_baseline",
]
