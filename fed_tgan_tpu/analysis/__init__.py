"""Correctness tooling: static lint, runtime sanitizers, IR contracts.

Three prongs, one goal -- keep the hot paths provably clean:

* :mod:`fed_tgan_tpu.analysis.lint` -- stdlib-AST rules J01-J06 + the
  :mod:`~fed_tgan_tpu.analysis.concurrency` lockset rules L01-L04 (host
  syncs in hot loops, PRNG key reuse, recompile hazards, numpy-in-jit,
  unguarded shared state, dtype promotion) with a checked-in ratcheting
  baseline.  Run ``python -m fed_tgan_tpu.analysis``.
* :mod:`fed_tgan_tpu.analysis.sanitizers` -- opt-in runtime guards:
  transfer guards around designated hot regions, a ``log_compiles``
  driven compile counter with per-program budgets, NaN debugging.
  Enabled by ``--sanitize`` on the train/serve CLIs.
* :mod:`fed_tgan_tpu.analysis.contracts` -- hlolint: every jitted
  entrypoint AOT-lowered on an 8-virtual-device CPU mesh and its
  StableHLO fingerprint (collectives, transfer surface, dtype census)
  ratcheted against checked-in contracts.  Run ``python -m
  fed_tgan_tpu.analysis --contracts``.

This ``__init__`` stays import-light (no JAX, no numpy) so the lint
gate and the CLI start instantly.
"""

from fed_tgan_tpu.analysis.lint import (  # noqa: F401
    DEFAULT_BASELINE_PATH,
    Finding,
    LintError,
    apply_baseline,
    load_baseline,
    run_lint,
    save_baseline,
)

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Finding",
    "LintError",
    "apply_baseline",
    "load_baseline",
    "run_lint",
    "save_baseline",
]
