"""Runtime sanitizers: transfer guards, compile budgets, NaN debugging.

Static lint (J01-J06) proves the *source* is clean; these prove the
*process* is: with sanitizers enabled, designated hot regions run under
``jax.transfer_guard_device_to_host("disallow")`` (an implicit pull
raises instead of silently costing a round trip -- explicit
``jax.device_get`` / ``copy_to_host_async`` stay legal, they ARE the
sanctioned idiom), and every XLA compile event is counted per program
so budget checks can assert "the fused epoch program compiled once" and
"the serve engine compiled at most one program per bucket".

Everything is opt-in and near-zero-cost when disabled:
``hot_region(name)`` is a no-op unless :func:`enable_sanitizers` (or the
``--sanitize`` CLI flag / ``sanitize()`` context manager) is active.
The first entry of each named region runs unguarded -- tracing and
compilation legitimately move constants -- the steady state is guarded
from the second entry on.

JAX is imported lazily so the lint prong never pays for it.
"""

from __future__ import annotations

import contextlib
import logging
import re
import threading
from typing import Dict, List, Optional

from fed_tgan_tpu.obs.journal import emit as _emit_event
from fed_tgan_tpu.obs.ledger import note_compile as _note_compile

__all__ = [
    "CompileCounter",
    "compile_report",
    "check_compile_budgets",
    "check_serving_budget",
    "check_training_budget",
    "disable_sanitizers",
    "enable_sanitizers",
    "hot_region",
    "sanitize",
    "sanitizing",
]

#: one record per trace+compile event, fired even on persistent-cache
#: hits (the in-process trace still happens), once per distinct
#: argument signature -- exactly the "did this retrace?" signal.
_COMPILE_RE = re.compile(r"Compiling ([\w.<>\[\]-]+) with global shapes")
_COMPILE_LOGGER = "jax._src.interpreters.pxla"

#: tiny auxiliary programs jit emits around dispatch (weak-type casts,
#: fill values); never interesting for budget accounting.
_NOISE = {"convert_element_type", "broadcast_in_dim", "_multi_slice",
          "multiply", "add", "true_divide", "fill", "copy", "iota",
          "_threefry_split", "_threefry_fold_in", "ravel", "concatenate"}


class CompileCounter(logging.Handler):
    """Counts XLA trace/compile events per program name while attached."""

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.events: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILE_RE.search(record.getMessage())
        except Exception:  # never let logging break the run
            return
        if m:
            # logging.Handler.handle() already serialises emit() calls
            # under the handler's own lock
            self.events.append(m.group(1))  # jaxlint: disable=L01
            _emit_event("compile", program=m.group(1))
            # live-compile feed for the process-wide cost ledger: the
            # AOT pass records analysis figures, this records the fact
            # that (and how often) the program compiled in vivo
            if m.group(1) not in _NOISE:
                _note_compile(m.group(1))

    # ----------------------------------------------------------- queries

    def counts(self, include_noise: bool = False) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for name in self.events:
            if include_noise or name not in _NOISE:
                out[name] = out.get(name, 0) + 1
        return out

    def count(self, name_substring: str) -> int:
        return sum(n for name, n in self.counts(include_noise=True).items()
                   if name_substring in name)

    def reset(self) -> None:
        self.events = []


class _State:
    def __init__(self) -> None:
        self.active = False
        self.counter: Optional[CompileCounter] = None
        self.warmups: Dict[str, int] = {}
        self.guard_warmup = False  # guard even first entries (strict)
        self._saved: dict = {}
        self._lock = threading.Lock()


_STATE = _State()


def sanitizing() -> bool:
    return _STATE.active


def enable_sanitizers(transfer_guard: bool = True,
                      compile_counter: bool = True,
                      nan_debug: bool = False,
                      guard_warmup: bool = False) -> Optional[CompileCounter]:
    """Turn the sanitizers on process-wide.  Returns the compile counter
    (None when ``compile_counter`` is off).  Idempotent; pair with
    :func:`disable_sanitizers` or use the :func:`sanitize` context."""
    import jax

    st = _STATE
    with st._lock:
        if st.active:
            return st.counter
        st._saved = {
            "log_compiles": jax.config.jax_log_compiles,
            "debug_nans": jax.config.jax_debug_nans,
        }
        st.warmups = {}
        st.guard_warmup = guard_warmup
        st.active = True
        st.counter = None
        if not transfer_guard:
            # transfer_guard=False: regions still tracked, never guarded
            st.guard_warmup = False
            st.warmups = None  # type: ignore[assignment]
        if compile_counter:
            jax.config.update("jax_log_compiles", True)
            logger = logging.getLogger(_COMPILE_LOGGER)
            st._saved["logger_level"] = logger.level
            if logger.level > logging.WARNING or logger.level == 0:
                logger.setLevel(logging.WARNING)
            st.counter = CompileCounter()
            logger.addHandler(st.counter)
        if nan_debug:
            jax.config.update("jax_debug_nans", True)
        return st.counter


def disable_sanitizers() -> None:
    import jax

    st = _STATE
    with st._lock:
        if not st.active:
            return
        if st.counter is not None:
            logger = logging.getLogger(_COMPILE_LOGGER)
            logger.removeHandler(st.counter)
            if "logger_level" in st._saved:
                logger.setLevel(st._saved["logger_level"])
        jax.config.update("jax_log_compiles", st._saved["log_compiles"])
        jax.config.update("jax_debug_nans", st._saved["debug_nans"])
        st.active = False
        st.counter = None
        st.warmups = {}


@contextlib.contextmanager
def sanitize(transfer_guard: bool = True, compile_counter: bool = True,
             nan_debug: bool = False, guard_warmup: bool = False):
    """``with sanitize() as counter:`` -- scoped enable/disable."""
    counter = enable_sanitizers(transfer_guard=transfer_guard,
                                compile_counter=compile_counter,
                                nan_debug=nan_debug,
                                guard_warmup=guard_warmup)
    try:
        yield counter
    finally:
        disable_sanitizers()


@contextlib.contextmanager
def hot_region(name: str, guard: str = "disallow"):
    """Mark a steady-state device-dispatch region.

    No-op unless sanitizers are active.  The first entry per ``name``
    runs unguarded (tracing/compilation legitimately transfers
    constants); later entries run under
    ``jax.transfer_guard_device_to_host(guard)`` so any *implicit*
    device->host pull raises.  Explicit ``jax.device_get`` and
    ``copy_to_host_async`` remain allowed -- they are the fix idiom J01
    points at, not the bug."""
    st = _STATE
    if not st.active or st.warmups is None:
        yield
        return
    n = st.warmups.get(name, 0)
    st.warmups[name] = n + 1
    if n == 0 and not st.guard_warmup:
        yield
        return
    import jax

    with jax.transfer_guard_device_to_host(guard):
        yield


# ----------------------------------------------------------------- budgets

def check_compile_budgets(budgets: Dict[str, int],
                          counter: Optional[CompileCounter] = None
                          ) -> List[str]:
    """Violations for ``{program-name-substring: max_compiles}``."""
    counter = counter or _STATE.counter
    if counter is None:
        return []
    out = []
    for name, budget in budgets.items():
        n = counter.count(name)
        if n > budget:
            out.append(f"program '{name}' compiled {n}x "
                       f"(budget {budget}) -- retrace leak?")
    return out


def check_training_budget(trainer, counter=None) -> List[str]:
    """The fused epoch program must compile once per distinct
    (chunk-size, fault-window) variant -- ``trainer._epoch_fns`` holds
    exactly that set.  (A watchdog rollback that rebuilds the trainer
    legitimately recompiles; check against the final trainer.)"""
    fns = getattr(trainer, "_epoch_fns", None)
    if fns is None:
        return []
    return check_compile_budgets({"epoch_local": max(1, len(fns))}, counter)


def check_serving_budget(engine, counter=None) -> List[str]:
    """The serve engine compiles at most one program per
    (power-of-two bucket, conditional?) pair -- and each bucket's
    program exactly once."""
    from fed_tgan_tpu.serve.naming import SERVE_BUCKET_PREFIX

    counter = counter or _STATE.counter
    programs = getattr(engine, "_programs", None)
    if counter is None or programs is None:
        return []
    out = check_compile_budgets(
        {SERVE_BUCKET_PREFIX: max(1, len(programs))}, counter)
    for name, n in counter.counts(include_noise=True).items():
        if name.startswith(SERVE_BUCKET_PREFIX) and n > 1:
            out.append(f"bucket program '{name}' compiled {n}x "
                       "(budget 1) -- bucket cache miss?")
    return out


def check_fleet_budget(cache, counter=None) -> List[str]:
    """The fleet's shared LRU compiles at most one program per cached
    (bucket, lanes, layout) key.  Evicted-then-rebuilt programs
    legitimately recompile, so the aggregate budget is entries +
    evictions; the stricter one-compile-per-name check only applies
    while nothing has been evicted."""
    from fed_tgan_tpu.serve.naming import SERVE_BUCKET_PREFIX

    counter = counter or _STATE.counter
    stats = cache.stats() if cache is not None else None
    if counter is None or stats is None:
        return []
    budget = max(1, stats["entries"] + stats["evictions"])
    out = check_compile_budgets({SERVE_BUCKET_PREFIX: budget}, counter)
    if stats["evictions"] == 0:
        for name, n in counter.counts(include_noise=True).items():
            if name.startswith(SERVE_BUCKET_PREFIX) and n > 1:
                out.append(f"fleet program '{name}' compiled {n}x "
                           "(budget 1) -- LRU cache miss?")
    return out


def compile_report(counter: Optional[CompileCounter] = None) -> str:
    counter = counter or _STATE.counter
    if counter is None:
        return "sanitize: compile counter inactive"
    counts = counter.counts()
    if not counts:
        return "sanitize: 0 compile events"
    lines = [f"sanitize: {sum(counts.values())} compile event(s):"]
    for name in sorted(counts, key=counts.get, reverse=True):
        lines.append(f"  {counts[name]:4d}x {name}")
    return "\n".join(lines)
