"""J05 -- unguarded shared mutable state (deprecation shim).

J05 was a *lexical* scan: per class it collected lock attributes and
flagged non-atomic ``self.attr`` mutations outside a ``with
self._lock:`` block.  It could not see cross-function lock flow, so a
``_shed``-style private helper that is only ever called under the lock
either got a false positive or an inline disable -- and a genuine
deadlock (PR 9's ``submit`` -> ``_shed`` re-acquire) sailed through.

Its findings migrated into the interprocedural locklint prong
(``analysis/concurrency/``): **L01** carries the unguarded-mutation
semantics with call-graph-propagated locksets, and L02-L04 cover the
ordering/blocking/leak hazards the lexical scan never could.  The rule
id stays registered so stale ``--rules J05`` invocations and old
``# jaxlint: disable=J05`` comments keep parsing, but ``check`` yields
nothing.

The type inventories below (what counts as a lock / a thread-safe
container / a mutator call) remain the single source of truth, shared
with ``analysis/concurrency/model.py``.
"""

from __future__ import annotations

import ast
from typing import Iterator

RULE_ID = "J05"
HINT = ("J05 is deprecated: the interprocedural lockset rule L01 "
        "(analysis/concurrency/) now carries these findings")

_LOCK_TYPES = ("threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition")
_SAFE_TYPES = ("queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
               "queue.PriorityQueue", "Queue", "SimpleQueue",
               "collections.deque", "deque", "threading.Event", "Event",
               "threading.Semaphore", "threading.BoundedSemaphore")

_MUTATORS = {"append", "extend", "add", "insert", "pop", "popitem",
             "popleft", "remove", "discard", "clear", "update",
             "setdefault", "appendleft", "sort", "reverse"}


def _imports_threading(tree) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "threading":
                return True
    return False


def _self_attr(node) -> str:
    """'x' for ``self.x``, else ''."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id in ("self", "cls"):
        return node.attr
    return ""


class SharedStateRule:
    """Deprecation shim: registered for id/CLI compatibility, finds
    nothing.  See L01 in ``analysis/concurrency/rules.py``."""

    rule_id = RULE_ID
    title = "unguarded shared state (deprecated -> L01)"
    hint = HINT

    def check(self, mod) -> Iterator:
        return iter(())
