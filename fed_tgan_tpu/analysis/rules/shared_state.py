"""J05 -- unguarded shared mutable state in threaded modules.

Applies to modules that import ``threading`` (the serve layer's HTTP
handler threads + batch worker, metrics, snapshot writers).  For each
class the rule collects lock attributes (``self._lock =
threading.Lock()/RLock()/Condition()``) and intrinsically thread-safe
attributes (``queue.Queue`` family), then flags non-atomic mutations
performed outside a ``with self._lock:`` block:

* ``self.attr[key] = ...`` / ``del self.attr[key]`` -- container writes;
* ``self.attr += ...`` -- read-modify-write;
* mutating method calls (``.append`` / ``.update`` / ``.pop`` ...) on
  ``self.attr`` containers.

Plain rebinds (``self.attr = value``) are atomic under the GIL and are
not flagged; ``__init__`` runs before any thread exists and is skipped.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fed_tgan_tpu.analysis.rules.base import dotted

RULE_ID = "J05"
HINT = ("guard the mutation with the class lock (`with self._lock:`) or "
        "use a thread-safe structure (queue.Queue)")

_LOCK_TYPES = ("threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition")
_SAFE_TYPES = ("queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
               "queue.PriorityQueue", "Queue", "SimpleQueue",
               "collections.deque", "deque", "threading.Event", "Event",
               "threading.Semaphore", "threading.BoundedSemaphore")

_MUTATORS = {"append", "extend", "add", "insert", "pop", "popitem",
             "popleft", "remove", "discard", "clear", "update",
             "setdefault", "appendleft", "sort", "reverse"}


def _imports_threading(tree) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "threading":
                return True
    return False


def _self_attr(node) -> str:
    """'x' for ``self.x``, else ''."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id in ("self", "cls"):
        return node.attr
    return ""


class SharedStateRule:
    rule_id = RULE_ID
    title = "unguarded shared state"
    hint = HINT

    def check(self, mod) -> Iterator:
        in_serve = "/serve/" in mod.relpath.replace("\\", "/")
        if not in_serve and not _imports_threading(mod.tree):
            return
        findings: dict = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node, findings)
        for line in sorted(findings):
            yield (self.rule_id, line, findings[line], self.hint)

    def _check_class(self, cls, findings) -> None:
        locks: set = set()
        safe: set = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                d = dotted(node.value.func) or ""
                for t in node.targets:
                    attr = _self_attr(t)
                    if not attr:
                        continue
                    if d in _LOCK_TYPES:
                        locks.add(attr)
                    elif d in _SAFE_TYPES:
                        safe.add(attr)

        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and item.name != "__init__":
                self._scan(item.body, held=False, locks=locks, safe=safe,
                           findings=findings)

    def _holds_lock(self, withstmt, locks) -> bool:
        for item in withstmt.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func
                if isinstance(expr, ast.Attribute) and \
                        expr.attr in ("acquire",):
                    expr = expr.value
            if _self_attr(expr) in locks:
                return True
        return False

    def _flag(self, findings, node, message) -> None:
        findings.setdefault(node.lineno, message)

    def _scan(self, stmts, held, locks, safe, findings) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, (ast.With, ast.AsyncWith)):
                self._scan(s.body, held or self._holds_lock(s, locks),
                           locks, safe, findings)
                continue
            if not held:
                self._scan_stmt_mutations(s, locks, safe, findings)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    self._scan(sub, held, locks, safe, findings)
            for h in getattr(s, "handlers", []):
                self._scan(h.body, held, locks, safe, findings)

    def _scan_stmt_mutations(self, s, locks, safe, findings) -> None:
        if isinstance(s, ast.Assign):
            for t in s.targets:
                self._check_target(t, safe, findings)
        elif isinstance(s, ast.AugAssign):
            t = s.target
            attr = _self_attr(t) or \
                (_self_attr(t.value) if isinstance(t, ast.Subscript) else "")
            if attr and attr not in safe:
                self._flag(findings, s,
                           f"read-modify-write of shared `self.{attr}` "
                           "without the lock")
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr and attr not in safe:
                        self._flag(findings, s,
                                   f"del on shared container `self.{attr}` "
                                   "without the lock")
        # mutating method calls anywhere in the statement's expressions
        for node in ast.walk(s):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                attr = _self_attr(node.func.value)
                if attr and attr not in safe and attr not in locks:
                    self._flag(findings, node,
                               f"`.{node.func.attr}()` mutates shared "
                               f"`self.{attr}` without the lock")

    def _check_target(self, t, safe, findings) -> None:
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr and attr not in safe:
                self._flag(findings, t,
                           f"item write to shared container `self.{attr}` "
                           "without the lock")
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                self._check_target(elt, safe, findings)
