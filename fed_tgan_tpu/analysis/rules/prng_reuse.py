"""J02 -- the same PRNG key consumed by two ``jax.random.*`` draws.

JAX keys are splittable, not stateful: passing one key to two samplers
yields *correlated* (often identical) streams.  The rule tracks a
per-identity generation counter -- rebinding a name bumps its
generation -- and flags (a) two consumptions of the same ``(identity,
generation)`` on one control-flow path, and (b) consumption inside a
loop of a key that is never rebound within that loop (the classic
"same key every iteration" bug).

Derivers (``split`` / ``fold_in`` / ``key`` / ``PRNGKey`` ...) are not
consumptions; ``if``/``else`` branches are checked independently so a
key consumed once per exclusive branch stays clean; ``keys[i]`` with a
non-constant index is assumed fresh per iteration.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from fed_tgan_tpu.analysis.rules.base import assigned_names, dotted

RULE_ID = "J02"
HINT = ("derive a fresh key per draw: `ka, kb = jax.random.split(key)` or "
        "`jax.random.fold_in(key, step)` inside loops")

#: ``jax.random`` functions that *produce* key material rather than
#: consuming it for a draw.
_DERIVERS = {"split", "fold_in", "key", "PRNGKey", "wrap_key_data",
             "key_data", "clone", "key_impl"}

_KEY_PREFIXES = ("jax.random.", "jrandom.", "jr.")


def _consumed_key(call) -> Optional[ast.AST]:
    """The key argument when ``call`` is a consuming jax.random draw."""
    d = dotted(call.func) or ""
    if "jax.random." not in d and not d.startswith(("jrandom.", "jr.")):
        return None
    last = d.rsplit(".", 1)[-1]
    if last in _DERIVERS:
        return None
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _ident(e) -> Optional[tuple]:
    if isinstance(e, ast.Name):
        return ("n", e.id)
    if isinstance(e, ast.Subscript) and isinstance(e.value, ast.Name):
        sl = e.slice
        if isinstance(sl, ast.Constant):
            return ("s", e.value.id, repr(sl.value))
        return ("s", e.value.id, None)  # dynamic index: assumed varying
    if isinstance(e, ast.Attribute):
        d = dotted(e)
        return ("a", d) if d else None
    return None


class _FnScan:
    def __init__(self):
        self.gen: dict = {}
        self.findings: dict = {}

    def _bump(self, target) -> None:
        ident = _ident(target)
        if ident is not None:
            self.gen[ident] = self.gen.get(ident, 0) + 1
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bump(elt)
        elif isinstance(target, ast.Starred):
            self._bump(target.value)
        elif isinstance(target, ast.Name):
            # rebinding a name invalidates subscript identities too
            stale = [i for i in self.gen
                     if i[0] == "s" and i[1] == target.id]
            for i in stale:
                self.gen[i] += 1

    def _consume(self, key_expr, line, uses, loop_names) -> None:
        ident = _ident(key_expr)
        if ident is None or (ident[0] == "s" and ident[2] is None):
            return
        slot = (ident, self.gen.get(ident, 0))
        if slot in uses:
            self.findings.setdefault(
                line, "key already consumed by the jax.random call on "
                      f"line {uses[slot]}")
            return
        if loop_names is not None and ident[0] != "a":
            base = ident[1]
            if base not in loop_names:
                self.findings.setdefault(
                    line, f"key `{base}` is consumed every loop "
                          "iteration without being rebound in the loop")
                # fall through: still record the use
        uses[slot] = line

    def _scan_calls(self, e, uses, loop_names) -> None:
        """In-order walk of an expression, consuming keys left-to-right."""
        if e is None or not isinstance(e, ast.AST):
            return
        if isinstance(e, ast.Call):
            self._scan_calls(e.func, uses, loop_names)
            for a in e.args:
                self._scan_calls(a, uses, loop_names)
            for k in e.keywords:
                self._scan_calls(k.value, uses, loop_names)
            key = _consumed_key(e)
            if key is not None:
                self._consume(key, e.lineno, uses, loop_names)
            return
        if isinstance(e, (ast.ListComp, ast.SetComp,
                          ast.GeneratorExp, ast.DictComp)):
            inner = set(loop_names or set())
            for gen in e.generators:
                self._scan_calls(gen.iter, uses, loop_names)
                inner |= {n.id for n in ast.walk(gen.target)
                          if isinstance(n, ast.Name)}
            parts = [e.key, e.value] if isinstance(e, ast.DictComp) \
                else [e.elt]
            for p in parts:
                self._scan_calls(p, uses, inner)
            return
        if isinstance(e, ast.Lambda):
            return
        for child in ast.iter_child_nodes(e):
            self._scan_calls(child, uses, loop_names)

    def scan(self, stmts, uses, loop_names) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Assign):
                self._scan_calls(s.value, uses, loop_names)
                for t in s.targets:
                    self._bump(t)
            elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
                if s.value is not None:
                    self._scan_calls(s.value, uses, loop_names)
                self._bump(s.target)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                self._scan_calls(s.iter, uses, loop_names)
                body_names = assigned_names(s.body) | {
                    n.id for n in ast.walk(s.target)
                    if isinstance(n, ast.Name)}
                if loop_names is not None:
                    body_names |= set()
                self.scan(s.body, uses, body_names)
                self.scan(s.orelse, uses, loop_names)
            elif isinstance(s, ast.While):
                body_names = assigned_names(s.body)
                self._scan_calls(s.test, uses, body_names)
                self.scan(s.body, uses, body_names)
                self.scan(s.orelse, uses, loop_names)
            elif isinstance(s, ast.If):
                self._scan_calls(s.test, uses, loop_names)
                a = dict(uses)
                self.scan(s.body, a, loop_names)
                b = dict(uses)
                self.scan(s.orelse, b, loop_names)
                # a branch that leaves (return/raise/...) contributes no
                # uses to the fallthrough path
                merged = dict(uses)
                if not _terminates(s.orelse):
                    merged.update(b)
                if not _terminates(s.body):
                    merged.update(a)
                uses.clear()
                uses.update(merged)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    self._scan_calls(item.context_expr, uses, loop_names)
                    if item.optional_vars is not None:
                        self._bump(item.optional_vars)
                self.scan(s.body, uses, loop_names)
            elif isinstance(s, ast.Try):
                self.scan(s.body, uses, loop_names)
                for h in s.handlers:
                    self.scan(h.body, uses, loop_names)
                self.scan(s.orelse, uses, loop_names)
                self.scan(s.finalbody, uses, loop_names)
            else:
                for child in ast.iter_child_nodes(s):
                    self._scan_calls(child, uses, loop_names)


class PrngReuseRule:
    rule_id = RULE_ID
    title = "PRNG key reuse"
    hint = HINT

    def check(self, mod) -> Iterator:
        tree = mod.tree
        bodies = [tree.body]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bodies.append(node.body)
        findings: dict = {}
        for body in bodies:
            sc = _FnScan()
            sc.scan(body, {}, None)
            for line, message in sc.findings.items():
                findings.setdefault(line, message)
        for line in sorted(findings):
            yield (self.rule_id, line, findings[line], self.hint)
