"""J06 -- dtype-promotion hazards inside jitted code.

Two silent ways an f32 program grows f64 (or lies about it):

* a host ``np.float64`` / ``np.double`` scalar (or a dtype-less
  ``np.array``/``np.asarray`` over float literals -- numpy defaults them
  to f64) combined with a traced value: under ``jax_enable_x64`` the
  whole expression promotes to f64 (double the collective payload, half
  the TPU throughput); without x64 the requested precision silently
  degrades to f32 -- either way the source stops meaning what it says;
* an explicit ``dtype=np.float64`` / ``dtype="float64"`` /
  ``dtype=float`` keyword inside jit -- the same two-faced request,
  spelled directly.

Plain Python float literals (``x * 2.0``) stay CLEAN: they are
weak-typed in JAX and inherit the traced operand's dtype -- that is the
sanctioned idiom the hint points at.  ``np.float64`` applied directly
TO a traced value is J04's finding (host numpy on a tracer), not ours;
this rule covers the constant-side operand J04 deliberately ignores.

The IR-level twin of this rule is the contracts dtype census
(``python -m fed_tgan_tpu.analysis --contracts`` flags f64 tensor types
in the lowered programs); J06 catches the hazard at the source line
before it ever lowers.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fed_tgan_tpu.analysis.rules.base import (
    dotted,
    jitted_functions,
    names_in,
)

RULE_ID = "J06"
HINT = ("use weak-typed Python scalars (x * 2.0) or explicit jnp dtypes "
        "(jnp.float32) inside jit; host f64 scalars promote under x64 "
        "and silently degrade without it")

#: numpy spellings that produce a strong f64 host scalar/array.
_F64_CALLS = {"np.float64", "numpy.float64", "onp.float64",
              "np.double", "numpy.double", "onp.double"}
#: dtype-less array constructors numpy defaults to f64 on float input.
_ARRAY_CALLS = {"np.array", "np.asarray", "numpy.array", "numpy.asarray",
                "onp.array", "onp.asarray"}


def _is_f64_operand(node) -> bool:
    """A call producing a strong f64 value from CONSTANTS (a traced
    argument is J04's finding, not a promotion-by-constant)."""
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func) or ""
    if d in _F64_CALLS:
        return not any(names_in(a) for a in node.args)
    if d in _ARRAY_CALLS:
        has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
        has_float_literal = any(
            isinstance(n, ast.Constant) and isinstance(n.value, float)
            for a in node.args for n in ast.walk(a)
        )
        return (not has_dtype and has_float_literal
                and not any(names_in(a) for a in node.args))
    return False


def _f64_dtype_kwarg(call: ast.Call):
    """The dtype kwarg value when it requests f64 (or builtin float)."""
    for kw in call.keywords:
        if kw.arg != "dtype":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and v.value in ("float64", "double"):
            return "dtype=\"float64\""
        if isinstance(v, ast.Name) and v.id == "float":
            return "dtype=float"
        d = dotted(v) or ""
        if d in _F64_CALLS or d.endswith(".float64") or d.endswith(".double"):
            return f"dtype={d}"
    return None


def _taint(jf) -> set:
    tainted = set(jf.dynamic_params)
    body = jf.node.body
    stmts = body if isinstance(body, list) else []
    for _ in range(2):  # propagate through simple assignments
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) and \
                        names_in(node.value) & tainted:
                    for t in node.targets:
                        tainted |= {n.id for n in ast.walk(t)
                                    if isinstance(n, ast.Name)}
                elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                        names_in(node.iter) & tainted:
                    tainted |= {n.id for n in ast.walk(node.target)
                                if isinstance(n, ast.Name)}
    return tainted


class DtypePromotionRule:
    rule_id = RULE_ID
    title = "dtype promotion hazard in jit"
    hint = HINT

    def check(self, mod) -> Iterator:
        findings: dict = {}
        for jf in jitted_functions(mod.tree):
            body = jf.node.body
            stmts = body if isinstance(body, list) else [ast.Expr(body)]
            tainted = _taint(jf)
            for stmt in stmts:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.BinOp):
                        for side, other in ((node.left, node.right),
                                            (node.right, node.left)):
                            if _is_f64_operand(side) and \
                                    names_in(other) & tainted:
                                d = dotted(side.func)
                                findings.setdefault(
                                    node.lineno,
                                    f"{d}() yields a strong float64 "
                                    "operand: combined with a traced "
                                    "value it promotes the expression "
                                    "under x64 (and silently stays f32 "
                                    "without it)")
                    elif isinstance(node, ast.Call):
                        req = _f64_dtype_kwarg(node)
                        if req is not None:
                            findings.setdefault(
                                node.lineno,
                                f"{req} inside jit requests float64: a "
                                "silent 2x payload upcast under x64, a "
                                "silent lie without it")
        for line in sorted(findings):
            yield (self.rule_id, line, findings[line], self.hint)
