"""jaxlint rule registry.

Each rule exposes ``rule_id``, ``title``, ``hint`` and
``check(module) -> iter[(rule_id, line, message, hint)]``.

The J01-J06 rules are the JAX-facing lint; the L01-L04 rules are the
locklint concurrency prong (``analysis/concurrency/``) and share the
same driver, suppression comments and baseline.
"""

from fed_tgan_tpu.analysis.concurrency.rules import (
    BlockingUnderLockRule,
    LockLeakRule,
    LockOrderRule,
    UnguardedFieldRule,
)
from fed_tgan_tpu.analysis.rules.dtype_promotion import DtypePromotionRule
from fed_tgan_tpu.analysis.rules.host_sync import HostSyncRule
from fed_tgan_tpu.analysis.rules.numpy_in_jit import NumpyInJitRule
from fed_tgan_tpu.analysis.rules.prng_reuse import PrngReuseRule
from fed_tgan_tpu.analysis.rules.recompile import RecompileRule
from fed_tgan_tpu.analysis.rules.shared_state import SharedStateRule

ALL_RULES = (
    HostSyncRule(),
    PrngReuseRule(),
    RecompileRule(),
    NumpyInJitRule(),
    SharedStateRule(),
    DtypePromotionRule(),
    UnguardedFieldRule(),
    LockOrderRule(),
    BlockingUnderLockRule(),
    LockLeakRule(),
)

RULES_BY_ID = {r.rule_id: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_ID", "DtypePromotionRule", "HostSyncRule",
           "NumpyInJitRule", "PrngReuseRule", "RecompileRule",
           "SharedStateRule", "UnguardedFieldRule", "LockOrderRule",
           "BlockingUnderLockRule", "LockLeakRule"]
