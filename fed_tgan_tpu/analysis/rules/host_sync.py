"""J01 -- blocking device->host sync inside a hot loop.

Tracks taint from *jit producers* (``jax.jit`` results, the repo's
``_epoch_fn_for``/``_program`` caches, ``shard_map``/``pmap``) through
assignments, subscripts, arithmetic, and tuple unpacks.  A sink is any
per-iteration host materialisation of a tainted value -- ``.item()``,
``float()`` / ``int()``, any ``np.*`` call, or ``jax.tree.map`` with a
host-pulling mapper -- lexically inside a ``for``/``while``/comprehension,
or inside a function that is itself called from such a loop (one level of
intra-module interprocedural propagation, enough to catch helpers like
``FederatedTrainer._check_finite``).

The sanctioned fix idiom is *not* flagged: ``jax.device_get(tree)`` is an
explicit, batched transfer, and its result (plain numpy) launders the
taint, so post-transfer ``np.*`` massaging stays clean.  ``bool(flag)``
is likewise exempt: a single-scalar "decide on host" sync, usually
preceded by ``copy_to_host_async``, is the designed control-flow idiom.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from fed_tgan_tpu.analysis.rules.base import (
    JIT_PRODUCER_RE,
    NUMPY_PREFIXES,
    TREE_MAP_NAMES,
    dotted,
)

RULE_ID = "J01"
HINT = ("batch the per-iteration host pulls into one explicit "
        "jax.device_get(...) per iteration (or defer them past the loop); "
        "pair decide-on-host scalars with .copy_to_host_async()")

#: Calls whose result is host-side regardless of inputs (taint launder).
_LAUNDER_NAMES = {"float", "int", "bool", "str", "len", "repr",
                  "jax.device_get", "device_get"}


@dataclass
class _FnInfo:
    node: ast.AST
    params: list
    tainted_params: set = field(default_factory=set)
    hot: bool = False  # called (transitively) from inside a loop


def _local_fns(tree: ast.Module) -> dict:
    out: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            params = [a.arg for a in args.posonlyargs + args.args
                      if a.arg not in ("self", "cls")]
            out[node.name] = _FnInfo(node=node, params=params)
    return out


class _Scanner:
    """One pass over one function body (or the module toplevel)."""

    def __init__(self, info, fns, module_taint, jitted_names, collect):
        self.info = info
        self.fns = fns
        self.taint = set(module_taint) | set(info.tainted_params)
        self.jitted_names = jitted_names
        self.collect = collect
        self.findings: list = []
        self.callsites: list = []

    # -------------------------------------------------------- taint eval

    def _is_launder(self, d: str) -> bool:
        return (d in _LAUNDER_NAMES
                or d.startswith(NUMPY_PREFIXES)
                or d.endswith(".item")
                or d.endswith(".tolist"))

    def _tainted(self, e) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.taint
        if isinstance(e, ast.Attribute):
            return self._tainted(e.value)
        if isinstance(e, ast.Subscript):
            return self._tainted(e.value)
        if isinstance(e, ast.Starred):
            return self._tainted(e.value)
        if isinstance(e, ast.Call):
            d = dotted(e.func) or ""
            if self._is_launder(d):
                return False
            if JIT_PRODUCER_RE.search(d):
                return True
            name = d[5:] if d.startswith("self.") else d
            if name in self.jitted_names:
                return True
            if isinstance(e.func, ast.Attribute) and self._tainted(e.func.value):
                return True  # method on a tainted object (.items(), ...)
            if name in self.fns and any(self._tainted(a) for a in e.args):
                return True  # local helper fed tainted data
            return False
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self._tainted(x) for x in e.elts)
        if isinstance(e, ast.Dict):
            return any(self._tainted(v) for v in e.values) or \
                any(self._tainted(k) for k in e.keys if k is not None)
        if isinstance(e, ast.BinOp):
            return self._tainted(e.left) or self._tainted(e.right)
        if isinstance(e, ast.UnaryOp):
            return self._tainted(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self._tainted(v) for v in e.values)
        if isinstance(e, ast.Compare):
            return self._tainted(e.left) or \
                any(self._tainted(c) for c in e.comparators)
        if isinstance(e, ast.IfExp):
            return self._tainted(e.body) or self._tainted(e.orelse)
        if isinstance(e, ast.NamedExpr):
            t = self._tainted(e.value)
            if t:
                self._bind(e.target)
            return t
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in e.generators:
                if self._tainted(gen.iter):
                    self._bind(gen.target)
            return self._tainted(e.elt)
        if isinstance(e, ast.DictComp):
            for gen in e.generators:
                if self._tainted(gen.iter):
                    self._bind(gen.target)
            return self._tainted(e.key) or self._tainted(e.value)
        return False

    def _bind(self, target) -> None:
        if isinstance(target, ast.Name):
            self.taint.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt)
        elif isinstance(target, ast.Starred):
            self._bind(target.value)

    # ------------------------------------------------------------- sinks

    def _finding(self, node, message) -> None:
        if self.collect:
            self.findings.append((node.lineno, message))

    def _check_call(self, call, in_loop) -> None:
        self._register_callsite(call, in_loop)
        hot = in_loop or self.info.hot
        if not hot:
            return
        d = dotted(call.func) or ""
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not call.args and self._tainted(func.value):
            self._finding(call, ".item() on a jitted output syncs the "
                                "device every iteration")
            return
        if d in ("float", "int") and len(call.args) == 1 \
                and self._tainted(call.args[0]):
            self._finding(call, f"{d}() on a jitted output blocks on a "
                                "device sync every iteration")
            return
        if d.startswith(NUMPY_PREFIXES) and \
                any(self._tainted(a) for a in call.args):
            self._finding(call, f"{d}() pulls a jitted output to host "
                                "every iteration")
            return
        if d in TREE_MAP_NAMES and len(call.args) >= 2 and \
                any(self._tainted(a) for a in call.args[1:]):
            mapper = call.args[0]
            md = dotted(mapper) or ""
            if md.startswith(NUMPY_PREFIXES) or md in ("float", "int"):
                self._finding(call, f"{d}({md}, ...) pulls every tree "
                                    "leaf to host separately")
            elif isinstance(mapper, ast.Lambda):
                lam_params = {a.arg for a in mapper.args.args}
                added = lam_params - self.taint
                self.taint |= lam_params
                before = len(self.findings)
                self._scan_expr(mapper.body, True)
                self.taint -= added
                if self.collect and len(self.findings) > before:
                    # re-anchor lambda-body findings to the map call
                    self.findings[before:] = [
                        (call.lineno, "tree.map with a host-pulling "
                                      "mapper materialises every leaf "
                                      "separately")]

    def _register_callsite(self, call, in_loop) -> None:
        func = call.func
        name = None
        if isinstance(func, ast.Name) and func.id in self.fns:
            name = func.id
        elif isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id in ("self", "cls") \
                and func.attr in self.fns:
            name = func.attr
        if name is None:
            return
        pos = [self._tainted(a) for a in call.args
               if not isinstance(a, ast.Starred)]
        kw = {k.arg: self._tainted(k.value)
              for k in call.keywords if k.arg}
        self.callsites.append(
            (name, pos, kw, in_loop or self.info.hot))

    # ----------------------------------------------------- tree walking

    def _scan_expr(self, e, in_loop) -> None:
        if e is None or not isinstance(e, ast.AST):
            return
        if isinstance(e, (ast.ListComp, ast.SetComp,
                          ast.GeneratorExp, ast.DictComp)):
            for gen in e.generators:
                self._scan_expr(gen.iter, in_loop)
                if self._tainted(gen.iter):
                    self._bind(gen.target)
                for cond in gen.ifs:
                    self._scan_expr(cond, True)
            if isinstance(e, ast.DictComp):
                self._scan_expr(e.key, True)
                self._scan_expr(e.value, True)
            else:
                self._scan_expr(e.elt, True)
            return
        if isinstance(e, ast.Lambda):
            return  # only entered via the tree.map special case
        if isinstance(e, ast.Call):
            self._check_call(e, in_loop)
            self._scan_expr(e.func, in_loop)
            for a in e.args:
                self._scan_expr(a, in_loop)
            for k in e.keywords:
                self._scan_expr(k.value, in_loop)
            return
        for child in ast.iter_child_nodes(e):
            self._scan_expr(child, in_loop)

    def _scan_stmts(self, stmts, in_loop) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue  # nested defs are scanned as their own functions
            if isinstance(s, ast.Assign):
                self._scan_expr(s.value, in_loop)
                if isinstance(s.value, ast.Call):
                    d = dotted(s.value.func) or ""
                    if JIT_PRODUCER_RE.search(d):
                        for t in s.targets:
                            if isinstance(t, ast.Name):
                                self.jitted_names.add(t.id)
                if self._tainted(s.value):
                    for t in s.targets:
                        self._bind(t)
            elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
                if s.value is not None:
                    self._scan_expr(s.value, in_loop)
                    if self._tainted(s.value):
                        self._bind(s.target)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                self._scan_expr(s.iter, in_loop)
                if self._tainted(s.iter):
                    self._bind(s.target)
                self._scan_stmts(s.body, True)
                self._scan_stmts(s.orelse, True)
            elif isinstance(s, ast.While):
                self._scan_expr(s.test, True)
                self._scan_stmts(s.body, True)
                self._scan_stmts(s.orelse, in_loop)
            elif isinstance(s, ast.If):
                self._scan_expr(s.test, in_loop)
                self._scan_stmts(s.body, in_loop)
                self._scan_stmts(s.orelse, in_loop)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    self._scan_expr(item.context_expr, in_loop)
                    if item.optional_vars is not None and \
                            self._tainted(item.context_expr):
                        self._bind(item.optional_vars)
                self._scan_stmts(s.body, in_loop)
            elif isinstance(s, ast.Try):
                self._scan_stmts(s.body, in_loop)
                for h in s.handlers:
                    self._scan_stmts(h.body, in_loop)
                self._scan_stmts(s.orelse, in_loop)
                self._scan_stmts(s.finalbody, in_loop)
            else:
                for child in ast.iter_child_nodes(s):
                    self._scan_expr(child, in_loop)

    def run(self, body) -> None:
        self._scan_stmts(body, False)


class HostSyncRule:
    rule_id = RULE_ID
    title = "host sync in hot path"
    hint = HINT

    #: fixpoint sweeps: 1 seeds call sites, 2 propagates hot/taint one
    #: hop, 3 reaches helpers-of-helpers and collects findings.
    _PASSES = 3

    def check(self, mod) -> Iterator:
        tree = mod.tree
        fns = _local_fns(tree)
        module_info = _FnInfo(node=tree, params=[])
        jitted_names: set = set()
        all_findings: dict = {}

        for sweep in range(self._PASSES):
            collect = sweep == self._PASSES - 1
            module_taint: set = set()
            scanners = []

            mscan = _Scanner(module_info, fns, set(), jitted_names, collect)
            mscan.run(tree.body)
            module_taint = mscan.taint
            scanners.append(mscan)

            for info in fns.values():
                sc = _Scanner(info, fns, module_taint, jitted_names, collect)
                sc.run(info.node.body)
                scanners.append(sc)

            for sc in scanners:
                for name, pos, kw, hot in sc.callsites:
                    callee = fns[name]
                    if hot:
                        callee.hot = True
                    for i, tainted in enumerate(pos):
                        if tainted and i < len(callee.params):
                            callee.tainted_params.add(callee.params[i])
                    for k, tainted in kw.items():
                        if tainted and k in callee.params:
                            callee.tainted_params.add(k)
                if collect:
                    for line, message in sc.findings:
                        all_findings.setdefault(line, message)

        for line in sorted(all_findings):
            yield (self.rule_id, line, all_findings[line], self.hint)
