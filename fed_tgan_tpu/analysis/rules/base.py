"""Shared AST machinery for the jaxlint rules.

Everything here is plain-stdlib AST analysis: no JAX import, no tracing.
The helpers encode the few JAX-shaped facts the rules agree on:

* what a *jit producer* looks like (``jax.jit``, ``pmap``, ``shard_map``,
  the repo's ``*_epoch_fn`` / ``_program`` caches) so taint can seed from
  "this value came out of a compiled program";
* how to resolve which plain functions end up wrapped by ``jax.jit``
  (decorators, ``partial(jax.jit, ...)``, ``jax.jit(name)`` call sites,
  one hop through ``shard_map``) together with their static arguments.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterator, Optional

#: Callee spellings whose *result* (or whose call result) is a compiled
#: program output.  Matched against the dotted form of the callee, where
#: nested calls collapse to "()" -- e.g. ``self._epoch_fn_for(n)(x)``
#: has the dotted callee ``self._epoch_fn_for()``.
JIT_PRODUCER_RE = re.compile(
    r"(?:^|\.)(?:jit|pjit|pmap)\b|epoch_fn|shard_map|(?:^|\.)_program\b"
)

#: ``jax.tree.map``-style spellings (first arg callable, rest are trees).
TREE_MAP_NAMES = {
    "jax.tree.map",
    "jax.tree_util.tree_map",
    "tree.map",
    "tree_map",
    "tree_util.tree_map",
}

#: Numpy module aliases as used across the repo.
NUMPY_PREFIXES = ("np.", "numpy.", "onp.")


def dotted(node: ast.AST) -> Optional[str]:
    """Dotted-name form of a callee expression.

    ``jax.random.split`` -> "jax.random.split"; a call in the chain
    collapses to "()": ``self._epoch_fn_for(n)(x)`` resolves its outer
    callee to "self._epoch_fn_for()".  Unresolvable shapes yield None,
    except attribute access on a complex base which keeps the attribute
    name alone (enough for ``.item()`` detection on subscripted values).
    """
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        base = dotted(node.func)
        return f"{base}()" if base else None
    return None


def assigned_names(stmts) -> set:
    """Every name (re)bound anywhere inside ``stmts``: plain/aug/ann
    assignments, for-targets, with-as, walrus, tuple unpacks."""
    out: set = set()

    def bind(target):
        if isinstance(target, ast.Name):
            out.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                bind(elt)
        elif isinstance(target, ast.Starred):
            bind(target.value)

    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    bind(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                bind(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                bind(node.target)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                bind(node.optional_vars)
            elif isinstance(node, ast.NamedExpr):
                bind(node.target)
    return out


def names_in(node: ast.AST) -> set:
    """All ``Name`` identifiers appearing anywhere under ``node``."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def const_str_tuple(node) -> Optional[tuple]:
    """A constant str/int or tuple/list thereof, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (str, int)):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, (str, int))):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None


@dataclass
class JittedFn:
    """A function whose body runs under ``jax.jit`` (possibly through one
    ``shard_map`` hop), with its traced-vs-static parameter split."""

    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    dynamic_params: set = field(default_factory=set)
    #: True when static-argument kwargs could not be parsed -- rules
    #: should then skip the function rather than risk false positives.
    opaque_statics: bool = False


def _decorator_jit_statics(dec) -> Optional[tuple]:
    """(static_names, static_nums, opaque) if ``dec`` marks the function
    as jitted, else None."""
    d = dotted(dec) or ""
    if re.search(r"(?:^|\.)(?:jit|pjit)$", d):
        return (set(), set(), False)
    if isinstance(dec, ast.Call):
        fd = dotted(dec.func) or ""
        is_partial = re.search(r"(?:^|\.)partial$", fd) is not None
        inner = dotted(dec.args[0]) if (is_partial and dec.args) else None
        if (is_partial and inner
                and re.search(r"(?:^|\.)(?:jit|pjit)$", inner)) or \
                re.search(r"(?:^|\.)(?:jit|pjit)$", fd):
            names: set = set()
            nums: set = set()
            opaque = False
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    vals = const_str_tuple(kw.value)
                    if vals is None:
                        opaque = True
                    else:
                        names |= set(vals)
                elif kw.arg == "static_argnums":
                    vals = const_str_tuple(kw.value)
                    if vals is None:
                        opaque = True
                    else:
                        nums |= set(vals)
            return (names, nums, opaque)
    return None


def _fn_params(fn) -> list:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def _make_jitted(fn, static_names=(), static_nums=(), opaque=False) -> JittedFn:
    if isinstance(fn, ast.Lambda):
        params = [a.arg for a in fn.args.args]
    else:
        params = _fn_params(fn)
    positional = [p for p in params if p not in ("self", "cls")]
    statics = set(static_names)
    for i in static_nums:
        if isinstance(i, int) and 0 <= i < len(positional):
            statics.add(positional[i])
    dynamic = {p for p in positional if p not in statics}
    return JittedFn(node=fn, dynamic_params=dynamic, opaque_statics=opaque)


def jitted_functions(tree: ast.Module) -> list:
    """Functions in ``tree`` that end up wrapped by ``jax.jit``.

    Covers: ``@jax.jit`` / ``@partial(jax.jit, static_arg...)``
    decorators, ``jax.jit(fn)`` call sites on a local def, and one
    resolution hop through ``name = shard_map(fn, ...)`` /
    ``name = partial(fn, ...)`` before the ``jax.jit(name)`` call.
    """
    defs: dict = {}
    assigns: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            assigns[node.targets[0].id] = node.value

    out: list = []
    seen: set = set()

    def add(fn, names=(), nums=(), opaque=False):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(_make_jitted(fn, names, nums, opaque))

    for fn in defs.values():
        for dec in fn.decorator_list:
            statics = _decorator_jit_statics(dec)
            if statics is not None:
                add(fn, *statics)

    def resolve(name: str, depth: int = 0):
        if name in defs:
            return defs[name]
        if depth < 1 and name in assigns:
            call = assigns[name]
            d = dotted(call.func) or ""
            if re.search(r"shard_map|pmap|(?:^|\.)partial$", d) and call.args:
                inner = call.args[0]
                if isinstance(inner, ast.Name):
                    return resolve(inner.id, depth + 1)
                if isinstance(inner, ast.Lambda):
                    return inner
        return None

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func) or ""
        if not re.search(r"(?:^|\.)(?:jit|pjit)$", d) or not node.args:
            continue
        target = node.args[0]
        names: set = set()
        nums: set = set()
        opaque = False
        for kw in node.keywords:
            if kw.arg == "static_argnames":
                vals = const_str_tuple(kw.value)
                names |= set(vals) if vals is not None else set()
                opaque = opaque or vals is None
            elif kw.arg == "static_argnums":
                vals = const_str_tuple(kw.value)
                nums |= set(vals) if vals is not None else set()
                opaque = opaque or vals is None
        if isinstance(target, ast.Lambda):
            add(target, names, nums, opaque)
        elif isinstance(target, ast.Name):
            fn = resolve(target.id)
            if fn is not None:
                add(fn, names, nums, opaque)
    return out
