"""J03 -- recompile hazards around ``jax.jit``.

Three shapes:

* ``jax.jit(...)`` called inside a ``for``/``while`` body -- a fresh
  compiled program (and cache entry) per iteration; hoist or cache it.
* A Python ``if``/``while`` on a traced (non-static) parameter inside a
  jitted function -- either a retrace per value or a concretisation
  error; use ``lax.cond`` / ``jnp.where`` or mark the argument static.
  ``x is None`` checks and ``isinstance`` tests are exempt (they are
  resolved at trace time against structure, not values).
* A dict/list/set *literal* passed positionally to a known-jitted
  callable -- container structure is part of the cache key, so ad-hoc
  literals retrace on every new shape.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from fed_tgan_tpu.analysis.rules.base import dotted, jitted_functions

RULE_ID = "J03"
HINT = ("hoist jit() out of loops and cache by static config; branch on "
        "traced values with lax.cond/jnp.where or mark the arg static "
        "(static_argnames)")

_JIT_CALL_RE = re.compile(r"(?:^|\.)(?:jit|pjit)$")


def _scan_jit_in_loop(tree):
    """(line, message) for jit() calls lexically inside loop bodies."""
    out = []

    def visit(node, in_loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                visit(child, False)
            return
        if isinstance(node, ast.Call) and in_loop:
            d = dotted(node.func) or ""
            if _JIT_CALL_RE.search(d):
                out.append((node.lineno, "jit() inside a loop compiles a "
                                         "fresh program every iteration"))
        loop = in_loop or isinstance(node, (ast.For, ast.AsyncFor,
                                            ast.While))
        for child in ast.iter_child_nodes(node):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)) and \
                    child in (getattr(node, "iter", None),
                              getattr(node, "test", None)):
                visit(child, in_loop)
            else:
                visit(child, loop)

    for stmt in tree.body:
        visit(stmt, False)
    return out


def _none_checked(test) -> set:
    """Names only compared against None / isinstance-checked in ``test``."""
    exempt = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops) and \
                all(isinstance(c, ast.Constant) and c.value is None
                    for c in node.comparators):
            exempt |= {n.id for n in ast.walk(node.left)
                       if isinstance(n, ast.Name)}
        elif isinstance(node, ast.Call):
            d = dotted(node.func) or ""
            if d in ("isinstance", "hasattr", "len", "callable"):
                for a in node.args:
                    exempt |= {n.id for n in ast.walk(a)
                               if isinstance(n, ast.Name)}
    return exempt


def _traced_branches(tree):
    out = []
    for jf in jitted_functions(tree):
        if jf.opaque_statics:
            continue
        body = jf.node.body
        stmts = body if isinstance(body, list) else []
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                elif isinstance(node, ast.IfExp):
                    test = node.test
                else:
                    continue
                names = {n.id for n in ast.walk(test)
                         if isinstance(n, ast.Name)}
                hot = (names & jf.dynamic_params) - _none_checked(test)
                if hot:
                    out.append(
                        (node.lineno,
                         f"Python branch on traced argument(s) "
                         f"{sorted(hot)} retraces per value (or fails "
                         "to trace)"))
    return out


def _literal_args_to_jitted(tree):
    """Calls of names bound to jax.jit(...) with container literals."""
    jitted_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            d = dotted(node.value.func) or ""
            if _JIT_CALL_RE.search(d):
                jitted_names.add(node.targets[0].id)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Name)
                and node.func.id in jitted_names):
            continue
        for a in node.args:
            if isinstance(a, (ast.Dict, ast.List, ast.Set)):
                out.append(
                    (a.lineno,
                     "container literal passed to a jitted function "
                     "retraces on every new structure; pass arrays or "
                     "mark the argument static"))
    return out


class RecompileRule:
    rule_id = RULE_ID
    title = "recompile hazard"
    hint = HINT

    def check(self, mod) -> Iterator:
        findings: dict = {}
        for line, message in _scan_jit_in_loop(mod.tree):
            findings.setdefault(line, message)
        for line, message in _traced_branches(mod.tree):
            findings.setdefault(line, message)
        for line, message in _literal_args_to_jitted(mod.tree):
            findings.setdefault(line, message)
        for line in sorted(findings):
            yield (self.rule_id, line, findings[line], self.hint)
