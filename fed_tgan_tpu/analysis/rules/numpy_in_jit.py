"""J04 -- host ``numpy`` applied to traced values inside a jitted function.

``np.*`` executes eagerly at trace time: fed a tracer it either crashes
(`TracerArrayConversionError`) or silently bakes a stale constant into
the compiled program.  The rule resolves jit-wrapped functions (through
one ``shard_map`` hop, so fused epoch bodies are covered), taints their
non-static parameters plus anything assigned from them, and flags any
``np.`` / ``numpy.`` call whose arguments touch tainted names.

``np.*`` on constants (lookup tables, shape tuples) is trace-time
constant folding and stays clean.
"""

from __future__ import annotations

import ast
from typing import Iterator

from fed_tgan_tpu.analysis.rules.base import (
    NUMPY_PREFIXES,
    dotted,
    jitted_functions,
    names_in,
)

RULE_ID = "J04"
HINT = ("inside jit, use jax.numpy (jnp) on traced values; reserve np.* "
        "for trace-time constants")


class NumpyInJitRule:
    rule_id = RULE_ID
    title = "numpy inside jit"
    hint = HINT

    def check(self, mod) -> Iterator:
        findings: dict = {}
        for jf in jitted_functions(mod.tree):
            body = jf.node.body
            stmts = body if isinstance(body, list) else []
            tainted = set(jf.dynamic_params)
            for _ in range(2):  # propagate through simple assignments
                for stmt in stmts:
                    for node in ast.walk(stmt):
                        if isinstance(node, ast.Assign) and \
                                names_in(node.value) & tainted:
                            for t in node.targets:
                                tainted |= {n.id for n in ast.walk(t)
                                            if isinstance(n, ast.Name)}
                        elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                                names_in(node.iter) & tainted:
                            tainted |= {n.id for n in ast.walk(node.target)
                                        if isinstance(n, ast.Name)}
            for stmt in stmts:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    d = dotted(node.func) or ""
                    if not d.startswith(NUMPY_PREFIXES):
                        continue
                    touched = set()
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        touched |= names_in(a)
                    if touched & tainted:
                        findings.setdefault(
                            node.lineno,
                            f"{d}() runs on host at trace time; on a "
                            "traced value it crashes or bakes in a stale "
                            "constant")
        for line in sorted(findings):
            yield (self.rule_id, line, findings[line], self.hint)
